#!/usr/bin/env python
"""Benchmark: the framework's throughput numbers on this chip.

Three measurements, merged into ONE printed JSON line:

1. **micro** — learner update throughput on the compute-critical loop
   (SURVEY.md §3.3) exactly as the flagship TPU config (CONFIGS row 8) runs
   it in production: replay resident in device HBM
   (memory/device_replay.py), uniform sampling fused into the train step,
   ``steps_per_dispatch`` update steps scanned inside one dispatched XLA
   program — the full DQN training step (Nature-CNN forward+backward, Adam,
   target update) at the reference's default batch 128 on 84x84x4 uint8
   states (reference utils/options.py:135, shared_memory.py:19-24).
   Measured at TWO fusion factors — the production K=32 and the peak
   K=256 (headline) — with a two-point fit of the per-dispatch overhead
   and the chip-bound asymptote, per-window p50/p90 so dispatch noise
   through a tunnelled chip is visible in the artifact, an XLA-derived
   flops/update and the achieved FLOP/s (with an MFU estimate when the
   chip's peak is known).

2. **families** — one on-chip updates/s + FLOPs row for EVERY other
   shipped model family's learner program (dqn-mlp, ddpg-mlp, drqn-mlp,
   drqn-cnn, dtqn-mlp, dtqn-moe, dtqn-pipe) at its drive-validated
   geometry, under the ``families`` key — each measured PRODUCTION-SHAPED:
   the family's train step fused over an HBM ring (uniform transition ring
   for the flat families, the prioritized segment ring for the sequence
   families) at ``steps_per_dispatch`` = 8, so the figures are K-amortised
   program rates, not one-unamortised-dispatch tunnel latency (round-3
   advisor finding; bench_families docstring).

3. **sampler** — Pallas hierarchical sampler vs the flat XLA
   cumsum+searchsorted draw on the production 50k-row PER priority
   vector (TPU only): a compile/perf regression in the Pallas path
   (memory/device_per.py's production draw on unsharded TPU rings) shows
   up here instead of only inside a north-star run.

4. **act A/B** — batch-16 actor forward on the host CPU vs on the
   accelerator (full-stack upload AND frame-packed upload variants):
   the measurement behind the "rollout inference is pinned to the host"
   design decision (agents/actor.py), re-taken on whatever hardware runs
   this bench so the decision is data, not folklore.

5. **actor_pipeline** — the ISSUE-4 actor hot loop, serial vs
   software-pipelined, on the production 16-env Nature-CNN shape:
   per-phase tick breakdown, frames/s for both schedules, the env-only
   ceiling, and ``overlap_efficiency`` (hidden device time / total
   device time — how much of the serial ``act`` cost the pipeline
   hides under host work).

6. **device_env** — the ISSUE-7 on-device env fleet: env frames/s of
   the host Python ``VectorEnv`` vs the native C++ stepper vs the
   pure-JAX device env (one scan advancing N envs per dispatch) at
   N in {64, 256, 1024}, plus the fused rollout engine
   (env+policy+n-step+replay-ring in ONE donated program) with the
   engine-cost (linear) and production (CNN) policies, and the
   ``speedup_vs_host`` headline the ROADMAP open item 1 tracks.

7. **e2e** — the BASELINE.md north-star accounting: env frames/sec with
   live actors + learner.  Runs the real config-8 topology (process
   backend, native batched pong stepper, HBM replay, replay-ratio
   pacing, and the ISSUE-4 actor plane: pipelined actors, or the
   SEED-style batched-inference backend when an accelerator hosts the
   learner — ``e2e_actor_backend`` records which) for a short
   wall-clock window and reads ``actor/total_nframes`` /
   ``learner/counter`` off the run's scalars — the same accounting as
   reference core/single_processes/dqn_logger.py:42.  Frames are agent
   steps (x4 emulated frames each, reference atari_env.py:95).
   ``e2e_actor_tick_ms`` carries the actors' phase medians (sync =
   blocked on the in-flight forward, dispatch = issue cost, param_swap
   = weight-refresh stall) and ``e2e_overlap_efficiency`` the fraction
   of per-tick device/server wait hidden under host work.

The merged line carries ``bench_schema`` (round-3 advisor finding: the
headline key's meaning changed once — K=256 peak -> K=32 production —
without a version marker; longitudinal consumers should key on the
schema).  Schema 2 = production-K headline + fused families rows +
sampler/act-A/B sections.

``vs_baseline`` compares micro updates/s against 250 updates/s — a
representative figure for this exact workload (batch-128 Nature-DQN Adam
step) on the single consumer CUDA GPU class the reference targets.  The
reference publishes no throughput numbers (BASELINE.md "published
frames/sec: none"), so this basis is self-declared; the ``*_basis`` field
says so explicitly.

Two rider sections measure the in-graph/host guards' cost on the fused
flagship program: ``health_overhead`` (the ISSUE-5 in-jit finite guard)
and ``perf_overhead`` (the ISSUE-6 live PerfMonitor doing its production
accounting) — both must stay <2% of median step time.

``--smoke`` is a separate seconds-scale CPU-safe mode (the dqn-mlp fused
program only) whose one-line JSON feeds ``tools/bench_gate.py --against
BENCH_SMOKE_BASELINE.json`` and ``BENCH_HISTORY.jsonl`` — the perf
regression gate CI runs (TESTING.md "Bench regression gate").

Usage: ``python bench.py [--mode micro|families|e2e|both] [--smoke]``
(default both = all three).
"""

from __future__ import annotations

import argparse
import dataclasses
import functools
import json
import os
import sys
import tempfile
import time

import numpy as np

BASELINE_UPDATES_PER_SEC = 250.0

# micro-bench geometry: batch per update / update steps per dispatched
# XLA program.  Two fusion factors are measured: K=32 is the production
# flagship value (the learner's TPU auto setting — kept small so publish/
# checkpoint cadences stay fine-grained and actor weight staleness stays
# bounded), K=256 is the peak-capability point (91% of the fitted
# dispatch-overhead asymptote on the tunnelled chip; sweep 2026-07-31:
# K=32/64/128/256 -> 2285/2999/3430/3751 updates/s).  The headline
# ``updates_per_sec`` is the PRODUCTION K=32 figure — what the learner
# actually runs — and the K=256 capability is published separately as
# ``updates_per_sec_peak`` (round-2 advisor finding: downstream consumers
# of the one-line JSON read the headline as production throughput).
MICRO_BATCH = 128
MICRO_DISPATCH = 32
MICRO_DISPATCH_PEAK = 256

# Peak FLOP/s table + the XLA cost-analysis FLOPs extraction now live in
# utils/perf.py (the live perf plane shares them with this bench and
# tools/mfu_probe.py — previously three inline copies).
from pytorch_distributed_tpu.utils.perf import (  # noqa: E402
    PEAK_FLOPS, flops_of_compiled, peak_flops_of as _peak_flops,
)


def bench_micro() -> dict:
    """Learner updates/s on the fused HBM-replay hot loop, at the
    production fusion factor (K=32) and the peak one (K=256), plus the
    two-point dispatch-overhead fit."""
    import jax

    from pytorch_distributed_tpu.memory.device_replay import (
        DeviceReplay, build_uniform_fused_step, round_capacity,
    )
    from pytorch_distributed_tpu.models import DqnCnnModel
    from pytorch_distributed_tpu.ops.losses import (
        build_dqn_train_step, init_train_state, make_optimizer,
    )
    from pytorch_distributed_tpu.parallel.mesh import make_mesh
    from pytorch_distributed_tpu.utils.experience import Transition

    B = MICRO_BATCH
    # NCHW rows, like production (factory.device_ring_channels_last is
    # False from measurement: the NHWC-resident variant A/B'd ~13% slower
    # on the v5 lite — TPU tiling pads the 4-wide channel minor dim)
    model = DqnCnnModel(action_space=6, norm_val=255.0)
    obs = np.zeros((1, 4, 84, 84), dtype=np.uint8)
    params = model.init(jax.random.PRNGKey(0), obs)
    tx = make_optimizer(lr=1e-4)
    state = init_train_state(params, tx)
    step = build_dqn_train_step(model.apply, tx, target_model_update=250)

    # multi-chip: ring rows shard over the mesh dp axis, train state
    # replicates, and XLA inserts the gradient all-reduce over ICI
    n_dev = len(jax.devices())
    mesh = make_mesh() if n_dev > 1 else None
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P

        state = jax.device_put(state, NamedSharding(mesh, P()))

    # HBM ring filled once — the learner hot loop samples on device and
    # never re-transfers host pages (ingest runs between dispatches in
    # production, off this loop's critical path).  2048 rows keep the
    # fill's H2D cost down (the tunnel moves ~1 MB/chunk-row-pair) while
    # sampling exactly like the production 50k buffer
    ring = DeviceReplay(capacity=round_capacity(2048, mesh),
                        state_shape=(4, 84, 84),
                        state_dtype=np.uint8, mesh=mesh)
    rng = np.random.default_rng(0)
    C = 512
    for _ in range(ring.capacity // C):
        ring.feed_chunk(Transition(
            state0=rng.integers(0, 255, size=(C, 4, 84, 84)).astype(
                np.uint8),
            action=rng.integers(0, 6, size=C).astype(np.int32),
            reward=rng.normal(size=C).astype(np.float32),
            gamma_n=np.full(C, 0.99 ** 5, dtype=np.float32),
            state1=rng.integers(0, 255, size=(C, 4, 84, 84)).astype(
                np.uint8),
            terminal1=(rng.random(C) < 0.1).astype(np.float32)))

    key = jax.random.PRNGKey(0)
    flops_per_update = None

    def drain(m):
        # Ground truth: through this image's tunnelled backend,
        # block_until_ready can resolve on remote ENQUEUE rather than
        # completion, which silently turns window timings into dispatch-
        # rate mirages (block-timed reads were 3-9x the fetch-bounded
        # truth).  A value fetch cannot lie — every window ends with a
        # scalar device_get off the last step's metrics, which the data
        # dependency chains behind the whole window's updates.
        return float(jax.device_get(m["learner/critic_loss"]))

    def measure(K: int):
        """Fetch-bounded update rates at fusion factor K (median of
        independent windows: tunnel latency is noisy, and one long
        window would let a single stall skew the figure)."""
        nonlocal key, state, flops_per_update
        fused = build_uniform_fused_step(step, B, steps_per_call=K)

        def keymat():
            nonlocal key
            key, sub = jax.random.split(key)
            return jax.random.split(sub, K)

        # Compile explicitly so the flops of THIS executable can be read
        # off its cost analysis (exact for the HLO, no hand model).
        # XLA's cost analysis counts a scan/while body ONCE (verified:
        # identical flops for K=1/8/64), so the figure is per-update.
        compiled = fused.lower(state, ring.state, keymat()).compile()
        if flops_per_update is None:
            flops_per_update = flops_of_compiled(compiled)

        # warmup: enough dispatches to settle the link (a tunnelled dev
        # chip's first dispatches pay connection setup)
        for _ in range(10):
            state, metrics = compiled(state, ring.state, keymat())
        drain(metrics)

        # Key splits are pre-dispatched OUTSIDE the window (the
        # production learner amortizes one split per 64 dispatches,
        # agents/learner.py key_buf) so the timed loop issues exactly
        # the production program stream.
        # constant updates-per-window across K so the end-of-window drain
        # fetch is amortized identically (short windows would tax high-K
        # rates with a full fetch RTT per ~0.3s of work)
        windows, iters = 8, max(7680 // K, 1)
        rates, enq_rates = [], []
        for _ in range(windows):
            keysets = [keymat() for _ in range(iters)]
            jax.block_until_ready(keysets[-1])
            t0 = time.perf_counter()
            for ks in keysets:
                state, metrics = compiled(state, ring.state, ks)
            t_enq = time.perf_counter() - t0
            drain(metrics)
            rates.append(iters * K / (time.perf_counter() - t0))
            enq_rates.append(iters * K / t_enq)
        return rates, enq_rates

    rates32, enq32 = measure(MICRO_DISPATCH)
    rates_pk, _ = measure(MICRO_DISPATCH_PEAK)

    k32 = float(np.median(rates32))
    peak_rate = float(np.median(rates_pk))
    out = {
        # headline: the PRODUCTION fusion factor (the learner's TPU auto
        # K=32) — what config 8 actually dispatches
        "updates_per_sec": round(k32, 2),
        "updates_per_sec_min": round(float(np.min(rates32)), 2),
        "updates_per_sec_p90": round(float(np.percentile(rates32, 90)),
                                     2),
        "updates_per_sec_windows": [round(r, 1) for r in rates32],
        "steps_per_dispatch": MICRO_DISPATCH,
        # peak-fusion capability point (K=256, ~91% of the fitted
        # dispatch-overhead asymptote)
        "updates_per_sec_peak": round(peak_rate, 2),
        "updates_per_sec_peak_p90": round(float(np.percentile(rates_pk,
                                                              90)), 2),
        "steps_per_dispatch_peak": MICRO_DISPATCH_PEAK,
        # how fast dispatches ENQUEUE (the pre-fix figure): the gap to
        # the fetch-bounded rates is the tunnel's async-dispatch illusion
        "updates_per_sec_enqueue": round(float(np.median(enq32)), 2),
        "batch_size": B,
    }
    # two-point fit of rate(K) = K / (K * t_update + t_dispatch): how
    # much of the gap to the chip-bound asymptote each K leaves
    k_a, k_b = MICRO_DISPATCH, MICRO_DISPATCH_PEAK
    t_a, t_b = k_a / k32, k_b / peak_rate
    t_update = (t_b - t_a) / (k_b - k_a)
    t_dispatch = t_a - k_a * t_update
    if t_update > 0 and t_dispatch > 0:
        # both positive or the fit is tunnel noise (e.g. a stall during
        # the K=32 windows) — omit rather than publish nonsense
        out["dispatch_overhead_ms"] = round(1e3 * t_dispatch, 3)
        out["chip_bound_updates_per_sec"] = round(1.0 / t_update, 1)
    if flops_per_update:
        achieved = k32 * flops_per_update
        achieved_pk = peak_rate * flops_per_update
        out["flops_per_update"] = round(flops_per_update)
        out["achieved_flops_per_sec"] = round(achieved)
        peak = _peak_flops(jax.devices()[0])
        out["mfu"] = round(achieved / peak, 4) if peak else None
        out["mfu_peak"] = round(achieved_pk / peak, 4) if peak else None
        # What binds the MFU: preferably the MACHINE-READABLE attribution
        # from the latest ``tools/mfu_probe.py --json --out
        # MFU_PROBE.json`` run on this class of hardware (re-tiling
        # share + per-category self-time bins off a real XLA trace);
        # falls back to the checked-in r03 finding when no probe
        # artifact exists (CPU CI hosts can't trace a TPU).
        out["mfu_bound"] = _mfu_bound_note()
    return out


def _mfu_bound_note() -> str:
    """Compose the micro section's ``mfu_bound`` string from the
    ``attribution`` block of an ``MFU_PROBE.json`` artifact at the repo
    root (written by ``tools/mfu_probe.py --json --out MFU_PROBE.json``)
    when one exists — the bench quotes the probe's measured numbers
    instead of a hand-copied string that can drift."""
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "MFU_PROBE.json")
    try:
        with open(path) as f:
            probe = json.load(f)
        att = probe["attribution"]
        bins = att.get("bins", {})
        top = sorted(bins.items(), key=lambda kv: -kv[1])[:3]
        bins_s = ", ".join(f"{k} {v:.0%}" for k, v in top)
        # measured attribution ONLY — no qualitative diagnosis spliced
        # in (a probe taken after the Pallas torso / wide family lands
        # may show no lane underfill at all; the conclusion belongs to
        # whoever reads the bins, not to a string frozen at r03)
        return (f"re-tiling share {att['retiling_share']:.0%} of device "
                f"self time; top self-time bins: {bins_s} "
                f"(mfu_probe.py on {probe.get('device_kind', '?')})")
    except (OSError, KeyError, ValueError, TypeError):
        # the r03 trace finding (2026-07-31, v5 lite): batch- and
        # dtype-invariant, channels-last A/B'd slower — the structural
        # lane underfill plus XLA's own re-tiling
        return ("narrow conv channels (4/32/64) underfill the 128-lane "
                "MXU; batch- and dtype-invariant, channels-last A/B'd "
                "slower; ~25% of device time is XLA's own re-tiling "
                "(mfu_probe.py)")


FAMILY_DISPATCH = 8  # steps per dispatched program in the family rows


def bench_families() -> dict:
    """On-chip updates/s + FLOPs for EVERY shipped model family's learner
    program (SURVEY §3.3 applied per family) — not just the flagship CNN.

    Each row builds the exact train step the factory gives the learner for
    that CONFIGS row and measures it PRODUCTION-SHAPED: fused over an HBM
    ring at ``FAMILY_DISPATCH`` update steps per dispatched XLA program —
    the uniform transition ring (memory/device_replay.py) for the flat
    families, the prioritized segment ring (memory/device_sequence.py,
    sampling + priority write-back fused in) for the sequence/transformer
    families.  Round 3 published one-update-per-dispatch figures here,
    which on a tunnelled chip measured dispatch latency, not the model
    (round-3 advisor/verdict finding); every row now carries its
    ``steps_per_dispatch``.  The same ``drain()``-style fetch bound guards
    against the tunnel's async-dispatch mirage.  The flagship dqn-cnn
    fused row stays in bench_micro.
    """
    import jax
    import jax.numpy as jnp

    from pytorch_distributed_tpu.config import build_options
    from pytorch_distributed_tpu.factory import (
        build_model, build_train_state_and_step, init_params, lstm_dim_of,
        probe_env, sequence_pack_frames,
    )
    from pytorch_distributed_tpu.memory.device_replay import (
        DeviceReplay, build_uniform_fused_step,
    )
    from pytorch_distributed_tpu.memory.device_sequence import (
        DeviceSequenceReplay, SegmentChunk,
    )
    from pytorch_distributed_tpu.utils.experience import Transition

    rng = np.random.default_rng(0)
    K = FAMILY_DISPATCH

    def fill_flat_ring(spec, capacity=1024):
        S = spec.state_shape
        img = len(S) == 3
        ring = DeviceReplay(
            capacity, S, spec.action_shape,
            state_dtype=np.uint8 if img else np.float32,
            action_dtype=spec.action_dtype)
        C = 256
        obs = ((lambda n: rng.integers(0, 255, (n, *S)).astype(np.uint8))
               if img else
               (lambda n: rng.normal(size=(n, *S)).astype(np.float32)))
        act = ((lambda n: rng.integers(0, spec.num_actions, n).astype(
                    np.int32)) if spec.discrete else
               (lambda n: rng.uniform(-1, 1, (n, spec.action_dim)).astype(
                    np.float32)))
        for _ in range(capacity // C):
            ring.feed_chunk(Transition(
                state0=obs(C), action=act(C),
                reward=rng.normal(size=C).astype(np.float32),
                gamma_n=np.full(C, 0.99 ** 5, np.float32),
                state1=obs(C),
                terminal1=(rng.random(C) < 0.1).astype(np.float32)))
        return ring

    def fill_seq_ring(opt, spec, capacity=256):
        L = opt.agent_params.seq_len
        S = spec.state_shape
        pack = sequence_pack_frames(opt)
        img = len(S) == 3
        dt = np.uint8 if img else np.float32
        ring = DeviceSequenceReplay(
            capacity, L, S, lstm_dim_of(opt), state_dtype=dt,
            priority_exponent=opt.memory_params.priority_exponent,
            importance_weight=opt.memory_params.priority_weight,
            pack_frames=pack)
        C = 64
        oshape = (L + pack, *S[1:]) if pack else (L + 1, *S)
        for _ in range(capacity // C):
            obs = (rng.integers(0, 255, (C, *oshape)).astype(np.uint8)
                   if img else
                   rng.normal(size=(C, *oshape)).astype(np.float32))
            ring.feed_chunk(SegmentChunk(
                obs=obs,
                action=rng.integers(0, max(spec.num_actions, 2),
                                    (C, L)).astype(np.int32),
                reward=rng.normal(size=(C, L)).astype(np.float32),
                terminal=np.zeros((C, L), np.float32),
                mask=np.ones((C, L), np.float32),
                c0=np.zeros((C, ring.lstm_dim), np.float32),
                h0=np.zeros((C, ring.lstm_dim), np.float32)))
        return ring

    # family -> (CONFIGS row, batch, option overrides); seq rows use the
    # drive-validated seq_len 16 geometry
    FAMILIES = [
        ("dqn-mlp", 1, 128, {}),
        ("ddpg-mlp", 2, 64, {}),
        ("drqn-mlp", 13, 32, dict(seq_len=16, burn_in=4)),
        ("drqn-cnn", 14, 32, dict(seq_len=16, burn_in=4)),
        ("dtqn-mlp", 15, 32, dict(seq_len=16)),
        ("dtqn-moe", 17, 32, dict(seq_len=16)),
        ("dtqn-pipe", 18, 32, dict(seq_len=16)),
    ]
    # ISSUE-13 megabatch leg for the dispatch-bound flat families: same
    # geometry, fused at megabatch M (K/M widened-gather groups per
    # dispatch) — the row's ``updates_per_sec_megabatch`` is the
    # campaign's gated capability figure, ``updates_per_sec`` stays the
    # sequential production default
    MEGABATCH_FAMILIES = {"dqn-mlp": 8, "ddpg-mlp": 8}

    peak = _peak_flops(jax.devices()[0])
    out = {}
    for name, cfg, B, over in FAMILIES:
        opt = build_options(cfg, batch_size=B, **over)
        spec = probe_env(opt)
        model = build_model(opt, spec)
        params = init_params(opt, spec, model, seed=0)
        state, step = build_train_state_and_step(opt, spec, model, params,
                                                 mesh=None)
        is_seq = opt.model_type.startswith(("drqn", "dtqn"))
        key = jax.random.PRNGKey(0)

        def keymat():
            nonlocal key
            key, sub = jax.random.split(key)
            return jax.random.split(sub, K)

        if is_seq:
            ring = fill_seq_ring(opt, spec)
            fused = ring.build_fused_step(step, B, steps_per_call=K)
            beta = jnp.asarray(0.6, jnp.float32)
            rs = ring.state
            compiled = fused.lower(state, rs, keymat(), beta).compile()

            def dispatch():
                nonlocal state, rs
                state, rs, metrics = compiled(state, rs, keymat(), beta)
                return metrics
        else:
            ring = fill_flat_ring(spec)
            fused = build_uniform_fused_step(step, B, steps_per_call=K)
            compiled = fused.lower(state, ring.state, keymat()).compile()

            def dispatch():
                nonlocal state
                state, metrics = compiled(state, ring.state, keymat())
                return metrics

        # scan bodies are counted once by cost_analysis (verified in
        # bench_micro across K=1/8/64), so this is per-update
        flops = flops_of_compiled(compiled)
        for _ in range(5):  # warmup + link settle
            metrics = dispatch()
        float(jax.device_get(metrics["learner/critic_loss"]))
        windows, iters, rates = 5, max(64 // K, 8), []
        for _ in range(windows):
            t0 = time.perf_counter()
            for _ in range(iters):
                metrics = dispatch()
            # fetch-bounded: the device_get chains behind the window
            float(jax.device_get(metrics["learner/critic_loss"]))
            rates.append(iters * K / (time.perf_counter() - t0))
        row = {
            "updates_per_sec": round(float(np.median(rates)), 2),
            "batch_size": B,
            "steps_per_dispatch": K,
            "megabatch": 1,
            "replay_fused": "device-sequence" if is_seq else "device",
        }
        if is_seq:
            row["seq_len"] = opt.agent_params.seq_len
        if flops:
            row["flops_per_update"] = round(flops)
            if peak:
                row["mfu"] = round(
                    float(np.median(rates)) * flops / peak, 4)
        M = MEGABATCH_FAMILIES.get(name, 0)
        if M > 1:
            from pytorch_distributed_tpu.factory import (
                build_megabatch_train_step,
            )
            from pytorch_distributed_tpu.memory.device_replay import (
                build_uniform_fused_step as _fuse,
            )

            # fresh params: the sequential leg's donating dispatches
            # consumed the original state's buffers, so re-init rather
            # than alias them
            mparams = init_params(opt, spec, model, seed=0)
            mstate, _ = build_train_state_and_step(opt, spec, model,
                                                   mparams, mesh=None)
            mega = build_megabatch_train_step(opt, model)
            mfused = _fuse(step, B, steps_per_call=K, megabatch=M,
                           megabatch_step=mega)
            mcompiled = mfused.lower(mstate, ring.state,
                                     keymat()).compile()
            for _ in range(5):
                mstate, mmetrics = mcompiled(mstate, ring.state,
                                             keymat())
            float(jax.device_get(mmetrics["learner/critic_loss"]))
            mrates = []
            for _ in range(windows):
                t0 = time.perf_counter()
                for _ in range(iters):
                    mstate, mmetrics = mcompiled(mstate, ring.state,
                                                 keymat())
                float(jax.device_get(mmetrics["learner/critic_loss"]))
                mrates.append(iters * K / (time.perf_counter() - t0))
            row["updates_per_sec_megabatch"] = round(
                float(np.median(mrates)), 2)
            row["megabatch_k"] = M
            row["megabatch_speedup"] = round(
                row["updates_per_sec_megabatch"]
                / max(row["updates_per_sec"], 1e-9), 3)
        out[name] = row
        print(f"[bench_families] {name}: {row}", file=sys.stderr,
              flush=True)
    return {"families": out}


def bench_sampler() -> dict:
    """Pallas hierarchical sampler vs flat XLA cumsum+searchsorted on the
    production PER geometry (50k-row priority vector, 128 draws) — the
    regression canary for memory/device_per.py's production draw path.
    TPU only: the Pallas kernel targets the TPU vector unit; on CPU the
    XLA scheme IS the production path and there is nothing to compare.

    Both schemes scan 32 draw batches inside one dispatched program so
    the figure compares kernel cost, not dispatch RTT; windows end with a
    value fetch (the async-dispatch guard bench_micro documents)."""
    import jax
    import jax.numpy as jnp

    if jax.devices()[0].platform != "tpu":
        return {}
    from pytorch_distributed_tpu.ops.pallas_sampling import (
        hierarchical_sample,
    )

    N, B, SCAN = 50048, 128, 32
    rng = np.random.default_rng(0)
    p = jnp.asarray(rng.gamma(1.0, 1.0, N).astype(np.float32))

    def xla_draw(prio, key):
        cdf = jnp.cumsum(prio)
        u = jax.random.uniform(key, (B,)) * cdf[-1]
        return jnp.clip(jnp.searchsorted(cdf, u, side="right"),
                        0, N - 1).astype(jnp.int32)

    def pallas_draw(prio, key):
        idx, _probs = hierarchical_sample(prio, key, B)
        return idx

    def scanned(draw):
        def many(prio, keys):
            def body(acc, k):
                return acc + jnp.sum(draw(prio, k)), None
            acc, _ = jax.lax.scan(body, jnp.int32(0), keys)
            return acc
        return jax.jit(many)

    out = {}
    key = jax.random.PRNGKey(0)
    for label, draw in (("xla", xla_draw), ("pallas", pallas_draw)):
        try:
            fn = scanned(draw)
            keys = jax.random.split(key, SCAN)
            int(jax.device_get(fn(p, keys)))  # compile + warm
            rates = []
            for _ in range(5):
                key, sub = jax.random.split(key)
                keys = jax.random.split(sub, SCAN)
                t0 = time.perf_counter()
                int(jax.device_get(fn(p, keys)))  # fetch-bounded
                rates.append(SCAN / (time.perf_counter() - t0))
            out[f"{label}_draws_per_sec"] = round(float(np.median(rates)),
                                                  1)
        except Exception as e:  # noqa: BLE001 - publish the failure
            out[f"{label}_error"] = str(e)[:200]
    out.update(n_rows=N, batch_size=B)
    return {"sampler": out}


def bench_act_ab() -> dict:
    """Host-CPU vs on-device batched actor forward (VERDICT round-3 #3).

    The production actor pins rollout inference to the host CPU
    (agents/actor.py, utils/helpers.pin_to_cpu) — a decision made when the
    only accelerator sat behind a ~50 MB/s network tunnel.  This measures
    all three candidate paths at the production vector width (16 envs,
    Nature-CNN flagship) so the pin is justified by numbers on WHATEVER
    hardware runs the bench:

    - ``act_ms_host``: jitted CPU forward on host-pinned params — the
      production path (reference analogue: the actor's own CUDA replica,
      reference dqn_actor.py:84-85).
    - ``act_ms_device``: obs batch up (full 4-stack, uint8), forward on
      the accelerator, actions down.
    - ``act_ms_device_packed``: only the NEWEST frame ships (16x84x84);
      a device-resident rolling stack rebuilds the 4-stack on chip
      (donated buffer) — the frame-packed upload variant.
    """
    import jax
    import jax.numpy as jnp

    from pytorch_distributed_tpu.models import DqnCnnModel
    from pytorch_distributed_tpu.models.policies import (
        build_epsilon_greedy_act,
    )
    from pytorch_distributed_tpu.utils.helpers import pin_to_cpu

    NV = 16  # production env-vector width
    model = DqnCnnModel(action_space=6, norm_val=255.0)
    params = model.init(jax.random.PRNGKey(0),
                        np.zeros((1, 4, 84, 84), np.uint8))
    act = build_epsilon_greedy_act(model.apply)
    rng = np.random.default_rng(0)
    frames = rng.integers(0, 255, (64, NV, 84, 84)).astype(np.uint8)
    obs_host = np.repeat(frames[0][:, None], 4, axis=1)  # (NV, 4, 84, 84)
    eps = np.full(NV, 0.1, np.float32)

    def timed(tick, n=40, warm=5):
        for _ in range(warm):
            tick(0)
        t0 = time.perf_counter()
        for i in range(n):
            tick(i)
        return round(1e3 * (time.perf_counter() - t0) / n, 3)

    out = {}
    # --- host path (production): CPU-committed params, numpy obs --------
    cparams = pin_to_cpu(params)
    ckey = pin_to_cpu(jax.random.PRNGKey(1))
    ceps = pin_to_cpu(jnp.asarray(eps))

    def host_tick(i):
        a, _q, _m = act(cparams, obs_host, ckey, ceps)
        np.asarray(a)  # actions down (actors consume numpy)
    out["act_ms_host"] = timed(host_tick)

    dev = jax.devices()[0]
    if dev.platform != "cpu":
        dparams = jax.device_put(params, dev)
        dkey = jax.device_put(jax.random.PRNGKey(1), dev)
        deps = jax.device_put(jnp.asarray(eps), dev)

        # --- full-stack upload: obs up per tick, actions down -----------
        def dev_tick(i):
            o = jax.device_put(obs_host, dev)
            a, _q, _m = act(dparams, o, dkey, deps)
            np.asarray(a)
        out["act_ms_device"] = timed(dev_tick)

        # --- frame-packed upload: newest frame up, stack rolls on chip --
        @functools.partial(jax.jit, donate_argnums=(1,))
        def packed_act(p, stack, new, key, e):
            stack = jnp.concatenate([stack[:, 1:], new[:, None]], axis=1)
            a, q, m = act(p, stack, key, e)
            return a, stack
        stack_box = [jax.device_put(jnp.asarray(obs_host), dev)]

        def packed_tick(i):
            new = jax.device_put(frames[i % len(frames)], dev)
            a, stack_box[0] = packed_act(dparams, stack_box[0], new, dkey,
                                         deps)
            np.asarray(a)
        out["act_ms_device_packed"] = timed(packed_tick)
        out["act_device_kind"] = getattr(dev, "device_kind", "?")
    return {"act_ab": out} if out else {}


def bench_health_overhead(windows: int = 6,
                          updates_per_window: int = 512) -> dict:
    """Health-sentinel guard cost (ISSUE 5 acceptance): the SAME fused
    flagship learner program (batch-128 Nature-CNN over an HBM ring,
    K=32 scanned updates per dispatch) measured with the in-jit finite
    guard ON (production default: loss/grad/TD checked in-graph, state
    select per leaf) vs OFF.  The guard must stay in-graph — no host
    syncs on the hot path — so the acceptance bar is
    ``health_overhead_frac`` < 0.02 of median step time.  Both variants
    use the fetch-bounded window timing bench_micro documents (the
    tunnel's async-dispatch mirage would hide the overhead too)."""
    import jax

    from pytorch_distributed_tpu.memory.device_replay import (
        DeviceReplay, build_uniform_fused_step, round_capacity,
    )
    from pytorch_distributed_tpu.models import DqnCnnModel
    from pytorch_distributed_tpu.ops.losses import (
        build_dqn_train_step, init_train_state, make_optimizer,
    )
    from pytorch_distributed_tpu.utils.experience import Transition

    B, K = MICRO_BATCH, MICRO_DISPATCH
    model = DqnCnnModel(action_space=6, norm_val=255.0)
    obs = np.zeros((1, 4, 84, 84), dtype=np.uint8)
    params = model.init(jax.random.PRNGKey(0), obs)
    tx = make_optimizer(lr=1e-4)

    ring = DeviceReplay(capacity=round_capacity(2048, None),
                        state_shape=(4, 84, 84), state_dtype=np.uint8)
    rng = np.random.default_rng(0)
    C = 512
    for _ in range(ring.capacity // C):
        ring.feed_chunk(Transition(
            state0=rng.integers(0, 255, (C, 4, 84, 84)).astype(np.uint8),
            action=rng.integers(0, 6, C).astype(np.int32),
            reward=rng.normal(size=C).astype(np.float32),
            gamma_n=np.full(C, 0.99 ** 5, dtype=np.float32),
            state1=rng.integers(0, 255, (C, 4, 84, 84)).astype(np.uint8),
            terminal1=(rng.random(C) < 0.1).astype(np.float32)))

    key = jax.random.PRNGKey(0)

    def measure(guard: bool) -> float:
        nonlocal key
        step = build_dqn_train_step(model.apply, tx,
                                    target_model_update=250, guard=guard)
        fused = build_uniform_fused_step(step, B, steps_per_call=K,
                                         donate=False)
        state = init_train_state(params, tx)

        def keymat():
            nonlocal key
            key, sub = jax.random.split(key)
            return jax.random.split(sub, K)

        compiled = fused.lower(state, ring.state, keymat()).compile()
        for _ in range(5):
            state, metrics = compiled(state, ring.state, keymat())
        float(jax.device_get(metrics["learner/critic_loss"]))
        iters, rates = max(updates_per_window // K, 2), []
        for _ in range(windows):
            keysets = [keymat() for _ in range(iters)]
            jax.block_until_ready(keysets[-1])
            t0 = time.perf_counter()
            for ks in keysets:
                state, metrics = compiled(state, ring.state, ks)
            float(jax.device_get(metrics["learner/critic_loss"]))
            rates.append(iters * K / (time.perf_counter() - t0))
        return float(np.median(rates))

    unguarded = measure(False)
    guarded = measure(True)
    frac = (unguarded - guarded) / unguarded if unguarded > 0 else None
    out = {
        "updates_per_sec_guarded": round(guarded, 2),
        "updates_per_sec_unguarded": round(unguarded, 2),
        # clamped at 0: window noise routinely makes the guarded run
        # measure FASTER on a noisy host; negative overhead is noise
        "health_overhead_frac": (round(max(frac, 0.0), 4)
                                 if frac is not None else None),
        "steps_per_dispatch": K,
        "batch_size": B,
    }
    print(f"[bench_health_overhead] {out}", file=sys.stderr, flush=True)
    return {"health_overhead": out}


def _mlp_fused_program(B: int, K: int, megabatch: int = 1):
    """The dqn-mlp learner program fused over a small uniform ring —
    the CPU-safe geometry shared by ``bench_smoke`` and the smoke
    variant of ``bench_perf_overhead`` (the flagship CNN takes minutes
    to compile on a CPU host; the MLP takes seconds).  Returns
    ``(fused, state, ring)``.  ``megabatch`` M > 1 builds the ISSUE-13
    megabatched variant (K/M widened-gather groups per dispatch)."""
    from pytorch_distributed_tpu.config import build_options
    from pytorch_distributed_tpu.factory import (
        build_model, build_train_state_and_step, init_params, probe_env,
    )
    from pytorch_distributed_tpu.memory.device_replay import (
        DeviceReplay, build_uniform_fused_step,
    )
    from pytorch_distributed_tpu.utils.experience import Transition

    opt = build_options(1, batch_size=B)  # dqn-mlp on the fake chain env
    spec = probe_env(opt)
    model = build_model(opt, spec)
    params = init_params(opt, spec, model, seed=0)
    state, step = build_train_state_and_step(opt, spec, model, params,
                                             mesh=None)
    rng = np.random.default_rng(0)
    ring = DeviceReplay(256, spec.state_shape, spec.action_shape,
                        state_dtype=np.float32,
                        action_dtype=spec.action_dtype)
    C = 64
    for c in range(ring.capacity // C):
        # rows carry provenance (two fake actors, version 1) so the
        # provenance-overhead bench's telemetry leg computes on REAL
        # stamps, not an all-sentinel fast path
        prov = np.stack([np.array([j % 2, j % 8, 1, c * C + j],
                                  np.int32) for j in range(C)])
        ring.feed_chunk(Transition(
            state0=rng.normal(size=(C, *spec.state_shape)).astype(
                np.float32),
            action=rng.integers(0, spec.num_actions, C).astype(np.int32),
            reward=rng.normal(size=C).astype(np.float32),
            gamma_n=np.full(C, 0.99 ** 5, np.float32),
            state1=rng.normal(size=(C, *spec.state_shape)).astype(
                np.float32),
            terminal1=(rng.random(C) < 0.1).astype(np.float32),
            prov=prov))
    mb_kw = {}
    if megabatch > 1:
        from pytorch_distributed_tpu.factory import (
            build_megabatch_train_step,
        )

        mb_kw = dict(megabatch=megabatch,
                     megabatch_step=build_megabatch_train_step(opt, model))
    fused = build_uniform_fused_step(step, B, steps_per_call=K,
                                     donate=False, **mb_kw)
    return fused, state, ring


def bench_perf_overhead(windows: int = 6,
                        updates_per_window: int = 512,
                        smoke: bool = False) -> dict:
    """Perf-plane monitor cost (ISSUE 6 acceptance): the SAME fused
    flagship learner program as bench_micro (batch-128 Nature-CNN over
    an HBM ring, K=32 scanned updates per dispatch) measured with a live
    ``utils/perf.PerfMonitor`` doing its production accounting — one
    ``note_updates`` per dispatch plus a ``drain()`` + JSONL flush per
    window, exactly the learner's stats-cadence wiring — vs bare.  The
    monitor's hot-path surface is one integer add, so the acceptance
    bar is ``perf_overhead_frac`` < 0.02 of median step time.  Both
    variants use the fetch-bounded window timing bench_micro documents.

    ``smoke=True`` swaps in the CPU-safe dqn-mlp geometry (shared with
    ``bench_smoke``) so the measurement logic itself is CI-exercisable —
    the flagship CNN program takes minutes to compile on a CPU host."""
    import jax

    from pytorch_distributed_tpu.config import PerfParams
    from pytorch_distributed_tpu.utils import perf
    from pytorch_distributed_tpu.utils.metrics import MetricsWriter

    if smoke:
        B, K = 32, 8
        fused, state0, ring = _mlp_fused_program(B, K)
    else:
        from pytorch_distributed_tpu.memory.device_replay import (
            DeviceReplay, build_uniform_fused_step, round_capacity,
        )
        from pytorch_distributed_tpu.models import DqnCnnModel
        from pytorch_distributed_tpu.ops.losses import (
            build_dqn_train_step, init_train_state, make_optimizer,
        )
        from pytorch_distributed_tpu.utils.experience import Transition

        B, K = MICRO_BATCH, MICRO_DISPATCH
        model = DqnCnnModel(action_space=6, norm_val=255.0)
        params = model.init(jax.random.PRNGKey(0),
                            np.zeros((1, 4, 84, 84), dtype=np.uint8))
        tx = make_optimizer(lr=1e-4)
        ring = DeviceReplay(capacity=round_capacity(2048, None),
                            state_shape=(4, 84, 84), state_dtype=np.uint8)
        rng = np.random.default_rng(0)
        C = 512
        for _ in range(ring.capacity // C):
            ring.feed_chunk(Transition(
                state0=rng.integers(0, 255, (C, 4, 84, 84)).astype(
                    np.uint8),
                action=rng.integers(0, 6, C).astype(np.int32),
                reward=rng.normal(size=C).astype(np.float32),
                gamma_n=np.full(C, 0.99 ** 5, dtype=np.float32),
                state1=rng.integers(0, 255, (C, 4, 84, 84)).astype(
                    np.uint8),
                terminal1=(rng.random(C) < 0.1).astype(np.float32)))
        step = build_dqn_train_step(model.apply, tx,
                                    target_model_update=250)
        fused = build_uniform_fused_step(step, B, steps_per_call=K,
                                         donate=False)
        state0 = init_train_state(params, tx)

    key = jax.random.PRNGKey(0)

    def keymat():
        nonlocal key
        key, sub = jax.random.split(key)
        return jax.random.split(sub, K)

    # ONE compile shared by both variants (donate=False keeps state0
    # reusable): the measurement is of the monitor, not the compiler
    compiled = fused.lower(state0, ring.state, keymat()).compile()
    flops = flops_of_compiled(compiled)

    def measure(monitored: bool) -> float:
        state = state0
        monitor, writer, mstep = None, None, 0
        if monitored:
            monitor = perf.PerfMonitor(
                "bench", PerfParams(enabled=True), prefix="learner")
            # immune to ambient TPU_APEX_PERF=0 (resolve() lets env
            # override the explicit params): a disabled monitor would
            # measure bare-vs-bare and report a vacuous 0% overhead
            monitor.enabled = True
            monitor.flops_per_update = flops
            monitor.register_jit("fused_step",
                                 getattr(fused, "_cache_size", None))
            writer = MetricsWriter(
                tempfile.mkdtemp(prefix="bench_perf_"),
                enable_tensorboard=False, role="learner")
            monitor.drain()  # anchor
        for _ in range(5):
            state, metrics = compiled(state, ring.state, keymat())
        float(jax.device_get(metrics["learner/critic_loss"]))
        iters, rates = max(updates_per_window // K, 2), []
        for _ in range(windows):
            keysets = [keymat() for _ in range(iters)]
            jax.block_until_ready(keysets[-1])
            t0 = time.perf_counter()
            for ks in keysets:
                state, metrics = compiled(state, ring.state, ks)
                if monitored:
                    monitor.note_updates(K)
            if monitored:
                mstep += iters * K
                writer.scalars(monitor.drain(step=mstep), step=mstep)
            float(jax.device_get(metrics["learner/critic_loss"]))
            rates.append(iters * K / (time.perf_counter() - t0))
        if writer is not None:
            writer.close()
        return float(np.median(rates))

    bare = measure(False)
    monitored = measure(True)
    frac = (bare - monitored) / bare if bare > 0 else None
    out = {
        "updates_per_sec_monitored": round(monitored, 2),
        "updates_per_sec_bare": round(bare, 2),
        # clamped at 0: window noise routinely makes the monitored run
        # measure FASTER on a noisy host; negative overhead is noise
        "perf_overhead_frac": (round(max(frac, 0.0), 4)
                               if frac is not None else None),
        "steps_per_dispatch": K,
        "batch_size": B,
        "geometry": "smoke-mlp" if smoke else "flagship-cnn",
    }
    print(f"[bench_perf_overhead] {out}", file=sys.stderr, flush=True)
    return {"perf_overhead": out}


def bench_provenance_overhead(windows: int = 5,
                              smoke: bool = False) -> dict:
    """Provenance-column cost on the fused hot paths (ISSUE 8
    acceptance): the data-plane X-ray must be <2% on both fused
    programs, enforced by the bench gate's absolute overhead band.

    Two legs, each instrumented-vs-bare on the SAME compiled jit:

    - **rollout** — the fused device rollout (emit="replay", linear
      policy: engine cost, not CNN FLOPs) dispatched WITH a provenance
      stamp (the (3,) int32 arg scattered as 4 extra int32 columns per
      emitted row) vs WITHOUT (columns written as the -1 sentinel —
      the write itself is schema-resident either way, so this measures
      the stamp's broadcast + the real column traffic).
    - **learner** — the fused learner step loop with the learner's
      stats-cadence telemetry running (one 256-row provenance gather
      D2H + the staleness/age/share numpy math + histogram rows per
      window, exactly agents/learner.py's wiring) vs bare.

    ``smoke=True`` shrinks N/windows to seconds-scale for CI; the
    measurement logic is identical.  Overhead fracs are clamped at 0 —
    negative overhead is window noise on a small host."""
    import jax
    import jax.numpy as jnp

    from pytorch_distributed_tpu.config import build_options
    from pytorch_distributed_tpu.envs.device_env import build_device_env
    from pytorch_distributed_tpu.memory.device_replay import (
        DeviceReplay, provenance_sample,
    )
    from pytorch_distributed_tpu.models.policies import (
        build_fused_rollout, init_rollout_carry,
    )
    from pytorch_distributed_tpu.utils import health as health_mod
    from pytorch_distributed_tpu.utils.metrics import MetricsWriter

    N, K = (32, 8) if smoke else (256, 8)
    opt = build_options(4, visualize=False)
    env = build_device_env(opt.env_params, 0, N)
    apply_fn, params = _device_env_linear_policy(env.state_shape)
    roll = build_fused_rollout(apply_fn, env, nstep=5, gamma=0.99,
                               rollout_ticks=K, emit="replay")
    eps = jnp.full((N,), 0.1, jnp.float32)
    key = jnp.asarray(jax.random.PRNGKey(0))
    prov3 = jnp.asarray(np.array([0, 1, 0], np.int32))

    def rollout_rate(with_prov: bool) -> float:
        import gc

        gc.collect()
        # fresh ring per leg: the rollout DONATES the ring state, so a
        # leg must never reuse the other leg's consumed buffers
        ring = DeviceReplay(capacity=max(2 * K * N, 2048),
                            state_shape=env.state_shape,
                            state_dtype=np.uint8)
        box = [init_rollout_carry(env, 5), ring.state, jnp.int32(0)]

        def tick():
            carry, rs, tick0 = box
            if with_prov:
                carry, rs, stats = roll(params, carry, rs, key, tick0,
                                        eps, prov3)
            else:
                carry, rs, stats = roll(params, carry, rs, key, tick0,
                                        eps)
            int(jax.device_get(stats.fed))  # fetch-bounded
            box[:] = [carry, rs, tick0 + K]

        tick()  # warm/compile
        ticks = max(1, (512 if smoke else 2048) // (K * N))
        rates = []
        for _ in range(windows):
            t0 = time.perf_counter()
            for _ in range(ticks):
                tick()
            rates.append(N * K * ticks / (time.perf_counter() - t0))
        return float(np.median(rates))

    roll_bare = rollout_rate(False)
    roll_prov = rollout_rate(True)
    roll_frac = ((roll_bare - roll_prov) / roll_bare
                 if roll_bare > 0 else None)

    # ---- learner leg: fused step loop ± the stats-cadence telemetry ----
    B, LK = (32, 8)
    fused, state0, lring = _mlp_fused_program(B, LK)
    lkey = jax.random.PRNGKey(0)

    def keymat():
        nonlocal lkey
        lkey, sub = jax.random.split(lkey)
        return jax.random.split(sub, LK)

    compiled = fused.lower(state0, lring.state, keymat()).compile()
    prov_jit = jax.jit(provenance_sample, static_argnames="n")
    tel_key = jax.random.PRNGKey(7)

    def learner_rate(instrumented: bool) -> float:
        state = state0
        writer = None
        if instrumented:
            writer = MetricsWriter(
                tempfile.mkdtemp(prefix="bench_prov_"),
                enable_tensorboard=False, role="learner")
        for _ in range(5):
            state, metrics = compiled(state, lring.state, keymat())
        float(jax.device_get(metrics["learner/critic_loss"]))
        iters = max((128 if smoke else 512) // LK, 2)
        rates, mstep = [], 0
        for _ in range(windows):
            keysets = [keymat() for _ in range(iters)]
            jax.block_until_ready(keysets[-1])
            t0 = time.perf_counter()
            for ks in keysets:
                state, metrics = compiled(state, lring.state, ks)
            if instrumented:
                mstep += iters * LK
                pr, _fill = prov_jit(
                    lring.state, jax.random.fold_in(tel_key, mstep),
                    n=256)
                # the EXACT production computation (agents/learner.py
                # calls the same helper) — the bench must not drift
                # from what the learner actually pays per cadence
                ds = health_mod.provenance_stats(np.asarray(pr), 1,
                                                 mstep)
                if ds is not None:
                    writer.histogram("learner/staleness",
                                     ds["staleness"].tolist(),
                                     step=mstep)
                    writer.histogram("learner/sample_age",
                                     ds["age"].tolist(), step=mstep)
                    writer.histogram("replay/actor_share",
                                     ds["shares"].tolist(), step=mstep)
            float(jax.device_get(metrics["learner/critic_loss"]))
            rates.append(iters * LK / (time.perf_counter() - t0))
        if writer is not None:
            writer.close()
        return float(np.median(rates))

    learn_bare = learner_rate(False)
    learn_instr = learner_rate(True)
    learn_frac = ((learn_bare - learn_instr) / learn_bare
                  if learn_bare > 0 else None)
    fracs = [f for f in (roll_frac, learn_frac) if f is not None]
    out = {
        "rollout_frames_per_sec_bare": round(roll_bare, 1),
        "rollout_frames_per_sec_prov": round(roll_prov, 1),
        "rollout_overhead_frac": (round(max(roll_frac, 0.0), 4)
                                  if roll_frac is not None else None),
        "learner_updates_per_sec_bare": round(learn_bare, 2),
        "learner_updates_per_sec_instr": round(learn_instr, 2),
        "learner_overhead_frac": (round(max(learn_frac, 0.0), 4)
                                  if learn_frac is not None else None),
        # the gate's single number: worst of the two fused paths
        "provenance_overhead_frac": (round(max(max(fracs), 0.0), 4)
                                     if fracs else None),
        "rollout_envs": N,
        "geometry": "smoke" if smoke else "full",
    }
    print(f"[bench_provenance_overhead] {out}", file=sys.stderr,
          flush=True)
    return {"provenance_overhead": out}


def bench_metrics_overhead(windows: int = 6,
                           updates_per_window: int = 512,
                           smoke: bool = False) -> dict:
    """Mission-control plane cost (ISSUE 10 acceptance): the fused
    dqn-mlp learner loop with its per-window stats rows (the bare
    stats cadence both legs pay) vs the same loop with the FULL
    telemetry path live — a MissionControl tailing + ingesting the run
    dir and evaluating an alert rule per window (the gateway-host leg),
    plus a MetricsPusher tailing the same stream and pushing the
    window's scalar deltas to a local gateway over T_METRICS (the
    fleet-host leg, including its wire round-trip and the gateway-side
    aggregator ingest).  Both legs land in ONE number because a real
    fleet host pays one or the other; paying both here is the
    conservative bound.  Everything runs on the stats cadence — the
    dispatch hot loop itself is untouched by the plane — so the
    acceptance bar is ``metrics_overhead_frac`` < 0.02 of median step
    time (the bench_gate absolute overhead band).

    ``smoke=True`` shrinks windows/iters to seconds-scale for CI; the
    measurement logic is identical."""
    import jax

    from pytorch_distributed_tpu.agents.clocks import (
        ActorStats, GlobalClock,
    )
    from pytorch_distributed_tpu.agents.param_store import ParamStore
    from pytorch_distributed_tpu.config import AlertParams, MetricsParams
    from pytorch_distributed_tpu.parallel.dcn import DcnGateway
    from pytorch_distributed_tpu.utils import telemetry
    from pytorch_distributed_tpu.utils.metrics import MetricsWriter

    B, K = 32, 8
    if smoke:
        # windows stay SECONDS-wide even in smoke: the plane's cost is
        # per-cadence, so a too-narrow window measures timer noise, not
        # the plane (a 128-update window is ~0.3 s on this class of
        # host — one 15 ms scheduler hiccup reads as 5% "overhead")
        windows = min(windows, 4)
        updates_per_window = min(updates_per_window, 384)
    fused, state0, ring = _mlp_fused_program(B, K)
    key = jax.random.PRNGKey(0)

    def keymat():
        nonlocal key
        key, sub = jax.random.split(key)
        return jax.random.split(sub, K)

    # ONE compile shared by both legs (donate=False keeps state0
    # reusable): the measurement is of the telemetry plane, not XLA
    compiled = fused.lower(state0, ring.state, keymat()).compile()

    log_dir = tempfile.mkdtemp(prefix="bench_metrics_")
    writer = MetricsWriter(log_dir, enable_tensorboard=False,
                           role="learner")
    # gateway-side aggregator behind a REAL gateway socket: the push
    # leg pays the wire, the decode, and the ingest
    sink = telemetry.MissionControl(
        None, MetricsParams(enabled=True), AlertParams(enabled=False))
    gw = DcnGateway(ParamStore(4), GlobalClock(), ActorStats(),
                    put_chunk=lambda items: None,
                    host="127.0.0.1", port=0,
                    metrics_sink=sink.ingest_remote)
    # local leg: tail + ingest + one quiet-threshold rule pass
    mission = telemetry.MissionControl(
        log_dir, MetricsParams(enabled=True),
        AlertParams(rules="slow: learner/updates_per_s < 1 for 60s"))
    pusher = telemetry.MetricsPusher(("127.0.0.1", gw.port), log_dir,
                                     MetricsParams(enabled=True))

    state = state0
    for _ in range(5):
        state, metrics = compiled(state, ring.state, keymat())
    float(jax.device_get(metrics["learner/critic_loss"]))
    pusher.push_once()  # offset handshake + pipe warmup, outside timing

    # INTERLEAVED windows (bare, instrumented, bare, ...): this host
    # class drifts ±10% between back-to-back runs (VM steal/freq
    # noise), which back-to-back legs read as fake overhead; pairing
    # windows makes each leg sample the same host weather.  The GATE
    # number is NOT the rate difference (a difference of two noisy
    # medians reads scheduler hiccups as multi-% "overhead" on a
    # loaded 2-vCPU host — observed flaking the tier-1 smoke gate):
    # the plane runs on a seconds-scale CADENCE, so its honest cost is
    # the DIRECTLY TIMED tail+ingest+alert-eval+push work as a
    # fraction of the wall span it amortizes over — one cadence every
    # other ~1 s window ≈ the production poll_s/push_s density.  The
    # A/B rates stay in the output as context.
    iters = max(updates_per_window // K, 2)
    rates = {False: [], True: []}
    plane_s = 0.0
    total_s = 0.0
    mstep = 0
    for w in range(windows * 2):
        instrumented = bool(w % 2)
        keysets = [keymat() for _ in range(iters)]
        jax.block_until_ready(keysets[-1])
        t0 = time.perf_counter()
        for ks in keysets:
            state, metrics = compiled(state, ring.state, ks)
        mstep += iters * K
        # the bare stats cadence BOTH legs pay: one scalar flush per
        # window (what agents/learner.py does)
        writer.scalars({"learner/updates_per_s": float(iters * K),
                        "learner/ingest_queue_util": 0.0}, step=mstep)
        if instrumented:
            tp = time.perf_counter()
            mission.poll()        # tail + ingest + alert eval
            pusher.push_once()    # T_METRICS push of the deltas
            plane_s += time.perf_counter() - tp
        float(jax.device_get(metrics["learner/critic_loss"]))
        dt = time.perf_counter() - t0
        total_s += dt
        rates[instrumented].append(iters * K / dt)
    writer.close()
    pushed_rows = pusher.pushed_rows
    mission.stop()
    gw.close()

    bare = float(np.median(rates[False]))
    instr = float(np.median(rates[True]))
    frac = plane_s / total_s if total_s > 0 else None
    out = {
        "updates_per_sec_bare": round(bare, 2),
        "updates_per_sec_metrics": round(instr, 2),
        # the gate number: cadence work / wall span it amortizes over
        "metrics_overhead_frac": (round(frac, 4)
                                  if frac is not None else None),
        "plane_ms_per_cadence": round(plane_s / max(windows, 1) * 1e3,
                                      2),
        "pushed_rows": int(pushed_rows),
        "steps_per_dispatch": K,
        "batch_size": B,
        "geometry": "smoke-mlp" if smoke else "mlp",
    }
    print(f"[bench_metrics_overhead] {out}", file=sys.stderr, flush=True)
    return {"metrics_overhead": out}


def bench_flow_overhead(chunks: int = 600, rows: int = 16,
                        smoke: bool = False) -> dict:
    """Flow-control plane cost on the ingest hot path (ISSUE 11
    acceptance): a real DcnClient→DcnGateway wire ingest loop with the
    plane at its production default (enabled, healthy — no credits on
    the wire) measures the per-chunk ingest span, and the plane's
    per-chunk adds — ``GatewayFlow.admit`` (time-gated governor
    refresh + token-bucket meter) plus the ``grant`` read riding the
    ack — are DIRECTLY timed in isolation.  The gate number
    ``flow_overhead_frac`` is flow-work-per-chunk over ingest-span-
    per-chunk, held under the 0.02 absolute band by bench_gate — the
    PR-10 lesson applies verbatim: a difference of two noisy wire
    throughputs on this loaded 2-vCPU host would read scheduler
    hiccups as multi-% fake overhead, so the rate difference is never
    the gate number.

    ``smoke=True`` shrinks the loop to sub-second for CI; the
    measurement logic is identical."""
    from pytorch_distributed_tpu.agents.clocks import (
        ActorStats, GlobalClock,
    )
    from pytorch_distributed_tpu.agents.param_store import ParamStore
    from pytorch_distributed_tpu.parallel.dcn import DcnClient, DcnGateway
    from pytorch_distributed_tpu.utils.experience import Transition

    flow_iters = 20_000
    if smoke:
        chunks = min(chunks, 250)
        flow_iters = 8_000
    z = np.zeros(4, dtype=np.float32)
    t = Transition(state0=z, action=np.int32(0), reward=np.float32(0.0),
                   gamma_n=np.float32(0.99), state1=z,
                   terminal1=np.float32(0.0))
    chunk = [(t, 1.0)] * rows
    store = ParamStore(4)
    store.publish(np.zeros(4, dtype=np.float32))
    gw = DcnGateway(store, GlobalClock(), ActorStats(),
                    put_chunk=lambda items: None, host="127.0.0.1",
                    port=0, pressure=lambda: 0.0)
    assert gw.flow is not None, "flow plane off at its production default"
    client = DcnClient(("127.0.0.1", gw.port), process_ind=0)
    for _ in range(30):  # session + validator + allocator warmup
        client.send_chunk(chunk)
    t0 = time.perf_counter()
    for _ in range(chunks):
        client.send_chunk(chunk)
    span = time.perf_counter() - t0
    # the plane's per-chunk work, timed directly: the serve loop pays
    # admit() per EXP frame and grant() inside every ack payload
    t0 = time.perf_counter()
    for _ in range(flow_iters):
        gw.flow.admit(0, rows)
        gw.flow.grant(0)
    flow_s = time.perf_counter() - t0
    client.close()
    gw.close()
    per_chunk = span / max(chunks, 1)
    per_flow = flow_s / max(flow_iters, 1)
    out = {
        "chunks_per_sec_ingest": round(chunks / span, 1),
        "chunk_ingest_us": round(per_chunk * 1e6, 2),
        "flow_us_per_chunk": round(per_flow * 1e6, 3),
        # the gate number: per-chunk flow work / per-chunk ingest span
        "flow_overhead_frac": round(per_flow / per_chunk, 4),
        "chunk_rows": rows,
        "geometry": "smoke-wire" if smoke else "wire",
    }
    print(f"[bench_flow_overhead] {out}", file=sys.stderr, flush=True)
    return {"flow_overhead": out}


def bench_replica_overhead(rounds: int = 200, grad_dim: int = 65536,
                           smoke: bool = False) -> dict:
    """Replica-plane cost on the learner hot path (ISSUE 15
    acceptance): a real ReplicaClient→gateway→ReplicaRegistry wire loop
    at N=1 (the solo-degenerate case every replicated learner passes
    through) measures the per-round exchange span at a production-ish
    gradient size (64k fp32 ≈ the dqn-mlp tree), and the plane's
    per-round adds — the generation-stamp validate + round bookkeeping
    (``submit`` fast path) and one lease ``renew`` (an upper bound:
    production renews every lease_s/3, not every round) — are DIRECTLY
    timed in isolation against the registry.  The gate number
    ``replica_overhead_frac`` is plane-work-per-round over
    exchange-span-per-round, held under the 0.02 absolute band by
    bench_gate — the PR-10 lesson applies verbatim: differencing two
    noisy round rates on this loaded host would read scheduler hiccups
    as fake overhead, so the rate difference is never the gate number.

    ``smoke=True`` shrinks the loop to sub-second for CI; the
    measurement logic is identical."""
    from pytorch_distributed_tpu.agents.clocks import (
        ActorStats, GlobalClock,
    )
    from pytorch_distributed_tpu.agents.param_store import ParamStore
    from pytorch_distributed_tpu.config import ReplicaParams
    from pytorch_distributed_tpu.parallel.dcn import (
        DcnGateway, LocalReplicaChannel, ReplicaClient, ReplicaRegistry,
    )

    plane_iters = 6_000
    if smoke:
        rounds = min(rounds, 80)
        plane_iters = 2_500
    registry = ReplicaRegistry(ReplicaParams(replicas=1, lease_s=30.0))
    store = ParamStore(4)
    store.publish(np.zeros(4, dtype=np.float32))
    gw = DcnGateway(store, GlobalClock(), ActorStats(),
                    put_chunk=lambda items: None, host="127.0.0.1",
                    port=0, replicas=registry)
    client = ReplicaClient(("127.0.0.1", gw.port), 0)
    client.acquire()
    grad = np.zeros(grad_dim, dtype=np.float32)
    for r in range(10):  # session + allocator warmup
        client.submit_round(r, grad)
    t0 = time.perf_counter()
    for r in range(10, 10 + rounds):
        client.submit_round(r, grad)
    span = time.perf_counter() - t0
    # the plane's own work, timed directly against a second registry:
    # the stamp/validate + completion bookkeeping of an N=1 submit
    # (tiny grad — the reduce over real bytes is already inside the
    # wire span above) and the renew path
    reg2 = ReplicaRegistry(ReplicaParams(replicas=1, lease_s=30.0))
    ch = LocalReplicaChannel(reg2, 0)
    ch.acquire()
    tiny = np.zeros(4, dtype=np.float32)
    t0 = time.perf_counter()
    for i in range(plane_iters):
        ch.submit_round(i, tiny)
    stamp_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(plane_iters):
        ch.renew()
    renew_s = time.perf_counter() - t0
    client.release()
    client.close()
    ch.release()
    ch.close()
    gw.close()
    per_round = span / max(rounds, 1)
    per_stamp = stamp_s / max(plane_iters, 1)
    per_renew = renew_s / max(plane_iters, 1)
    out = {
        "rounds_per_sec_wire": round(rounds / span, 1),
        "round_exchange_us": round(per_round * 1e6, 2),
        "stamp_us_per_round": round(per_stamp * 1e6, 3),
        "renew_us": round(per_renew * 1e6, 3),
        # the gate number: per-round plane work (stamp + one renew,
        # the conservative bound) / per-round exchange span
        "replica_overhead_frac": round(
            (per_stamp + per_renew) / per_round, 4),
        "grad_dim": grad_dim,
        "geometry": "smoke-wire" if smoke else "wire",
    }
    print(f"[bench_replica_overhead] {out}", file=sys.stderr, flush=True)
    return {"replica_overhead": out}


def bench_gateway_ha_overhead(chunks: int = 600, rows: int = 16,
                              smoke: bool = False) -> dict:
    """Gateway HA-plane cost on the ingest hot path (ISSUE 16
    acceptance): a real DcnClient→DcnGateway wire ingest loop with the
    HA plane ON (journaling its control state to a WAL) measures the
    per-chunk ingest span, and the plane's adds are DIRECTLY timed in
    isolation — the per-frame session gate (term check, rate-limited
    TERM re-read amortized in), one fsynced journal ``append`` (paid
    once per state window, never per chunk — charged at the measured
    append count), and one primary-side sync-stream serve (charged at
    the production sync_s cadence, standby or not).  The gate number
    ``gateway_ha_overhead_frac`` is HA-work-per-chunk over
    ingest-span-per-chunk, held under the 0.02 absolute band by
    bench_gate — the PR-10 lesson applies verbatim: differencing an
    HA-on wire rate against an HA-off one on this loaded host would
    read scheduler hiccups as multi-% fake overhead, so the rate
    difference is never the gate number.

    ``smoke=True`` shrinks the loop to sub-second for CI; the
    measurement logic is identical."""
    import shutil
    import tempfile

    from pytorch_distributed_tpu.agents.clocks import (
        ActorStats, GlobalClock,
    )
    from pytorch_distributed_tpu.agents.param_store import ParamStore
    from pytorch_distributed_tpu.config import GatewayParams
    from pytorch_distributed_tpu.parallel.dcn import (
        DcnClient, DcnGateway, GatewayJournal, T_EXP,
    )
    from pytorch_distributed_tpu.utils.experience import Transition

    gate_iters = 20_000
    append_iters = 120
    sync_iters = 4_000
    if smoke:
        chunks = min(chunks, 250)
        gate_iters = 8_000
        append_iters = 50
        sync_iters = 1_500
    gp = GatewayParams(enabled=True)  # production lease/sync defaults
    tmp = tempfile.mkdtemp(prefix="bench-gw-ha-")
    z = np.zeros(4, dtype=np.float32)
    t = Transition(state0=z, action=np.int32(0), reward=np.float32(0.0),
                   gamma_n=np.float32(0.99), state1=z,
                   terminal1=np.float32(0.0))
    chunk = [(t, 1.0)] * rows
    store = ParamStore(4)
    store.publish(np.zeros(4, dtype=np.float32))
    gw = DcnGateway(store, GlobalClock(), ActorStats(),
                    put_chunk=lambda items: None, host="127.0.0.1",
                    port=0, gateway_params=gp, log_dir=tmp)
    client = DcnClient(("127.0.0.1", gw.port), process_ind=0)
    for _ in range(30):  # session + validator + allocator warmup
        client.send_chunk(chunk)
    appends_before = gw.status_snapshot()["gateway"]["journal_appends"]
    t0 = time.perf_counter()
    for _ in range(chunks):
        client.send_chunk(chunk)
    span = time.perf_counter() - t0
    appends_during = (gw.status_snapshot()["gateway"]["journal_appends"]
                      - appends_before)
    # the plane's own work, timed directly: the per-frame gate...
    t0 = time.perf_counter()
    for _ in range(gate_iters):
        gw._session_gate(T_EXP)
    gate_s = time.perf_counter() - t0
    # ...one fsynced state append against a second journal (same dir =
    # same storage medium; the wire span above amortizes the SAME cost
    # across every chunk in a state window)...
    j = GatewayJournal(os.path.join(tmp, "direct"))
    j.start_term(1)
    state = {"tick_seq": {"0": 999}, "clock": {"learner_step": 10 ** 6,
                                               "actor_step": 10 ** 7},
             "chunks_in": 10 ** 6, "lost": 0,
             "ledger": {"ingested": 10 ** 7, "shed": 0,
                        "quarantined": 0}}
    t0 = time.perf_counter()
    for _ in range(append_iters):
        j.append("state", state)
    append_s = time.perf_counter() - t0
    # ...and one primary-side sync serve (steady state: the standby's
    # incremental pull finds the tail it already has)
    t0 = time.perf_counter()
    for _ in range(sync_iters):
        base, recs = j.records_since(max(0, j.seq - 1))
        json.dumps({"term": 1, "seq": j.seq, "base_seq": base,
                    "records": recs})
    sync_s_total = time.perf_counter() - t0
    j.close()
    client.close()
    gw.close()
    shutil.rmtree(tmp, ignore_errors=True)
    per_chunk = span / max(chunks, 1)
    per_gate = gate_s / max(gate_iters, 1)
    per_append = append_s / max(append_iters, 1)
    per_sync = sync_s_total / max(sync_iters, 1)
    # HA work charged per chunk: every frame pays the gate; the
    # measured append count amortizes the fsync across the loop; the
    # sync stream is charged at its production cadence over the span
    ha_per_chunk = (per_gate
                    + per_append * appends_during / max(chunks, 1)
                    + per_sync * (span / max(gp.sync_s, 1e-3))
                    / max(chunks, 1))
    out = {
        "chunks_per_sec_ingest": round(chunks / span, 1),
        "chunk_ingest_us": round(per_chunk * 1e6, 2),
        "gate_us_per_chunk": round(per_gate * 1e6, 3),
        "journal_append_us": round(per_append * 1e6, 2),
        "journal_appends_during": appends_during,
        "sync_serve_us": round(per_sync * 1e6, 3),
        # the gate number: per-chunk HA work / per-chunk ingest span
        "gateway_ha_overhead_frac": round(ha_per_chunk / per_chunk, 4),
        "chunk_rows": rows,
        "geometry": "smoke-wire" if smoke else "wire",
    }
    print(f"[bench_gateway_ha_overhead] {out}", file=sys.stderr,
          flush=True)
    return {"gateway_ha_overhead": out}


def _shard_bench_plane(shards: int, capacity: int = 4096,
                       fill: int = 2048):
    """A warmed loopback shard plane: ``fill`` slot-routed rows over
    ``shards`` in-process shards (capacity split evenly), ready to
    sample."""
    from pytorch_distributed_tpu.config import ShardParams
    from pytorch_distributed_tpu.memory.shard_plane import (
        build_loopback_plane,
    )
    from pytorch_distributed_tpu.utils.experience import (
        Transition, make_prov,
    )

    plane, _, registry = build_loopback_plane(
        ShardParams(shards=shards, lease_s=120.0), capacity=capacity,
        state_shape=(4,))
    z = np.zeros(4, dtype=np.float32)
    for i in range(fill):
        t = Transition(state0=z, action=np.int32(0),
                       reward=np.float32(i % 7),
                       gamma_n=np.float32(0.99), state1=z,
                       terminal1=np.float32(0.0),
                       prov=make_prov(i % 8, 0, 0, i))
        plane.feed(t, float(1.0 + (i % 13)))
    return plane, registry


def bench_shard(samples: int = 400, batch: int = 64,
                smoke: bool = False) -> dict:
    """Sharded-replay sample latency vs shard count (ISSUE 20
    acceptance): the SAME global capacity and fill, sampled through the
    two-level tree at 1, 2, and 4 in-process (loopback) shards — the
    1-shard figure is the plane's degenerate case (bit-identical
    draws to a plain ``PrioritizedReplay``, the tier-1 parity oracle),
    so the 2/4-shard columns read as the pure cost of the stratified
    mass routing + per-shard local draws + the |TD| write-back merge.
    Loopback isolates plane arithmetic from socket noise; the wire
    path's per-verb cost is ISSUE-18's accountant's to report.

    ``smoke=True`` shrinks the loop to sub-second for CI; the
    measurement logic is identical."""
    if smoke:
        samples = min(samples, 120)
    out: dict = {"batch": batch, "samples": samples,
                 "geometry": "smoke-loopback" if smoke else "loopback"}
    reps = 5  # best-of-reps: scheduler hiccups inflate a mean, not a min
    chunk = max(1, samples // reps)
    for n in (1, 2, 4):
        plane, _ = _shard_bench_plane(n)
        rng = np.random.default_rng(0)
        for _ in range(10):  # tree/route warmup
            b = plane.sample(batch, rng)
            plane.update_priorities(b.index, np.abs(b.reward) + 0.5)
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            for _ in range(chunk):
                b = plane.sample(batch, rng)
                plane.update_priorities(b.index, np.abs(b.reward) + 0.5)
            best = min(best, time.perf_counter() - t0)
        out[f"sample_ms_{n}shard"] = round(best / chunk * 1e3, 4)
    print(f"[bench_shard] {out}", file=sys.stderr, flush=True)
    return {"shard": out}


def bench_shard_overhead(samples: int = 400, batch: int = 64,
                         smoke: bool = False) -> dict:
    """Shard-plane cost on the sample hot path (ISSUE 20 acceptance):
    the per-sample span at the production-shaped 4-shard loopback
    geometry, with the plane's own adds — one forced level-1
    mass-vector rebuild (the per-sample refresh at the exact-proportions
    default ``mass_refresh_s=0``) and one cold route rebuild (the
    every-feed epoch check's worst case) — DIRECTLY timed in isolation.
    The gate number ``shard_overhead_frac`` is plane-work-per-sample
    over sample-span, held under the 0.02 absolute band by bench_gate —
    the PR-10 lesson applies verbatim: differencing two noisy sample
    rates on a loaded host would read scheduler hiccups as fake
    overhead, so the rate difference is never the gate number."""
    plane_iters = 4_000
    if smoke:
        samples = min(samples, 120)
        plane_iters = 1_500
    plane, _ = _shard_bench_plane(4)
    rng = np.random.default_rng(0)
    for _ in range(10):
        b = plane.sample(batch, rng)
        plane.update_priorities(b.index, np.abs(b.reward) + 0.5)
    t0 = time.perf_counter()
    for _ in range(samples):
        b = plane.sample(batch, rng)
        plane.update_priorities(b.index, np.abs(b.reward) + 0.5)
    span = time.perf_counter() - t0
    # the plane's own work, timed directly: the mass rebuild every
    # sample pays (poll each live shard + rebuild the level-1 vector)
    # and the cold route rebuild a membership event would force
    t0 = time.perf_counter()
    for _ in range(plane_iters):
        plane._refresh_mass(force=True)
    mass_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(plane_iters):
        plane._route_epoch = -1
        plane._refresh_route()
    route_s = time.perf_counter() - t0
    per_sample = span / max(samples, 1)
    per_mass = mass_s / max(plane_iters, 1)
    per_route = route_s / max(plane_iters, 1)
    out = {
        "sample_ms": round(per_sample * 1e3, 4),
        "mass_refresh_us": round(per_mass * 1e6, 3),
        "route_rebuild_us": round(per_route * 1e6, 3),
        # the gate number: per-sample plane work (mass rebuild + cold
        # route rebuild, the conservative bound) / per-sample span
        "shard_overhead_frac": round(
            (per_mass + per_route) / per_sample, 4),
        "shards": 4,
        "geometry": "smoke-loopback" if smoke else "loopback",
    }
    print(f"[bench_shard_overhead] {out}", file=sys.stderr, flush=True)
    return {"shard_overhead": out}


def bench_wire(rows: int = 400, chunk_rows: int = 25,
               grad_dim: int = 65536, smoke: bool = False) -> dict:
    """Wire byte economics (ISSUE 18): the bandwidth X-ray's measured
    baseline for the ROADMAP-4 compression campaign.  Three numbers,
    all read off the LinkAccountant over REAL client→gateway wires:

    - ``legacy_bytes_per_transition`` — one transition per EXP frame
      (the pre-PR-4 upload shape: every tick ships its own savez
      envelope + 9-byte header);
    - ``bytes_per_transition`` — the production frame-packed shape
      (``actor_freq``-row chunks, envelope amortized across the chunk)
      — the headline every compression leg will be gated against;
    - ``replica_bytes_per_round`` — the ISSUE-15 replica exchange at
      N=1 with a production-ish 64k-fp32 gradient.

    Byte counts are deterministic (savez layout, fixed geometry), so
    the gate band is tight — a change here is a wire-format change,
    not noise."""
    from pytorch_distributed_tpu.agents.clocks import (
        ActorStats, GlobalClock,
    )
    from pytorch_distributed_tpu.agents.param_store import ParamStore
    from pytorch_distributed_tpu.config import ReplicaParams
    from pytorch_distributed_tpu.parallel.dcn import (
        DcnClient, DcnGateway, ReplicaClient, ReplicaRegistry,
    )
    from pytorch_distributed_tpu.utils import bandwidth
    from pytorch_distributed_tpu.utils.experience import Transition

    rounds = 6 if smoke else 20
    if smoke:
        rows = min(rows, 100)
    rows -= rows % chunk_rows  # same row count on both legs
    z = np.zeros(4, dtype=np.float32)
    t = Transition(state0=z, action=np.int32(0), reward=np.float32(0.0),
                   gamma_n=np.float32(0.99), state1=z,
                   terminal1=np.float32(0.0))

    def ingest_leg(per_frame: int) -> float:
        bandwidth.reset_for_tests()
        store = ParamStore(4)
        store.publish(np.zeros(4, dtype=np.float32))
        gw = DcnGateway(store, GlobalClock(), ActorStats(),
                        put_chunk=lambda items: None, host="127.0.0.1",
                        port=0, pressure=lambda: 0.0)
        client = DcnClient(("127.0.0.1", gw.port), process_ind=0)
        chunk = [(t, 1.0)] * per_frame
        for _ in range(rows // per_frame):
            client.send_chunk(chunk)
        acct = bandwidth.get_accountant()
        bpt = acct.bytes_per_transition()
        client.close()
        gw.close()
        return bpt

    legacy = ingest_leg(1)
    packed = ingest_leg(chunk_rows)

    # the replica exchange leg: N=1 rounds with a 64k-fp32 gradient
    bandwidth.reset_for_tests()
    registry = ReplicaRegistry(ReplicaParams(replicas=1, lease_s=30.0))
    store = ParamStore(4)
    store.publish(np.zeros(4, dtype=np.float32))
    gw = DcnGateway(store, GlobalClock(), ActorStats(),
                    put_chunk=lambda items: None, host="127.0.0.1",
                    port=0, replicas=registry)
    rclient = ReplicaClient(("127.0.0.1", gw.port), 0)
    rclient.acquire()
    grad = np.zeros(grad_dim, dtype=np.float32)
    acct = bandwidth.get_accountant()
    for r in range(2):  # session setup pays a one-off extra frame
        rclient.submit_round(r, grad)
    base_b = sum(acct.totals(link="gateway", verb=v)[0]
                 for v in ("rlease", "rgrad", "rprio"))
    base_rounds = acct.rounds
    for r in range(2, 2 + rounds):
        rclient.submit_round(r, grad)
    meas_b = sum(acct.totals(link="gateway", verb=v)[0]
                 for v in ("rlease", "rgrad", "rprio"))
    bpr = (meas_b - base_b) / max(acct.rounds - base_rounds, 1)
    rclient.release()
    rclient.close()
    gw.close()
    bandwidth.reset_for_tests()

    out = {
        # the headline: the production frame-packed upload shape
        "bytes_per_transition": round(packed, 1),
        "legacy_bytes_per_transition": round(legacy, 1),
        "packing_ratio": round(legacy / packed, 2) if packed else None,
        "replica_bytes_per_round": round(bpr, 1),
        "chunk_rows": chunk_rows,
        "rows": rows,
        "grad_dim": grad_dim,
        "geometry": "smoke-wire" if smoke else "wire",
    }
    print(f"[bench_wire] {out}", file=sys.stderr, flush=True)
    return {"wire": out}


def bench_wire_overhead(chunks: int = 600, rows: int = 16,
                        smoke: bool = False) -> dict:
    """Bandwidth-accountant cost on the ingest hot path (ISSUE 18
    acceptance): a real DcnClient→DcnGateway wire ingest loop with the
    plane at its production default (enabled) measures the per-chunk
    ingest span, and the plane's per-chunk adds — the four
    ``note_frame`` stamps an EXP round-trip pays (exp tx/rx + ack
    tx/rx, each a weak socket lookup + one dict get + two int adds
    under the lock) plus the ``note_transitions`` row count and the
    flow ledger's byte legs — are DIRECTLY timed in isolation.  The
    gate number ``wire_overhead_frac`` is plane-work-per-chunk over
    ingest-span-per-chunk, held under the 0.02 absolute band by
    bench_gate — the PR-10 lesson applies verbatim: differencing two
    noisy wire throughputs reads scheduler hiccups as fake overhead,
    so the rate difference is never the gate number.

    ``smoke=True`` shrinks the loop to sub-second for CI; the
    measurement logic is identical."""
    import socket as socket_mod

    from pytorch_distributed_tpu.agents.clocks import (
        ActorStats, GlobalClock,
    )
    from pytorch_distributed_tpu.agents.param_store import ParamStore
    from pytorch_distributed_tpu.parallel.dcn import (
        T_CLOCK, T_EXP, DcnClient, DcnGateway,
    )
    from pytorch_distributed_tpu.utils import bandwidth
    from pytorch_distributed_tpu.utils.experience import Transition

    wire_iters = 20_000
    if smoke:
        chunks = min(chunks, 250)
        wire_iters = 8_000
    z = np.zeros(4, dtype=np.float32)
    t = Transition(state0=z, action=np.int32(0), reward=np.float32(0.0),
                   gamma_n=np.float32(0.99), state1=z,
                   terminal1=np.float32(0.0))
    chunk = [(t, 1.0)] * rows
    bandwidth.reset_for_tests()
    store = ParamStore(4)
    store.publish(np.zeros(4, dtype=np.float32))
    gw = DcnGateway(store, GlobalClock(), ActorStats(),
                    put_chunk=lambda items: None, host="127.0.0.1",
                    port=0, pressure=lambda: 0.0)
    acct = bandwidth.get_accountant()
    assert acct is not None, "wire plane off at its production default"
    client = DcnClient(("127.0.0.1", gw.port), process_ind=0)
    for _ in range(30):  # session + validator + allocator warmup
        client.send_chunk(chunk)
    t0 = time.perf_counter()
    for _ in range(chunks):
        client.send_chunk(chunk)
    span = time.perf_counter() - t0
    # the plane's per-chunk work, timed directly on a registered live
    # socket (the weak side-table lookup is part of the cost)
    s1, s2 = socket_mod.socketpair()
    acct.register_socket(s1, "client", 0)
    nb = 4096
    t0 = time.perf_counter()
    for _ in range(wire_iters):
        acct.note_frame(s1, T_EXP, nb, "tx")
        acct.note_frame(s1, T_EXP, nb, "rx")
        acct.note_frame(s1, T_CLOCK, 64, "tx")
        acct.note_frame(s1, T_CLOCK, 64, "rx")
        acct.note_transitions(rows)
        gw.flow.note_ingested_bytes(nb)
    wire_s = time.perf_counter() - t0
    s1.close()
    s2.close()
    client.close()
    gw.close()
    bandwidth.reset_for_tests()
    per_chunk = span / max(chunks, 1)
    per_wire = wire_s / max(wire_iters, 1)
    out = {
        "chunks_per_sec_ingest": round(chunks / span, 1),
        "chunk_ingest_us": round(per_chunk * 1e6, 2),
        "wire_us_per_chunk": round(per_wire * 1e6, 3),
        # the gate number: per-chunk accountant work / per-chunk
        # ingest span
        "wire_overhead_frac": round(per_wire / per_chunk, 4),
        "chunk_rows": rows,
        "geometry": "smoke-wire" if smoke else "wire",
    }
    print(f"[bench_wire_overhead] {out}", file=sys.stderr, flush=True)
    return {"wire_overhead": out}


def bench_smoke(updates: int = 384) -> dict:
    """Seconds-scale, CPU-safe bench for CI gating (ISSUE 6 satellite):
    the dqn-mlp learner program fused over a small uniform HBM-style
    ring — tiny enough to compile and run in seconds on a CPU host,
    production-shaped enough (fused sample+train scan, fetch-bounded
    windows, XLA-derived flops) that a real regression in the core
    train-step machinery moves it.  The output feeds
    ``tools/bench_gate.py --against BENCH_SMOKE_BASELINE.json`` and is
    recorded into ``BENCH_HISTORY.jsonl`` — perf as a CI check, not an
    offline artifact.  Absolute rates are machine-dependent; gate smoke
    runs against a SAME-MACHINE baseline/history (the checked-in
    baseline documents this image's figures)."""
    import jax

    B, K = 32, 8
    fused, state, ring = _mlp_fused_program(B, K)
    key = jax.random.PRNGKey(0)

    def keymat():
        nonlocal key
        key, sub = jax.random.split(key)
        return jax.random.split(sub, K)

    t_compile = time.perf_counter()
    compiled = fused.lower(state, ring.state, keymat()).compile()
    t_compile = time.perf_counter() - t_compile
    flops = flops_of_compiled(compiled)
    for _ in range(3):
        state, metrics = compiled(state, ring.state, keymat())
    float(jax.device_get(metrics["learner/critic_loss"]))
    windows, rates = 4, []
    iters = max(updates // (4 * K), 1)
    for _ in range(windows):
        keysets = [keymat() for _ in range(iters)]
        jax.block_until_ready(keysets[-1])
        t0 = time.perf_counter()
        for ks in keysets:
            state, metrics = compiled(state, ring.state, ks)
        float(jax.device_get(metrics["learner/critic_loss"]))
        rates.append(iters * K / (time.perf_counter() - t0))
    out = {
        "updates_per_sec": round(float(np.median(rates)), 2),
        "batch_size": B,
        "steps_per_dispatch": K,
        "compile_seconds": round(t_compile, 2),
    }
    if flops:
        out["flops_per_update"] = round(flops)

    # ISSUE-13 megabatch leg: the same dqn-mlp program fused as ONE
    # M=32 widened-gather group per dispatch — the smoke gate's
    # regression canary for the megabatch machinery (additive key,
    # schema stays 4)
    MB = 32
    mfused, mstate, mring = _mlp_fused_program(B, MB, megabatch=MB)
    mkey = jax.random.PRNGKey(0)

    def mkeymat():
        nonlocal mkey
        mkey, sub = jax.random.split(mkey)
        return jax.random.split(sub, MB)

    mcompiled = mfused.lower(mstate, mring.state, mkeymat()).compile()
    for _ in range(3):
        mstate, mmetrics = mcompiled(mstate, mring.state, mkeymat())
    float(jax.device_get(mmetrics["learner/critic_loss"]))
    mrates = []
    miters = max(updates // (4 * MB), 1)
    for _ in range(4):
        keysets = [mkeymat() for _ in range(miters)]
        jax.block_until_ready(keysets[-1])
        t0 = time.perf_counter()
        for ks in keysets:
            mstate, mmetrics = mcompiled(mstate, mring.state, ks)
        float(jax.device_get(mmetrics["learner/critic_loss"]))
        mrates.append(miters * MB / (time.perf_counter() - t0))
    out["updates_per_sec_megabatch"] = round(float(np.median(mrates)), 2)
    out["megabatch_k"] = MB
    print(f"[bench_smoke] {out}", file=sys.stderr, flush=True)
    return {"smoke": out}


def bench_actor_pipeline(envs: int = 16, ticks: int = 300) -> dict:
    """Actor hot-loop section (ISSUE 4): serial vs software-pipelined
    schedules on the production actor shape (pong-sim vector, Nature-CNN
    forward on the host CPU — the inline/pipelined backends always run
    inference host-side; the accelerator-served ``batched`` backend is
    measured by the e2e section, where a learner process owns the chip).

    Reported per schedule: per-tick phase breakdown (ms; the jit-compile
    tick is excluded by dropping each phase's max before averaging) and
    the implied frames/s.  Plus:

    - ``env_only_frames_per_sec`` — the ceiling if inference were free:
      the bare env vector stepped with constant actions;
    - ``overlap_efficiency`` — hidden device time / total device time:
      of the act time the serial schedule pays (``act`` = dispatch +
      blocked sync), the fraction the pipelined schedule hides under
      host work, ``(act_serial - sync - dispatch) / act_serial``.  On a
      one-core host CPU compute cannot actually overlap host python — so
      this number is ALSO the honest measure of how much of the "act"
      cost was dispatch/transfer latency rather than compute.
    """
    from pytorch_distributed_tpu.config import build_options
    from pytorch_distributed_tpu.factory import build_env_vector
    from pytorch_distributed_tpu.agents.actor import bounded_actor_run

    root = tempfile.mkdtemp(prefix="bench_actor_")

    def adjusted(timer_ms, phase):
        """Per-call ms with the single worst call (the compile) dropped."""
        mean = timer_ms.get(f"actor/time_{phase}_ms")
        if mean is None:
            return None
        mx = timer_ms[f"actor/time_{phase}_max_ms"]
        n = timer_ms[f"actor/time_{phase}_calls"]
        if n <= 1:
            return round(mean, 3)
        return round((mean * n - mx) / (n - 1), 3)

    out = {"envs": envs, "ticks": ticks}
    for backend in ("inline", "pipelined"):
        opt = build_options(
            4, root_dir=root, refs=f"actor_{backend}", num_actors=1,
            num_envs_per_actor=envs, actor_backend=backend,
            visualize=False,
            # no mid-run flush/sync: the timer must hold the whole run
            actor_freq=10 ** 9, actor_sync_freq=10 ** 9)
        res = bounded_actor_run(opt, ticks)
        t = res["timer_ms"]
        phases = {p: adjusted(t, p)
                  for p in ("act", "sync", "dispatch", "env", "advance")
                  if adjusted(t, p) is not None}
        host = (("sync", "dispatch", "env", "advance")
                if backend == "pipelined" else ("act", "env", "advance"))
        tick_ms = sum(phases[p] for p in host if p in phases)
        out[backend] = {
            "tick_ms": round(tick_ms, 3),
            "frames_per_sec": round(envs / tick_ms * 1e3, 1) if tick_ms
            else None,
            "phases_ms": phases,
        }
        print(f"[bench_actor_pipeline] {backend}: {out[backend]}",
              file=sys.stderr, flush=True)
    # env-only ceiling: the same vector stepped with constant actions
    opt = build_options(4, root_dir=root, refs="actor_env_only",
                        num_envs_per_actor=envs, visualize=False)
    env = build_env_vector(opt, 0, envs)
    env.train()
    env.reset()
    acts = np.zeros(envs, dtype=np.int64)
    for _ in range(10):
        env.step(acts)
    t0 = time.perf_counter()
    for _ in range(ticks):
        env.step(acts)
    env_tick = (time.perf_counter() - t0) / ticks
    out["env_only_frames_per_sec"] = round(envs / env_tick, 1)
    act_serial = out["inline"]["phases_ms"].get("act")
    pip = out["pipelined"]["phases_ms"]
    if act_serial:
        hidden = act_serial - pip.get("sync", 0.0) - pip.get("dispatch",
                                                             0.0)
        out["overlap_efficiency"] = round(
            min(max(hidden / act_serial, 0.0), 1.0), 4)
    if out["inline"].get("frames_per_sec") and \
            out["pipelined"].get("frames_per_sec"):
        out["pipeline_speedup"] = round(
            out["pipelined"]["frames_per_sec"]
            / out["inline"]["frames_per_sec"], 3)
    return {"actor_pipeline": out}


def _device_env_linear_policy(state_shape):
    """A fixed random linear Q-head over the flattened obs: the
    cheapest policy that still exercises the rollout engine's full
    per-tick structure (forward -> eps-greedy -> env -> n-step ->
    ring).  Engine-cost rows use it so the section separates what the
    ROLLOUT PLANE costs from what the configured model costs (on a CPU
    host the Nature CNN forward alone caps any actor plane at ~1k
    frames/s; on a TPU it is noise)."""
    import jax.numpy as jnp

    dim = int(np.prod(state_shape))
    w = jnp.asarray(np.random.default_rng(0).normal(
        size=(dim, 6)).astype(np.float32) * 0.01)

    def apply_fn(params, obs):
        x = obs.reshape((obs.shape[0], -1)).astype(jnp.float32) / 255.0
        return x @ params

    return apply_fn, w


def bench_device_env(ns=(64, 256, 1024), scan_ticks: int = 8,
                     smoke: bool = False) -> dict:
    """The ISSUE-7 device env fleet section: env frames/s of the three
    env backends at N in ``ns`` plus the fused rollout engine.

    - ``ladder`` — env-STEPPING throughput per backend: the Python
      ``VectorEnv`` (the reference-shaped host path), the C++ batched
      stepper (when the toolchain builds it), and the device env (one
      jitted scan advancing all N pure-JAX envs ``scan_ticks`` ticks
      per dispatch).  All three produce the full 84x84 uint8 stacked
      observation per tick; actions are held fixed, as in the
      actor-pipeline section's env-only ceiling.
    - ``fused`` — the COMPLETE device actor plane per dispatch
      (models/policies.build_fused_rollout, emit="replay"): policy
      forward + eps-greedy + env + on-device n-step assembly +
      transitions scattered straight into a device replay ring with
      zero host round-trip.  Two policies: ``linear`` (engine cost —
      what the rollout plane itself costs) and ``cnn`` (the production
      Nature-CNN policy; on CPU hosts its forward dominates, which the
      row's ``policy_bound`` flag says explicitly).
    - ``speedup_vs_host`` — device ladder row over the Python host row
      at the widest N: the acceptance figure (>= 10x on this image's
      CPU: the host plane pays ~N Python frames per tick, the device
      plane one dispatch).

    Window timing is fetch-bounded like every other section (a value
    fetch chains behind the dispatched work).
    """
    import jax
    import jax.numpy as jnp

    from pytorch_distributed_tpu.config import build_options
    from pytorch_distributed_tpu.envs.device_env import build_device_env
    from pytorch_distributed_tpu.envs.vector import VectorEnv
    from pytorch_distributed_tpu.envs.pong_sim import PongSimEnv
    from pytorch_distributed_tpu.memory.device_replay import DeviceReplay
    from pytorch_distributed_tpu.models.policies import (
        build_fused_rollout, init_rollout_carry,
    )

    if smoke:
        ns = (32,)
    opt = build_options(4, visualize=False)
    K = scan_ticks
    out: dict = {"n_ladder": list(ns), "scan_ticks": K, "ladder": {}}

    def median_windows(tick_fn, frames_per_tick: int, ticks: int,
                       windows: int = 5):
        """Median frames/s over independent windows (the bench-wide
        convention: one scheduler stall must not skew a row), with a
        gc pass first so a previous row's teardown is not billed
        here."""
        import gc

        gc.collect()
        tick_fn()  # warm (compile / allocator settle)
        rates = []
        for _ in range(windows):
            t0 = time.perf_counter()
            for _ in range(ticks):
                tick_fn()
            rates.append(frames_per_tick * ticks
                         / (time.perf_counter() - t0))
        return float(np.median(rates))

    def host_row(N: int):
        env = VectorEnv([PongSimEnv(opt.env_params, j) for j in range(N)])
        env.reset()
        acts = np.zeros(N, dtype=np.int64)
        return median_windows(lambda: env.step(acts), N,
                              ticks=max(2, 1024 // N))

    def native_row(N: int):
        try:
            from pytorch_distributed_tpu.envs.native_pong import (
                NativePongVectorEnv, get_lib,
            )

            get_lib()
        except Exception:  # noqa: BLE001 - no toolchain: row omitted
            return None
        env = NativePongVectorEnv(opt.env_params, 0, N)
        env.reset()
        acts = np.zeros(N, dtype=np.int64)
        return median_windows(lambda: env.step(acts), N,
                              ticks=max(2, 4096 // N))

    def device_row(N: int):
        env = build_device_env(opt.env_params, 0, N)
        acts = jnp.zeros((N,), jnp.int32)

        @functools.partial(jax.jit, donate_argnums=(0,))
        def scan_steps(state):
            def body(s, _):
                s, out_ = env.step(s, acts)
                return s, out_.reward

            s, r = jax.lax.scan(body, state, None, length=K)
            return s, r

        box = [env.init()]

        def tick():
            box[0], r = scan_steps(box[0])
            float(jax.device_get(r[-1][0]))  # fetch-bounded
        return median_windows(tick, N * K,
                              ticks=max(1, 8192 // (K * N)))

    for N in ns:
        row = {"host_frames_per_sec": round(host_row(N), 1)}
        nat = native_row(N)
        if nat is not None:
            row["native_frames_per_sec"] = round(nat, 1)
        row["device_frames_per_sec"] = round(device_row(N), 1)
        out["ladder"][str(N)] = row
        print(f"[bench_device_env] N={N}: {row}", file=sys.stderr,
              flush=True)

    # ---- fused rollout engine (emit="replay": zero-copy into HBM) ----
    def fused_row(N: int, policy: str):
        env = build_device_env(opt.env_params, 0, N)
        if policy == "linear":
            apply_fn, params = _device_env_linear_policy(env.state_shape)
        else:
            from pytorch_distributed_tpu.models import DqnCnnModel

            model = DqnCnnModel(action_space=6, norm_val=255.0)
            params = model.init(jax.random.PRNGKey(0),
                                np.zeros((1, 4, 84, 84), np.uint8))
            apply_fn = model.apply
        ring = DeviceReplay(capacity=max(2 * K * N, 2048),
                            state_shape=env.state_shape,
                            state_dtype=np.uint8)
        roll = build_fused_rollout(apply_fn, env, nstep=5, gamma=0.99,
                                   rollout_ticks=K, emit="replay")
        eps = jnp.full((N,), 0.1, jnp.float32)
        key = jnp.asarray(jax.random.PRNGKey(0))
        box = [init_rollout_carry(env, 5), ring.state, jnp.int32(0)]

        def tick():
            carry, rs, tick0 = box
            carry, rs, stats = roll(params, carry, rs, key, tick0, eps)
            int(jax.device_get(stats.fed))  # fetch-bounded
            box[:] = [carry, rs, tick0 + K]

        return median_windows(
            tick, N * K,
            ticks=max(1, (2048 if policy == "linear" else 256)
                      // (K * N)),
            windows=3 if policy == "linear" else 2)

    out["fused"] = {}
    fused_ns = ns if not smoke else (32,)
    for N in fused_ns:
        row = {"linear_frames_per_sec": round(fused_row(N, "linear"), 1)}
        if not smoke:
            row["cnn_frames_per_sec"] = round(fused_row(N, "cnn"), 1)
            # on CPU hosts the Nature-CNN forward alone is the wall;
            # flag it so the row is read as a model cost, not an
            # engine cost
            row["policy_bound"] = bool(
                row["cnn_frames_per_sec"]
                < 0.5 * row["linear_frames_per_sec"])
        out["fused"][str(N)] = row
        print(f"[bench_device_env] fused N={N}: {row}", file=sys.stderr,
              flush=True)

    top = str(max(ns))
    host = out["ladder"][top]["host_frames_per_sec"]
    dev = out["ladder"][top]["device_frames_per_sec"]
    out["host_frames_per_sec"] = host
    out["device_frames_per_sec"] = dev
    out["fused_frames_per_sec"] = out["fused"][top][
        "linear_frames_per_sec"]
    if host:
        out["speedup_vs_host"] = round(dev / host, 2)
    # the ROADMAP open-item-1 read: with the env fleet on device, the
    # actor plane stops being bound by the host env step — what binds
    # next is the policy forward (CPU) or the ingest plane (TPU)
    out["host_step_bound"] = False
    return {"device_env": out}


def bench_anakin(pairs: int = 10, envs: int = 16, ticks: int = 8,
                 smoke: bool = False) -> dict:
    """The ISSUE-12 closed-loop section: the co-located Anakin driver
    (agents/anakin.py — env fleet + learner in ONE process, the fused
    rollout scattering straight into the HBM PER ring, zero host work
    on the experience path) against the split-process ``device``
    backend's host plumbing driving the SAME XLA programs (chunk D2H
    -> per-row feeder -> spawn queue -> ingest drain -> fused learner
    step — the ~56 KB/transition wall BENCH_r03 measured).

    Both legs run the same strict-alternation schedule (one rollout
    dispatch, one learner dispatch, ``pairs`` times) on the same
    geometry, so ``speedup_vs_device`` is purely the host plumbing the
    co-location deletes.  ``duty_cycle`` is the rollout share of busy
    time (the ``anakin/duty_cycle`` telemetry tag's exact definition);
    frames/s counts ALL env frames over the pair wall clock — the
    e2e-loop rate, not the rollout-only ceiling the device_env section
    reports.  ``smoke=True`` shrinks the fleet to seconds-scale and
    skips the split leg (one compile instead of three); the smoke
    output rides ``smoke.anakin_frames_per_sec`` into the gate."""
    import jax

    from pytorch_distributed_tpu.agents.anakin import AnakinDriver
    from pytorch_distributed_tpu.agents.clocks import (
        ActorStats, GlobalClock, LearnerStats,
    )
    from pytorch_distributed_tpu.config import build_options
    from pytorch_distributed_tpu.agents.param_store import (
        ParamStore, make_flattener,
    )
    from pytorch_distributed_tpu.factory import (
        build_memory, build_model, init_params, probe_env,
    )

    if smoke:
        pairs, envs, ticks = 4, 8, 6

    def make_opt(root, **over):
        # config 12 (pong-sim + HBM PER ring) with the mlp head: the
        # cnn forward would drown the plumbing delta on a CPU host (the
        # device_env section's policy_bound flag), and the ring schema
        # pins uint8 to match the device env's frames (the config-12
        # cnn default; the mlp default would flip it to float32)
        base = dict(
            root_dir=root, refs="bench_anakin", num_actors=1,
            num_envs_per_actor=envs, actor_backend="anakin",
            visualize=False, model_type="dqn-mlp", state_dtype="uint8",
            nstep=4, memory_size=4096, learn_start=64, batch_size=32,
            steps=10 ** 9, early_stop=50, actor_freq=10 ** 9,
            learner_freq=10 ** 9, param_publish_freq=10 ** 9,
            checkpoint_freq=10 ** 9)
        base.update(over)
        opt = build_options(config=12, **base)
        opt.env_params.device_rollout_ticks = ticks
        return opt

    # ---- leg A: the co-located driver ----
    root_a = tempfile.mkdtemp(prefix="bench_anakin_")
    opt = make_opt(root_a)
    spec = probe_env(opt)
    handles = build_memory(opt, spec)
    model = build_model(opt, spec)
    flat0, _ = make_flattener(init_params(opt, spec, model,
                                          seed=opt.seed))
    drv = AnakinDriver(opt, spec, handles.learner_side,
                       ParamStore(flat0.size), GlobalClock(),
                       LearnerStats(), actor_stats=ActorStats())
    drv.dispatch_rollout()   # compile both programs outside the window
    drv.dispatch_learn()
    drv._roll_s = drv._learn_s = 0.0
    t0 = time.perf_counter()
    for _ in range(pairs):
        drv.dispatch_rollout()
        drv.dispatch_learn()
    jax.block_until_ready(drv.state.params)
    wall = time.perf_counter() - t0
    frames = pairs * ticks * envs
    updates = pairs * drv.K_learn
    busy = drv._roll_s + drv._learn_s
    out = {
        "frames_per_sec": round(frames / wall, 1),
        "updates_per_sec": round(updates / wall, 2),
        "duty_cycle": round(drv._roll_s / busy, 4) if busy else None,
        "pairs": pairs,
        "geometry": f"dqn-mlp head, {envs} envs x {ticks} ticks, "
                    f"uint8 HBM PER ring (config 12)",
    }
    drv.writer.close()
    handles.learner_side.close()
    print(f"[bench_anakin] co-located: {out}", file=sys.stderr,
          flush=True)

    if not smoke:
        out["split_frames_per_sec"] = _anakin_split_leg(
            make_opt, pairs, envs, ticks)
        out["speedup_vs_device"] = round(
            out["frames_per_sec"] / out["split_frames_per_sec"], 2)
        print(f"[bench_anakin] split-process: "
              f"{out['split_frames_per_sec']} f/s "
              f"(speedup {out['speedup_vs_device']}x)",
              file=sys.stderr, flush=True)
    return {"anakin": out}


def _anakin_split_leg(make_opt, pairs: int, envs: int,
                      ticks: int) -> float:
    """The split-process ``actor_backend="device"`` loop's pieces in
    one process, driven to the same strict-alternation schedule as the
    co-located leg: chunk-emit rollout -> device_get -> per-row feeder
    (the device actor loop's exact feed path) -> spawn queue -> ingest
    drain -> fused learner step."""
    import jax
    import jax.numpy as jnp

    from pytorch_distributed_tpu.factory import (
        build_device_env, build_memory, build_model,
        build_train_state_and_step, init_params, probe_env,
    )
    from pytorch_distributed_tpu.models.policies import (
        apex_epsilons, build_fused_rollout, init_rollout_carry,
    )
    from pytorch_distributed_tpu.utils.experience import (
        Transition, make_prov,
    )
    from pytorch_distributed_tpu.utils.rngs import np_rng, process_key

    root = tempfile.mkdtemp(prefix="bench_anakin_split_")
    opt = make_opt(root, actor_backend="device")
    ap = opt.agent_params
    spec = probe_env(opt)
    ingest = build_memory(opt, spec).learner_side
    model = build_model(opt, spec)
    params = init_params(opt, spec, model, seed=opt.seed)
    state, step_fn = build_train_state_and_step(opt, spec, model, params)
    ring = ingest.attach()
    fused = ring.build_fused_step(step_fn, ap.batch_size,
                                  donate=opt.parallel_params.donate,
                                  steps_per_call=1)
    device_key = jax.random.PRNGKey(
        np_rng(opt.seed, "learner", 0).integers(2 ** 31))
    env = build_device_env(opt, 0, envs)
    roll = build_fused_rollout(model.apply, env, nstep=ap.nstep,
                               gamma=ap.gamma, rollout_ticks=ticks,
                               emit="chunk")
    carry = init_rollout_carry(env, ap.nstep)
    base_key = jnp.asarray(process_key(opt.seed, "actor", 0))
    eps = jnp.asarray(apex_epsilons(0, 1, envs, ap.eps, ap.eps_alpha),
                      jnp.float32)
    feeder = ingest.make_feeder()
    tick0 = jnp.int32(0)
    fed_expected = 0

    def pair(k):
        nonlocal carry, tick0, state, device_key, fed_expected
        carry, chunk = roll(state.params, carry, base_key, tick0, eps)
        tick0 = tick0 + ticks
        ch = jax.device_get(chunk)   # the split path's chunk D2H
        valid = np.asarray(ch.valid)
        for t in range(ticks):
            for j in range(envs):
                if not valid[t, j]:
                    continue
                feeder.feed(Transition(
                    state0=ch.state0[t, j], action=ch.action[t, j],
                    reward=ch.reward[t, j], gamma_n=ch.gamma_n[t, j],
                    state1=ch.state1[t, j],
                    terminal1=ch.terminal1[t, j],
                    prov=make_prov(0, j, 0, k)), None)
                fed_expected += 1
        feeder.flush()
        # drain until THIS dispatch's transitions have all landed in
        # the ring — the freshness the co-located loop gives by
        # construction (each learn samples the rollout it just ran).
        # Letting the queue lag instead hides the plumbing behind the
        # learner's XLA time on an idle core, at the price of sampling
        # stale data — exactly the Podracer trade this section exists
        # to measure.  The geometry keeps every dispatch's emission
        # count a multiple of the smallest feeder chunk (64) so the
        # drain can fully settle.
        deadline = time.monotonic() + 30.0
        while ingest._fed_total < fed_expected \
                and time.monotonic() < deadline:
            ingest.drain()
            time.sleep(0.001)
        keys = jax.random.split(device_key, 2)
        device_key = keys[0]
        beta = jax.device_put(np.float32(ring.beta(k)))
        new_state, ring.state, _m = fused(state, ring.state, keys[1],
                                          beta)
        return new_state

    state = pair(0)   # compile outside the window
    t0 = time.perf_counter()
    for k in range(pairs):
        state = pair(k + 1)
    jax.block_until_ready(state.params)
    wall = time.perf_counter() - t0
    ingest.close()
    return round(pairs * ticks * envs / wall, 1)


def bench_e2e(seconds: float = 60.0, actors: int = 1,
              envs_per_actor: int = 16,
              actor_backend: str | None = None) -> dict:
    """North-star accounting: env frames/s + paced updates/s with the full
    config-8 topology live (actors -> feeder -> HBM replay -> learner).

    ``actors``/``envs_per_actor`` reshape the fleet: the default 1x16 is
    the production topology for few-CPU hosts (the actor tick is ~94%
    jitted CNN inference, so one process with a wider batch beats N
    processes time-slicing a core — measured 143 -> 250+ agent steps/s on
    the 1-CPU image, 2026-07-31); ``--e2e-actors 16 --e2e-envs 1`` is the
    reference-scale fan-out drive (reference main.py:68-80 spawns
    num_actors processes), converting the many-actor architecture claim
    into a measured aggregate rate on whatever host runs this."""
    import jax

    from pytorch_distributed_tpu import runtime
    from pytorch_distributed_tpu.config import build_options
    from pytorch_distributed_tpu.utils.metrics import read_scalars

    if actor_backend is None:
        # with an accelerator present the learner parent owns it and can
        # host the SEED-style inference batcher — actor ticks stop being
        # host-CPU convnet forwards (ISSUE 4); CPU-only hosts run the
        # ISSUE-12 CLOSED loop: env fleet + learner co-located in one
        # process, zero spawn-queue/D2H work on the experience path
        # (the config-8 pong-sim env has a device implementation and
        # the config-8 memory is the HBM ring anakin scatters into)
        actor_backend = ("batched"
                         if jax.devices()[0].platform != "cpu"
                         else "anakin")

    t_start = time.perf_counter()

    def mark(stage: str) -> None:
        print(f"[bench_e2e +{time.perf_counter() - t_start:.1f}s] {stage}",
              file=sys.stderr, flush=True)

    root = tempfile.mkdtemp(prefix="bench_e2e_")
    opt = build_options(
        8, root_dir=root, refs="bench_e2e", num_actors=actors,
        num_envs_per_actor=envs_per_actor, batch_size=128, visualize=False,
        learn_start=1000, max_replay_ratio=8.0, logger_freq=5,
        actor_backend=actor_backend,
        evaluator_nepisodes=0,  # no evaluator process in the bench
        steps=10 ** 9, max_seconds=seconds + 45.0)
    if actor_backend == "anakin" and jax.devices()[0].platform == "cpu":
        # duty-cycle setpoint for the CPU image: the split-process
        # backends' actors free-run while the CNN learner trails far
        # behind (BENCH_r03: ~470 f/s against ~1 update/s — replay
        # ratio << 1), so the comparable anakin schedule is the same
        # data-rich regime, ~4 frames collected per sampled-batch row.
        # Strict alternation (ratio 0, the default) is the TPU
        # operating point: there the learn dispatch is ms-scale and
        # alternation keeps the chip saturated either way.
        opt.anakin_params = dataclasses.replace(
            opt.anakin_params, rollout_ratio=4.0 * opt.agent_params.
            batch_size)

    # The topology (and its child processes) write progress to fd 1; the
    # driver contract is ONE JSON line on stdout, so point fd 1 at stderr
    # for the duration and restore it for the final print.
    saved_stdout = os.dup(1)
    mark("starting topology")
    try:
        sys.stdout.flush()
        os.dup2(2, 1)
        runtime.train(opt, backend="process")
    finally:
        sys.stdout.flush()  # buffered worker prints must NOT hit real fd 1
        os.dup2(saved_stdout, 1)
        os.close(saved_stdout)
    mark("topology done")

    rows = read_scalars(os.path.join(root, "logs", "bench_e2e"))
    frames = [(r["wall"], r["value"]) for r in rows
              if r["tag"] == "actor/total_nframes"]
    lrates = [(r["wall"], r["value"]) for r in rows
              if r["tag"] == "learner/steps_per_sec"]
    if len(frames) < 3:
        return {"e2e_error": "too few logger windows"}
    # drop the first quarter of the wall span: children are still paying
    # jax import + compile there, which is startup, not throughput
    t0, t1 = frames[0][0], frames[-1][0]
    cut = t0 + 0.25 * (t1 - t0)
    kept = [(w, v) for w, v in frames[1:] if w >= cut]  # [1:]: deltas
    span = kept[-1][0] - kept[0][0] if len(kept) > 1 else 0.0
    agent_steps = sum(v for _, v in kept[1:])
    out = {
        "e2e_frames_per_sec": round(agent_steps / span, 1) if span else None,
        "e2e_emulator_frames_per_sec":
            round(4 * agent_steps / span, 1) if span else None,
        "e2e_seconds": round(t1 - t0, 1),
        "e2e_actors": f"{actors}x{envs_per_actor} envs",
        "e2e_num_actors": actors,
        "e2e_actor_backend": actor_backend,
    }
    lr = [v for w, v in lrates if w >= cut]
    if lr:
        out["e2e_paced_updates_per_sec"] = round(float(np.median(lr)), 2)
    # Actor-plane wall-time breakdown (SURVEY §7 hard part "batch-1 actor
    # inference latency"): the actors' StepTimer scalars say where each
    # tick goes — jitted act() forward, env.step, or the python feed path
    # (advance).  Medians over the kept window, ms per vector tick.
    breakdown = {}
    for tag in ("actor/time_act_ms", "actor/time_env_ms",
                "actor/time_advance_ms", "actor/time_sync_ms",
                "actor/time_dispatch_ms", "actor/time_param_swap_ms",
                "actor/time_rollout_ms", "actor/time_emit_ms"):
        vals = [r["value"] for r in rows
                if r["tag"] == tag and r["wall"] >= cut]
        if vals:
            breakdown[tag.split("/")[-1]] = round(float(np.median(vals)), 3)
    if breakdown:
        out["e2e_actor_tick_ms"] = breakdown
    # pipelined/batched actors: overlap efficiency = the host work the
    # in-flight dispatch hid / the device-wait it couldn't hide + that
    # hidden work — per-tick, from the actors' own phase timers.  1.0
    # means every device/server microsecond was covered by env stepping
    # and feed work; 0 means the pipeline never hid anything (the serial
    # loop's behaviour by construction).
    if "time_sync_ms" in breakdown:
        hidden = breakdown.get("time_env_ms", 0.0) + breakdown.get(
            "time_advance_ms", 0.0)
        wait = breakdown["time_sync_ms"] + breakdown.get(
            "time_dispatch_ms", 0.0)
        if hidden + wait > 0:
            out["e2e_overlap_efficiency"] = round(
                hidden / (hidden + wait), 4)
    if actor_backend == "device":
        # the ISSUE-7 read: the actor plane has NO host env step — its
        # tick breakdown is the fused device dispatch (rollout), the
        # once-per-dispatch chunk fetch (emit) and the replay feed
        # (advance); time_env_ms cannot appear by construction
        out["e2e_host_env_step_ms"] = 0.0
        out["e2e_actor_plane"] = (
            "device rollout (fused env+policy+nstep scan) — actor "
            "plane no longer bound by the host env step")
    elif actor_backend == "anakin":
        # the ISSUE-12 read: there is no actor PROCESS at all — the
        # learner process hosts the env fleet and alternates the fused
        # rollout (scattering in-graph into its own HBM ring) with the
        # fused learner step; no host env step, no spawn queue, no
        # D2H on the experience path.  What binds e2e now is the
        # learner-side FLOPs (rollout forward + train step) alone.
        out["e2e_host_env_step_ms"] = 0.0
        out["e2e_actor_plane"] = (
            "anakin co-located loop (env fleet in the learner "
            "process, in-graph replay scatter) — e2e is "
            "learner-FLOPs-bound, zero experience-path transfers")
    return out


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--mode", choices=("micro", "e2e", "both", "families",
                                       "sampler", "act", "actor",
                                       "health", "perf", "device_env",
                                       "provenance", "metrics", "flow",
                                       "anakin", "replica",
                                       "gateway", "wire", "shard"),
                    default="both")
    ap.add_argument("--smoke", action="store_true",
                    help="seconds-scale CPU-safe bench (the dqn-mlp "
                         "fused learner program only) for CI gating: "
                         "pipe the JSON into tools/bench_gate.py "
                         "--against BENCH_SMOKE_BASELINE.json")
    ap.add_argument("--e2e-seconds", type=float, default=60.0)
    ap.add_argument("--e2e-actors", type=int, default=1)
    ap.add_argument("--e2e-envs", type=int, default=16)
    ap.add_argument("--e2e-actor-backend", type=str, default=None,
                    choices=("inline", "pipelined", "batched", "device",
                             "anakin"),
                    help="override the e2e actor schedule (default: "
                         "batched on accelerator hosts, else the "
                         "ISSUE-12 co-located anakin loop)")
    ap.add_argument("--actor-envs", type=int, default=16,
                    help="env-vector width for the actor-pipeline section")
    ap.add_argument("--actor-ticks", type=int, default=300)
    args = ap.parse_args()

    import jax

    from pytorch_distributed_tpu.utils.helpers import enable_compile_cache

    # a fresh process otherwise pays minutes of remote compiles on a
    # tunnelled chip before measuring anything
    enable_compile_cache()

    result = {}
    if args.smoke:
        result.update(bench_smoke())
        # seconds-scale device-env engine row (N=32, linear policy)
        # so the gate covers the ISSUE-7 actor plane from day one
        dev = bench_device_env(smoke=True)["device_env"]
        result["smoke"]["device_env_frames_per_sec"] = \
            dev["fused"]["32"]["linear_frames_per_sec"]
        result["smoke"]["device_env_host_frames_per_sec"] = \
            dev["ladder"]["32"]["host_frames_per_sec"]
        # ISSUE-10 telemetry-plane overhead rides the smoke output so
        # the pre-PR gate holds the <2% band continuously (additive
        # key — existing keys keep their meaning, so no schema bump)
        result.update(bench_metrics_overhead(smoke=True))
        # ISSUE-11 flow-plane overhead rides the smoke output the same
        # way (additive key, schema stays 4)
        result.update(bench_flow_overhead(smoke=True))
        # ISSUE-15 replica-plane overhead (lease renew + generation
        # stamp vs the round-exchange span): additive key, schema
        # stays 4; tools/check.sh stage 2c fails on its absence
        result.update(bench_replica_overhead(smoke=True))
        # ISSUE-16 gateway HA-plane overhead (journal append + sync
        # serve + per-frame term gate vs the wire ingest span):
        # additive key, schema stays 4; tools/check.sh stage 2d fails
        # on its absence
        result.update(bench_gateway_ha_overhead(smoke=True))
        # ISSUE-18 wire byte economics (legacy vs frame-packed
        # bytes/transition, replica bytes/round) and the accountant's
        # hot-path cost: additive keys, schema stays 4; tools/check.sh
        # stage 2e fails on their absence
        result.update(bench_wire(smoke=True))
        result.update(bench_wire_overhead(smoke=True))
        # ISSUE-20 sharded-replay plane: sample latency at 1/2/4
        # loopback shards and the mass-refresh+route cost vs the
        # sample span: additive keys, schema stays 4; tools/check.sh
        # stage 2f fails on their absence
        result.update(bench_shard(smoke=True))
        result.update(bench_shard_overhead(smoke=True))
        # ISSUE-12 co-located loop: the closed rollout+learn pair rate
        # on a tiny fleet (additive key, schema stays 4; the full
        # section with the split-process comparison runs under --mode
        # anakin/both)
        result["smoke"]["anakin_frames_per_sec"] = \
            bench_anakin(smoke=True)["anakin"]["frames_per_sec"]
        out = {
            "bench_schema": 4,
            "metric": "smoke_updates_per_sec",
            "value": result["smoke"]["updates_per_sec"],
            "unit": ("updates/s (dqn-mlp fused x8, smoke geometry — "
                     "machine-local figure, gate against same-machine "
                     "history)"),
            "mode": "smoke",
            "device_kind": getattr(jax.devices()[0], "device_kind", "?"),
        }
        out.update(result)
        print(json.dumps(out))
        return
    if args.mode in ("micro", "both"):
        result.update(bench_micro())
    if args.mode in ("both", "families"):
        result.update(bench_families())
    if args.mode in ("both", "sampler"):
        result.update(bench_sampler())
    if args.mode in ("both", "act"):
        result.update(bench_act_ab())
    if args.mode in ("both", "health"):
        result.update(bench_health_overhead())
    if args.mode in ("both", "perf"):
        result.update(bench_perf_overhead())
    if args.mode in ("both", "provenance"):
        result.update(bench_provenance_overhead())
    if args.mode in ("both", "metrics"):
        result.update(bench_metrics_overhead())
    if args.mode in ("both", "flow"):
        result.update(bench_flow_overhead())
    if args.mode in ("both", "replica"):
        result.update(bench_replica_overhead())
    if args.mode in ("both", "gateway"):
        result.update(bench_gateway_ha_overhead())
    if args.mode in ("both", "wire"):
        result.update(bench_wire())
        result.update(bench_wire_overhead())
    if args.mode in ("both", "shard"):
        result.update(bench_shard())
        result.update(bench_shard_overhead())
    if args.mode in ("both", "actor"):
        result.update(bench_actor_pipeline(args.actor_envs,
                                           args.actor_ticks))
    if args.mode in ("both", "device_env"):
        result.update(bench_device_env())
    if args.mode in ("both", "anakin"):
        result.update(bench_anakin())
    if args.mode in ("e2e", "both"):
        result.update(bench_e2e(args.e2e_seconds, args.e2e_actors,
                                args.e2e_envs, args.e2e_actor_backend))

    headline = result.get("updates_per_sec")
    n_dev = len(jax.devices())
    if headline is not None:
        metric = "dqn_cnn_learner_updates_per_sec"
        value = headline
        unit = (f"updates/s (batch {MICRO_BATCH}, "
                f"production fused x{MICRO_DISPATCH}, "
                f"HBM replay, {n_dev} device(s), "
                f"{jax.devices()[0].platform})")
    elif args.mode in ("e2e", "both"):
        # e2e ran (value may be None on an error path — keep the e2e
        # metric label either way so consumers see what failed)
        metric, value, unit = ("e2e_frames_per_sec",
                               result.get("e2e_frames_per_sec"),
                               "agent steps/s")
    elif "families" in result:  # families-only: summarize the table
        fams = result["families"]
        rates = [v["updates_per_sec"] for v in fams.values()
                 if "updates_per_sec" in v]
        metric = "family_learner_updates_per_sec_median"
        value = round(float(np.median(rates)), 2) if rates else None
        unit = f"updates/s (median of {len(rates)} model families)"
    else:  # sampler/act-only invocations have no throughput headline
        metric, value, unit = f"bench_{args.mode}", None, "see section keys"
    out = {
        # schema 4: adds the ISSUE-7 device_env section (on-device env
        # fleet ladder + fused rollout engine) and the e2e default
        # actor plane on CPU hosts becomes actor_backend=device (no
        # host env step — e2e_frames_per_sec is not comparable to
        # schema-3 rows measured with pipelined host-env actors;
        # e2e_actor_backend says which plane ran).  Schema 3: e2e runs
        # the ISSUE-4 actor plane (pipelined/batched), actor_pipeline
        # section, e2e_overlap_efficiency.  Schema 2 (r3):
        # production-K headline, fused families rows, sampler +
        # act-A/B sections.  Bump whenever a key's MEANING changes so
        # longitudinal consumers never compare across semantics
        # (round-3 advisor finding).
        "bench_schema": 4,
        "metric": metric,
        "value": value,
        "unit": unit,
        "vs_baseline": round(headline / BASELINE_UPDATES_PER_SEC, 3)
                       if headline is not None else None,
        "vs_baseline_basis": "self-declared 250 updates/s (consumer-GPU "
                             "class for this workload); reference "
                             "publishes no throughput figures",
        "device_kind": getattr(jax.devices()[0], "device_kind", "?"),
    }
    out.update(result)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
