#!/usr/bin/env python
"""Benchmark: learner update throughput on the flagship config.

Measures the compute-critical loop (SURVEY.md §3.3) exactly as the
flagship TPU config (CONFIGS row 8) runs it in production: replay resident
in device HBM (memory/device_replay.py), uniform sampling fused into the
train step, and ``steps_per_dispatch`` update steps scanned inside one
dispatched XLA program — the full DQN training step (Nature-CNN
forward+backward, Adam, target update) at the reference's default batch
size 128 on 84x84x4 uint8 states (reference utils/options.py:135,
shared_memory.py:19-24).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Baseline: the reference publishes no throughput numbers (BASELINE.md
"published frames/sec: none").  ``vs_baseline`` is computed against 250
updates/s, a representative figure for this exact workload (batch-128
Nature-DQN Adam step) on the single consumer CUDA GPU class the reference
targets — stated here explicitly since the reference gives nothing to
measure against.
"""

from __future__ import annotations

import json
import time

import numpy as np

BASELINE_UPDATES_PER_SEC = 250.0


def main() -> None:
    import jax

    from pytorch_distributed_tpu.memory.device_replay import (
        DeviceReplay, build_uniform_fused_step,
    )
    from pytorch_distributed_tpu.models import DqnCnnModel
    from pytorch_distributed_tpu.ops.losses import (
        build_dqn_train_step, init_train_state, make_optimizer,
    )
    from pytorch_distributed_tpu.utils.experience import Transition

    B, K = 128, 8  # batch per update; update steps per dispatched program
    model = DqnCnnModel(action_space=6, norm_val=255.0)
    obs = np.zeros((1, 4, 84, 84), dtype=np.uint8)
    params = model.init(jax.random.PRNGKey(0), obs)
    tx = make_optimizer(lr=1e-4)
    state = init_train_state(params, tx)
    step = build_dqn_train_step(model.apply, tx, target_model_update=250)

    # multi-chip: ring rows shard over the mesh dp axis, train state
    # replicates, and XLA inserts the gradient all-reduce over ICI
    from pytorch_distributed_tpu.memory.device_replay import round_capacity
    from pytorch_distributed_tpu.parallel.mesh import make_mesh

    n_dev = len(jax.devices())
    mesh = make_mesh() if n_dev > 1 else None
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P

        state = jax.device_put(state, NamedSharding(mesh, P()))

    # HBM ring at a size whose sampling behaves like the production 50k
    # buffer; filled once — the learner hot loop samples on device and
    # never re-transfers host pages (ingest runs between dispatches in
    # production, off this loop's critical path)
    ring = DeviceReplay(capacity=round_capacity(4096, mesh),
                        state_shape=(4, 84, 84),
                        state_dtype=np.uint8, mesh=mesh)
    rng = np.random.default_rng(0)
    C = 512
    for _ in range(ring.capacity // C):
        ring.feed_chunk(Transition(
            state0=rng.integers(0, 255, size=(C, 4, 84, 84)).astype(
                np.uint8),
            action=rng.integers(0, 6, size=C).astype(np.int32),
            reward=rng.normal(size=C).astype(np.float32),
            gamma_n=np.full(C, 0.99 ** 5, dtype=np.float32),
            state1=rng.integers(0, 255, size=(C, 4, 84, 84)).astype(
                np.uint8),
            terminal1=(rng.random(C) < 0.1).astype(np.float32)))

    fused = build_uniform_fused_step(step, B, steps_per_call=K)
    key = jax.random.PRNGKey(0)

    def keymat():
        nonlocal key
        key, sub = jax.random.split(key)
        return jax.random.split(sub, K)

    # warmup: compile + enough dispatches to settle the link (a tunnelled
    # dev chip's first dispatches pay connection setup)
    for _ in range(10):
        state, metrics = fused(state, ring.state, keymat())
    jax.block_until_ready(state.params)

    # median of independent windows: dispatch latency through a shared
    # tunnel is noisy, and one long window would let a single stall skew
    # the figure either way
    windows, iters = 5, 30
    rates = []
    for _ in range(windows):
        t0 = time.perf_counter()
        for _ in range(iters):
            state, metrics = fused(state, ring.state, keymat())
        jax.block_until_ready(state.params)
        rates.append(iters * K / (time.perf_counter() - t0))

    updates_per_sec = float(np.median(rates))
    print(json.dumps({
        "metric": "dqn_cnn_learner_updates_per_sec",
        "value": round(updates_per_sec, 2),
        "unit": f"updates/s (batch {B}, fused x{K}, HBM replay, "
                f"{n_dev} device(s), {jax.devices()[0].platform})",
        "vs_baseline": round(updates_per_sec / BASELINE_UPDATES_PER_SEC, 3),
    }))


if __name__ == "__main__":
    main()
