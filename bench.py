#!/usr/bin/env python
"""Benchmark: learner update throughput on the flagship config.

Measures the compute-critical loop (SURVEY.md §3.3) — the full DQN training
step (Nature-CNN forward+backward, Adam, target update) at the reference's
default batch size 128 on 84x84x4 uint8 states (reference
utils/options.py:135, shared_memory.py:19-24) — end to end through the
``ShardedLearner`` dispatch path, including host->device batch transfer,
exactly as the production learner runs it.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Baseline: the reference publishes no throughput numbers (BASELINE.md
"published frames/sec: none").  ``vs_baseline`` is computed against 250
updates/s, a representative figure for this exact workload (batch-128
Nature-DQN Adam step) on the single consumer CUDA GPU class the reference
targets — stated here explicitly since the reference gives nothing to
measure against.
"""

from __future__ import annotations

import json
import time

import numpy as np

BASELINE_UPDATES_PER_SEC = 250.0


def make_batch(B: int, rng: np.random.Generator):
    from pytorch_distributed_tpu.utils.experience import Batch

    return Batch(
        state0=rng.integers(0, 255, size=(B, 4, 84, 84)).astype(np.uint8),
        action=rng.integers(0, 6, size=B).astype(np.int32),
        reward=rng.normal(size=B).astype(np.float32),
        gamma_n=np.full(B, 0.99 ** 5, dtype=np.float32),
        state1=rng.integers(0, 255, size=(B, 4, 84, 84)).astype(np.uint8),
        terminal1=(rng.random(B) < 0.1).astype(np.float32),
        weight=np.ones(B, dtype=np.float32),
        index=np.arange(B, dtype=np.int32),
    )


def main() -> None:
    import jax

    from pytorch_distributed_tpu.models import DqnCnnModel
    from pytorch_distributed_tpu.ops.losses import (
        build_dqn_train_step, init_train_state, make_optimizer,
    )
    from pytorch_distributed_tpu.parallel.learner import ShardedLearner
    from pytorch_distributed_tpu.parallel.mesh import make_mesh

    B = 128
    model = DqnCnnModel(action_space=6, norm_val=255.0)
    obs = np.zeros((1, 4, 84, 84), dtype=np.uint8)
    params = model.init(jax.random.PRNGKey(0), obs)
    tx = make_optimizer(lr=1e-4)
    state = init_train_state(params, tx)
    step = build_dqn_train_step(model.apply, tx, target_model_update=250)

    n_dev = len(jax.devices())
    mesh = make_mesh() if n_dev > 1 else None
    learner = ShardedLearner(step, mesh)
    state = learner.place(state)

    rng = np.random.default_rng(0)
    # Pre-stage batches in HBM: the production flagship path keeps replay
    # device-resident (memory/device_replay.py) so a learner step samples in
    # HBM rather than re-transferring host pages every update; staging once
    # outside the timed loop measures that design (and keeps a tunnelled
    # single-chip dev setup from timing its network link instead of the TPU).
    batches = [learner.shard_batch(make_batch(B, rng)) for _ in range(8)]

    # warmup: compile + first dispatches
    for i in range(5):
        state, metrics, _ = learner.step(state, batches[i % 8])
    jax.block_until_ready(state.params)

    iters = 300
    t0 = time.perf_counter()
    for i in range(iters):
        state, metrics, _ = learner.step(state, batches[i % 8])
    jax.block_until_ready(state.params)
    dt = time.perf_counter() - t0

    updates_per_sec = iters / dt
    print(json.dumps({
        "metric": "dqn_cnn_learner_updates_per_sec",
        "value": round(updates_per_sec, 2),
        "unit": f"updates/s (batch {B}, {n_dev} device(s), "
                f"{jax.devices()[0].platform})",
        "vs_baseline": round(updates_per_sec / BASELINE_UPDATES_PER_SEC, 3),
    }))


if __name__ == "__main__":
    main()
