#!/usr/bin/env python
"""Entry point.

Equivalent of reference main.py — mode 1 trains the configured agent
topology, mode 2 tests a checkpoint — plus the CLI the reference never had
(it is edit-the-file configured, reference README.md:41-49): every CONFIGS
row is selectable and the common knobs are flags.

Examples:
    python main.py --config 4 --num-actors 8            # DQN on sim-Pong
    python main.py --config 1 --steps 2000 --backend thread
    python main.py --config 2 --mode 2 --model-file models/run.msgpack
"""

from __future__ import annotations

import argparse

from pytorch_distributed_tpu.config import CONFIGS, build_options


def parse_args(argv=None):
    p = argparse.ArgumentParser(description=__doc__,
                                formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("--config", type=int, default=0,
                   help=f"CONFIGS row 0..{len(CONFIGS) - 1} "
                        "(reference utils/options.py:10-14)")
    p.add_argument("--mode", type=int, default=1, choices=(1, 2),
                   help="1=train, 2=test (reference main.py:34,107)")
    p.add_argument("--seed", type=int, default=100)
    p.add_argument("--num-actors", type=int, default=None)
    p.add_argument("--num-envs-per-actor", type=int, default=None,
                   help="vector-env width per actor (batched inference)")
    p.add_argument("--steps", type=int, default=None,
                   help="max learner steps (reference utils/options.py:119)")
    p.add_argument("--memory-size", type=int, default=None)
    p.add_argument("--batch-size", type=int, default=None)
    p.add_argument("--nstep", type=int, default=None)
    p.add_argument("--enable-double", action="store_true")
    p.add_argument("--publish-freq", type=int, default=None,
                   help="learner steps between param publications")
    p.add_argument("--model-file", type=str, default=None,
                   help="finetune (mode 1) / test (mode 2) checkpoint")
    p.add_argument("--resume", type=str, default=None, metavar="REFS",
                   help="resume run REFS from its newest complete "
                        "checkpoint epoch (models/REFS_ckpt): train "
                        "state, replay, clock counters, best-score and "
                        "RNG continue; fails fast if no complete epoch "
                        "or legacy snapshot exists")
    p.add_argument("--backend", choices=("process", "thread"),
                   default="process")
    p.add_argument("--no-tensorboard", action="store_true")
    p.add_argument("--render", action="store_true",
                   help="dump eval frames (tester in mode 2, evaluator in "
                        "mode 1) as PNGs under the run's log dir (headless "
                        "stand-in for the reference's cv2.imshow display)")
    p.add_argument("--dp-size", type=int, default=-1,
                   help="learner mesh data-parallel width (-1 = all devices)")
    p.add_argument("--set", action="append", default=[], metavar="K=V",
                   help="any Options override, e.g. --set seq_len=16 "
                        "--set lr=2e-3 (repeatable)")
    return p.parse_args(argv)


def options_from_args(args):
    from pytorch_distributed_tpu.config import parse_set_overrides

    overrides = dict(mode=args.mode, seed=args.seed)
    # --set wins over flag defaults (and may name the same keys)
    overrides.update(parse_set_overrides(args.set))
    if args.num_actors is not None:
        overrides["num_actors"] = args.num_actors
    if args.num_envs_per_actor is not None:
        overrides["num_envs_per_actor"] = args.num_envs_per_actor
    if args.steps is not None:
        overrides["steps"] = args.steps
    if args.memory_size is not None:
        overrides["memory_size"] = args.memory_size
    if args.batch_size is not None:
        overrides["batch_size"] = args.batch_size
    if args.nstep is not None:
        overrides["nstep"] = args.nstep
    if args.enable_double:
        overrides["enable_double"] = True
    if args.publish_freq is not None:
        overrides["param_publish_freq"] = args.publish_freq
    if args.model_file is not None:
        overrides["model_file"] = args.model_file
    if args.resume is not None:
        overrides["refs"] = args.resume
        overrides["resume"] = "must"
    if args.no_tensorboard:
        overrides["visualize"] = False
    if args.render:
        overrides["render"] = True
    if args.dp_size != -1:
        overrides["dp_size"] = args.dp_size
    return build_options(config=args.config, **overrides)


def main(argv=None):
    args = parse_args(argv)
    opt = options_from_args(args)

    from pytorch_distributed_tpu.utils.helpers import enable_compile_cache

    enable_compile_cache()

    from pytorch_distributed_tpu import runtime

    if opt.mode == 1:
        print(f"[main] training config {args.config} "
              f"({opt.agent_type}/{opt.env_type}/{opt.game}/"
              f"{opt.memory_type}/{opt.model_type}) -> {opt.refs}")
        runtime.train(opt, backend=args.backend)
    else:
        runtime.test(opt)


if __name__ == "__main__":
    main()
