"""Pipeline parallelism: the DTQN block stack staged over the mesh ``pp``
axis with a GPipe microbatch schedule.

No reference equivalent (SURVEY.md §2 "parallelism strategies" lists
pipeline parallelism as NOT present in the single-GPU reference) — this
is the capability that makes the mesh's ``pp`` axis real for the
stacked-block DTQN (models/dtqn_pipeline.py).

Design — the SPMD pipeline pattern, expressed as one ``shard_map``:

- the model's stacked block params (leading ``depth`` axis) shard over
  ``pp``; each of the S stages holds ``depth / S`` contiguous blocks and
  runs them as a local ``lax.scan`` (same ``block_forward`` math as the
  single-device path);
- the dp-sharded batch splits into M microbatches; a ``lax.scan`` over
  ``M + S - 1`` ticks drives the classic GPipe schedule: stage 0 injects
  microbatch t, every stage applies its blocks, activations hop to the
  next stage via one ``jax.lax.ppermute`` over ICI, and the last stage
  banks its finished microbatch.  Warm-up/drain bubbles execute garbage
  that the injection/banking masks ignore — the standard (S-1)/M
  overhead;
- the banked output lives on the last stage only, so one masked ``psum``
  over pp replicates it (cheap: done once, after the loop);
- the whole thing is differentiable (scan + ppermute + psum all have
  transposes), so ``jax.grad`` through the pipelined apply yields the
  backward pipeline automatically — with stage grads landing exactly on
  the ``pp`` shard that owns the stage's params.

Embedding and the Q head run OUTSIDE the shard_map (replicated compute;
they are a few percent of the FLOPs — cheaper than two more stages).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pytorch_distributed_tpu.models.dtqn_pipeline import block_forward
from pytorch_distributed_tpu.utils.helpers import shard_map


def pipeline_blocks(stacked: Any, x: jnp.ndarray, *, mesh: Mesh,
                    heads: int, num_microbatches: int) -> jnp.ndarray:
    """Run the stacked blocks over ``x`` (B, T, D) with the layer axis
    sharded over ``pp`` and the batch over ``dp``."""
    S = mesh.shape["pp"]
    M = num_microbatches
    perm = [(i, (i + 1) % S) for i in range(S)]

    @partial(shard_map, mesh=mesh,
             in_specs=(jax.tree_util.tree_map(lambda _: P("pp"), stacked),
                       P("dp")),
             out_specs=P("dp"), check_vma=False)
    def run(local_stack, x_loc):
        idx = jax.lax.axis_index("pp")
        Bl, T, D = x_loc.shape
        assert Bl % M == 0, (
            f"per-dp-shard batch {Bl} must divide into {M} microbatches")
        mb = Bl // M
        micro = x_loc.reshape(M, mb, T, D)

        def stage(h):
            def body(hh, layer):
                return block_forward(layer, hh, heads=heads), None

            out, _ = jax.lax.scan(body, h, local_stack)
            return out

        def tick(carry, t):
            act, banked = carry
            inj = jax.lax.dynamic_index_in_dim(
                micro, jnp.clip(t, 0, M - 1), 0, keepdims=False)
            y = stage(jnp.where(idx == 0, inj, act))
            ot = t - (S - 1)
            write = jnp.logical_and(ot >= 0, ot < M)
            upd = jax.lax.dynamic_update_index_in_dim(
                banked, y, jnp.clip(ot, 0, M - 1), 0)
            banked = jnp.where(write, upd, banked)
            act = jax.lax.ppermute(y, "pp", perm)
            return (act, banked), None

        zeros = jnp.zeros((mb, T, D), x_loc.dtype)
        banked0 = jnp.zeros((M, mb, T, D), x_loc.dtype)
        (_, banked), _ = jax.lax.scan(tick, (zeros, banked0),
                                      jnp.arange(M + S - 1))
        # only the last stage banked real outputs; replicate over pp
        banked = jax.lax.psum(
            jnp.where(idx == S - 1, banked, jnp.zeros_like(banked)), "pp")
        return banked.reshape(Bl, T, D)

    return run(stacked, x)


def pipelined_window_apply(model, mesh: Mesh,
                           num_microbatches: int) -> Callable:
    """The learner-side ``window_apply`` for a DtqnPipelineModel on a
    mesh with pp > 1: embed (replicated) -> pipelined block stack ->
    head (replicated).  Same (params, obs_seq) -> (B, T, A) contract as
    ``model.window_q``."""
    S = mesh.shape["pp"]
    assert model.depth % S == 0, (
        f"depth {model.depth} must divide over pp={S} stages")

    def apply(params, obs_seq):
        x = model.apply(params, obs_seq, method=model.embed)
        y = pipeline_blocks(params["params"]["blocks"], x, mesh=mesh,
                            heads=model.heads,
                            num_microbatches=num_microbatches)
        return model.apply(params, y, method=model.head)

    return apply


def pipeline_state_shardings(state: Any, mesh: Mesh) -> Any:
    """A NamedSharding pytree for a DtqnPipelineModel TrainState: every
    leaf under a ``blocks`` subtree shards its leading (layer) axis over
    ``pp``; everything else replicates.  Params, target params and Adam
    moments share paths, so one rule shards all three."""

    from pytorch_distributed_tpu.parallel.tensor_parallel import (
        _path_strings,
    )

    def spec(path, leaf):
        if "blocks" in _path_strings(path) and getattr(leaf, "ndim", 0) >= 1:
            return P("pp", *([None] * (leaf.ndim - 1)))
        return P()

    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: NamedSharding(mesh, spec(path, leaf)), state)
