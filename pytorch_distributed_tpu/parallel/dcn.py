"""DCN transport: cross-host experience ingestion + parameter publication.

No reference equivalent — the reference's entire communication backend is
single-machine ``torch.multiprocessing`` shared memory (reference main.py:13,
core/memories/shared_memory.py:30-37; SURVEY.md §2 "distributed communication
backend").  On a TPU pod the learner host owns the mesh and remote actor
hosts cannot share pages with it, so the three shared-state mechanisms the
reference relies on become one explicit wire protocol over DCN
(host-to-host Ethernet/ICI-external network):

- **experience in** — actors stream fixed-schema transition chunks to the
  learner host's ``DcnGateway``, which forwards them into the same
  single-owner spawn queue the local feeders use (memory/feeder.py,
  memory/device_replay.py): the learner drains local and remote experience
  through one path.
- **weights out** — the gateway answers versioned parameter requests from
  the learner's ``ParamStore`` snapshot; remote actors poll on their
  ``actor_sync_freq`` cadence exactly like local ones (reference
  dqn_actor.py:176-178), with staleness bounded by cadence + one RTT.
- **clocks/stats** — the global learner step rides back on every reply
  (actors need it only for termination, reference dqn_actor.py:62), and
  actor-step/stat increments are batched client-side so the hot loop never
  blocks on the network.

Wire format: 1-byte frame type + 8-byte big-endian payload length, then the
payload — JSON for control frames, ``np.savez`` for experience chunks, raw
fp32 for parameter snapshots.  No pickle on the wire: frames are
schema-checked, so a gateway never executes peer-controlled code.

Failure model (the session layer; drills in tests/test_chaos.py and
tools/chaos_soak.py, policy knobs via ``DCN_*`` env vars):

- **Transient disconnects are transparent.**  A send/recv error inside
  ``DcnClient._request`` redials with exponential backoff, re-HELLOs with
  a bumped **incarnation number**, and retransmits the one unacknowledged
  frame — experience delivery is at-least-once (a chunk whose ack was
  lost may be fed twice; replay sampling tolerates duplicates, lost
  chunks it cannot).  Retransmitted T_TICKs, whose double-count would
  skew the fleet step count and stats, are deduplicated gateway-side by
  sequence number (the one residual window: an ack lost across a
  gateway RESTART, which forgets the dedup map).  A reconnect that exhausts its budget
  (``DCN_RECONNECT_TIMEOUT``) is terminal: the client raises
  ``DcnDisconnected`` and latches ``disconnected`` so the worker exits
  **nonzero** and the supervision layer (utils/supervision.RestartBudget)
  engages — never a silent "run complete".
- **Slot fencing.**  The gateway keys each actor slot by incarnation; a
  HELLO carrying a higher incarnation for an already-held slot evicts the
  stale predecessor connection (the half-open leftover of a partition)
  instead of bouncing off "slot already connected".  Equal/lower
  incarnations are refused — that is a genuine duplicate actor, the
  config error that silently skews the fleet-wide Ape-X epsilon schedule.
- **Liveness vs backpressure.**  The client pings (T_PING) after
  ``DCN_HEARTBEAT_INTERVAL`` of idleness and bounds every reply wait with
  ``DCN_REPLY_DEADLINE``; the gateway drops connections idle longer than
  ``DCN_IDLE_DEADLINE`` (> the ping interval), freeing their slots.  The
  deadlines are deliberately long relative to ingest stalls: a brief
  stall (learner compile) rides under them, while a frozen or
  partitioned peer trips the deadline and enters the reconnect path
  instead of hanging forever on the old ``settimeout(None)`` socket.
- **Overload degrades, never deadlocks (ISSUE 11, utils/flow.py).**
  Sustained pressure (full spawn queue, slow learner ingest) no longer
  stalls the fleet through blocking puts: the gateway's overload
  governor (healthy → throttled → shedding, surfaced on T_STATUS and
  alerted via DEFAULT_RULES) sizes per-slot send credits onto every
  T_CLOCK ack; a creditless client parks chunks in a bounded
  drop-oldest ring (newest experience wins, every drop counted +
  provenance-stamped) while its T_PING heartbeats keep flowing — so a
  throttled actor never reads as dead, is never reaped by the idle
  deadline, and never blocks its own rollout loop.  Per-slot token
  buckets meter the throttled grants (one runaway actor drains its own
  bucket, not its neighbours'), and sustained shedding climbs a
  brownout ladder — telemetry pushes first, then trace sampling, then
  (tier 3, for credit-ignoring peers) oldest experience at the
  gateway's one declared shed point.  Conservation is checkable live:
  minted = ingested + dropped + quarantined (+ still-buffered), from
  the counters on the STATUS ``flow`` block.  Drilled by
  ``chaos_soak --flood`` / ``--slow-learner-ingest`` / ``--slow-slot``.
- **"Learner said stop" and "connection lost" are distinct states**:
  ``DcnClient.stop`` is set only by a T_CLOCK reply carrying
  ``stop: true``; ``DcnClient.disconnected`` only by a terminal session
  loss.  fleet.py maps them to exit codes 0 / EXIT_DISCONNECTED.
- **Learner replicas are leased, not sessioned (ISSUE 15,
  ReplicaRegistry below).**  N data-parallel learner replicas hold
  renewable leases with MONOTONIC generation numbers; a missed lease
  expires the replica and fences its stragglers (stale-generation
  gradient/priority write-backs are counted rejects, never applied —
  the slot-fencing contract lifted to the learner plane).  The gradient
  exchange is a generation-stamped allreduce round that reconfigures on
  membership change: a dead replica's round completes over the
  surviving set within one lease window (a HUNG-but-renewing replica is
  expelled by the round-stall rule — leases prove liveness, rounds
  prove progress), and an N=1 completion is bit-identical to the solo
  learner.  Rejoin = re-lease at a new generation + sync from the
  join-barrier checkpoint epoch.  Drilled by ``chaos_soak
  --kill-replica / --hang-replica / --rejoin`` and the
  tests/test_replicas.py parity oracle.

Client-side adapters (``RemoteMemory``, ``RemoteParamStore``,
``RemoteClock``, ``RemoteStats``) present the exact surfaces the actor
harness binds to (agents/actor.py), so ``run_dqn_actor``/``run_ddpg_actor``
run unmodified on a remote host.

Observability (utils/tracing.py, utils/flight_recorder.py): EXP frames
carry the chunk's trace id + birth wall-clock as savez columns, so the
gateway records the actor→gateway wire hop against the same trace the
learner-side drain continues; session transitions (claims, fences,
releases, reconnects, terminal losses) land in per-role flight-recorder
rings dumped to ``blackbox/`` on abnormal exits.  The ``T_STATUS`` verb
answers a live health snapshot — slot/incarnation/heartbeat-age states
plus topology-provided replay/queue/budget/rate fields — to sessionless
probes (``fetch_status``; rendered by tools/fleet_top.py).
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import socket
import struct
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

import numpy as np

from pytorch_distributed_tpu.agents.param_store import ParamStore
from pytorch_distributed_tpu.memory.feeder import QueueFeeder
from pytorch_distributed_tpu.utils import bandwidth, experience, \
    flight_recorder, flow, tracing
from pytorch_distributed_tpu.utils.experience import Transition
from pytorch_distributed_tpu.utils.faults import FaultInjector

# ---------------------------------------------------------------------------
# framing
# ---------------------------------------------------------------------------

_HDR = struct.Struct("!BQ")

T_HELLO = 1    # JSON {role, process_ind, incarnation} -> T_CLOCK
T_EXP = 2      # savez transition chunk              -> T_CLOCK
T_GETP = 3     # !Q min_version                      -> T_PARAMS
T_PARAMS = 4   # !Q version + raw fp32 (empty = no newer snapshot)
T_CLOCK = 5    # JSON {learner_step, stop}
T_TICK = 6     # JSON {actor_steps, stats?, seq?}    -> T_CLOCK
T_BYE = 7      # empty                               -> (close)
T_PING = 8     # empty heartbeat                     -> T_CLOCK
T_STATUS = 9   # empty -> T_STATUS JSON health snapshot (no HELLO needed)
T_PROFILE = 10  # JSON {seconds, label?, role?} -> T_PROFILE JSON reply
#                (sessionless like T_STATUS: triggers a bounded XLA
#                profiler window on the learner host and reports the
#                trace directory back — tools/fleet_top.py --profile)
T_METRICS = 11  # JSON {rows, offset?, host?} -> T_METRICS JSON reply
#                (sessionless like T_STATUS, outside the fault plane:
#                fleet hosts push batched scalar-window deltas into the
#                learner-host aggregator on the stats cadence; the
#                reply's ``wall`` lets the pusher estimate its clock
#                offset NTP-style — utils/telemetry.MetricsPusher)
# ---- the elastic multi-learner replica plane (ISSUE 15).  Sessionless-
# adjacent: no actor-slot HELLO — membership is the LEASE table below,
# riding the same incarnation-fencing idea as slot claims.  Outside the
# gateway's wire fault plane like T_STATUS (replica drills inject at the
# replica driver through REPLICA_FAULTS — utils/faults.py — where a
# kill/hang is the real failure mode; routing these frames through the
# wire injector would also shift every existing drill's frame schedule).
T_RLEASE = 12   # JSON {action, replica, incarnation|generation, ...}
#                -> JSON reply: lease acquire/renew/release/activate/
#                epoch/status against the gateway's ReplicaRegistry
T_RGRAD = 13    # savez round submission (generation-stamped gradient +
#                PER write-back) -> savez reply (reduced gradient,
#                merged write-backs, surviving membership); BLOCKS the
#                serve thread until the round completes or fences
T_RPRIO = 14    # savez out-of-round |TD| priority write-back -> JSON
#                reply; stale-generation writes are counted rejects
#                (last-generation-wins fencing: a zombie replica can
#                never resurrect stale priorities)
T_SYNC = 15     # JSON {since} -> JSON {term, seq, base_seq, records,
#                wall}: the gateway HA control-plane stream (ISSUE 16).
#                Sessionless like T_STATUS and outside the wire fault
#                plane: the warm standby pulls journal records past its
#                applied offset on its sync cadence; records are
#                ABSOLUTE state snapshots (idempotent to re-apply), so
#                a standby that restarts mid-sync can resync from any
#                offset without double-counting ledger entries.  Only
#                an HA primary answers with records; everyone else
#                replies with an ``error`` key — the verb is never sent
#                unless the HA plane is on, keeping the pre-HA wire
#                byte-identical.
# --- sharded-replay verbs (ISSUE 20): sessionless-adjacent like the
# replica verbs, OUTSIDE the wire fault plane (the shard fault plane is
# lease expiry + generation fencing in memory/shard_plane.py — a
# kill/hang of the shard HOST is the real failure mode).  None of these
# frames is ever sent unless ShardParams.shards > 1, keeping the
# pre-shard wire byte-identical.  All codecs live in shard_plane.py;
# the gateway dispatches to duck-typed ``handle_*`` methods on its
# ``shards=`` object (a LocalShard on shard hosts, a ShardRegistry on
# the coordinator) so this module never imports the plane.
T_SSAMPLE = 16  # savez {meta=[shard, generation], values?} -> savez
#                mass report (+ sampled rows when values were sent):
#                the two-level sample's shard-local leg; empty values
#                doubles as the level-1 mass poll
T_SMASS = 17    # JSON shard membership verbs against the coordinator's
#                ShardRegistry (acquire/renew/release/activate/status)
#                or a mass poll against a shard host
T_SPRIO = 18    # savez {meta=[shard, generation], pidx, ptd} -> JSON
#                reply; stale-generation write-backs are counted
#                rejects (the T_RPRIO contract on the shard plane)

_MAX_FRAME = 1 << 31  # 2 GiB — far above any chunk; rejects garbage lengths

# verb names for the bandwidth X-ray (utils/bandwidth.py): registered
# here so the accountant never imports this module (no import cycle)
bandwidth.register_verbs({
    T_HELLO: "hello", T_EXP: "exp", T_GETP: "getp", T_PARAMS: "params",
    T_CLOCK: "clock", T_TICK: "tick", T_BYE: "bye", T_PING: "ping",
    T_STATUS: "status", T_PROFILE: "profile", T_METRICS: "metrics",
    T_RLEASE: "rlease", T_RGRAD: "rgrad", T_RPRIO: "rprio",
    T_SYNC: "sync", T_SSAMPLE: "ssample", T_SMASS: "smass",
    T_SPRIO: "sprio",
})


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


def _send_frame(sock: socket.socket, ftype: int, payload: bytes) -> None:
    # stamp BEFORE sendall so the tx note happens-before the peer's
    # reply can complete an RPC — a reader polling the accountant after
    # a synchronous round-trip must never observe the request counted
    # but the reply missing (byte-exact means exact at every quiescent
    # point, not eventually)
    bandwidth.note_frame(sock, ftype, _HDR.size + len(payload), "tx")
    sock.sendall(_HDR.pack(ftype, len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        part = sock.recv(n - len(buf))
        if not part:
            raise ConnectionError("peer closed")
        buf.extend(part)
    return bytes(buf)


def _recv_frame(sock: socket.socket) -> Tuple[int, bytes]:
    ftype, length = _HDR.unpack(_recv_exact(sock, _HDR.size))
    if length > _MAX_FRAME:
        raise ConnectionError(f"oversized frame: {length}")
    payload = _recv_exact(sock, length) if length else b""
    bandwidth.note_frame(sock, ftype, _HDR.size + length, "rx")
    return ftype, payload


# ---------------------------------------------------------------------------
# experience chunk encoding: columnar, no pickle
# ---------------------------------------------------------------------------

# the six replay columns come from the ONE schema declaration
# (utils.experience.REPLAY_FIELDS) — a re-typed copy here would drift
# silently when a column lands (apexlint schema-contract)
_FIELDS = experience.REPLAY_FIELDS

# Everything encode_chunk may put on the wire / decode_chunk may read:
# the declared wire schema apexlint checks the codec against.  Extending
# the wire format means extending this tuple FIRST (and keeping decode
# tolerant of peers that don't ship the new column yet).
WIRE_COLUMNS = experience.REPLAY_FIELDS + (
    "priority", "priority_ok", "prov", "trace_id", "trace_born")


def encode_chunk(items: List[Tuple[Transition, Optional[float]]]) -> bytes:
    """Stack a chunk of (transition, priority) into one savez payload.
    ``priority`` None (uniform / new-sample-max semantics) travels as an
    explicit ``priority_ok`` validity column — NOT as a NaN sentinel:
    a genuine NaN priority from a diverged actor used to silently decode
    as None ("give it the new-sample max"), the exact corruption the
    ingest quarantine exists to catch; with the validity column a NaN
    survives the wire as the NaN it is and is quarantined at the
    gateway.  (Decode still accepts sentinel-era frames from old peers.)
    A ``tracing.TracedChunk`` carries its trace id + birth wall-clock as
    two extra columns (still no pickle on the wire), so the trace minted
    at the actor survives the hop to the gateway."""
    cols = {f: np.stack([np.asarray(getattr(t, f)) for t, _ in items])
            for f in _FIELDS}
    cols["priority"] = np.array(
        [np.nan if p is None else float(p) for _, p in items],
        dtype=np.float32)
    cols["priority_ok"] = np.array([p is not None for _, p in items],
                                   dtype=np.bool_)
    prov = experience.stack_prov(items)
    if (prov >= 0).any():
        # provenance rides as one (n, 4) int64 column (ISSUE 8); rows
        # minted without provenance are the explicit -1 sentinel.  Only
        # shipped when at least one row carries it, so legacy peers and
        # synthetic chunks keep their exact wire bytes.
        cols["prov"] = prov
    if isinstance(items, tracing.TracedChunk):
        cols["trace_id"] = np.array([items.trace_id], dtype=np.uint64)
        cols["trace_born"] = np.array([items.born], dtype=np.float64)
    out = io.BytesIO()
    np.savez(out, **cols)
    return out.getvalue()


def decode_chunk(payload: bytes
                 ) -> List[Tuple[Transition, Optional[float]]]:
    """Decode + schema-validate one EXP payload.

    Raises ``ValueError`` on a WELL-FRAMED but malformed chunk — missing
    columns, truncated/mismatched column lengths, non-numeric dtypes —
    which the gateway answers with a counted reject + ack (the PEER is
    malformed; retransmitting the same bytes can never help).  Bytes
    ``np.load`` itself cannot parse raise ``ConnectionError`` instead —
    wire-level corruption stays on the drop-connection path, where the
    client's retransmit IS the cure (its copy is clean)."""
    try:
        with np.load(io.BytesIO(payload)) as z:
            cols = {k: z[k] for k in z.files}
    except Exception as e:
        raise ConnectionError(f"unparseable EXP payload: {e!r}")
    missing = [f for f in _FIELDS + ("priority",) if f not in cols]
    if missing:
        raise ValueError(f"malformed chunk: missing columns {missing}")
    pr = cols["priority"]
    if pr.ndim != 1 or pr.dtype.kind != "f":
        raise ValueError(
            f"malformed chunk: priority must be a 1-D float column "
            f"(got ndim={pr.ndim}, dtype={pr.dtype})")
    n = len(pr)
    for f in _FIELDS:
        c = cols[f]
        if c.ndim < 1 or len(c) != n:
            raise ValueError(
                f"malformed chunk: column {f} is "
                f"{'scalar' if c.ndim < 1 else f'length {len(c)}'}, "
                f"want length {n}")
        if c.dtype.kind not in "fiub":
            raise ValueError(
                f"malformed chunk: column {f} dtype {c.dtype} "
                f"is not numeric")
    ok = cols.get("priority_ok")
    if ok is not None and (ok.ndim != 1 or len(ok) != n):
        raise ValueError("malformed chunk: priority_ok length mismatch")
    pv = cols.get("prov")
    if pv is not None and (pv.ndim != 2 or len(pv) != n
                           or pv.shape[1] != len(experience.PROV_FIELDS)
                           or pv.dtype.kind not in "iu"):
        raise ValueError("malformed chunk: prov column must be "
                         f"(n, {len(experience.PROV_FIELDS)}) integer "
                         f"(got shape {pv.shape}, dtype {pv.dtype})")
    items: List[Tuple[Transition, Optional[float]]] = []
    for i in range(n):
        t = Transition(*(cols[f][i] for f in _FIELDS))
        if pv is not None and pv[i][0] >= 0:
            t = t._replace(prov=np.asarray(pv[i],
                                           experience.PROV_DTYPE))
        p = pr[i]
        if ok is not None:
            valid = bool(ok[i])
        else:  # sentinel-era peer: NaN meant None on the old wire
            valid = not np.isnan(p)
        items.append((t, float(p) if valid else None))
    if "trace_id" in cols:  # re-wrap: the trace continues past the wire
        return tracing.TracedChunk(items,
                                   trace_id=int(cols["trace_id"][0]),
                                   born=float(cols["trace_born"][0]))
    return items


# ---------------------------------------------------------------------------
# elastic multi-learner replica plane (ISSUE 15): lease-fenced membership
# + fault-tolerant, generation-stamped gradient exchange
# ---------------------------------------------------------------------------

def resolve_replica(rp=None):
    """ReplicaParams + ``TPU_APEX_REPLICA_<FIELD>`` env overrides — the
    same override-by-env contract as the health/perf/flow planes
    (flow.resolve_flow is the template).  Returns a NEW instance; the
    input is never mutated (Options rides spawn pickles)."""
    import dataclasses

    from pytorch_distributed_tpu.config import ReplicaParams

    if rp is None:
        rp = ReplicaParams()
    changes: Dict[str, Any] = {}
    for f in dataclasses.fields(rp):
        raw = os.environ.get("TPU_APEX_REPLICA_" + f.name.upper())
        if raw is None:
            continue
        cur = getattr(rp, f.name)
        if isinstance(cur, bool):
            changes[f.name] = raw.strip().lower() not in (
                "0", "false", "off", "no", "")
        elif isinstance(cur, int) and not isinstance(cur, bool):
            changes[f.name] = int(float(raw))
        elif isinstance(cur, float):
            changes[f.name] = float(raw)
        else:
            changes[f.name] = raw.strip()
    return dataclasses.replace(rp, **changes) if changes else rp


def export_replica_env(rp) -> None:
    """Export a RESOLVED ReplicaParams into the environment so spawn
    children resolve the same plane the topology configured
    programmatically.  setdefault: an operator's explicit env wins."""
    import dataclasses

    for f in dataclasses.fields(rp):
        val = getattr(rp, f.name)
        if val != f.default:
            os.environ.setdefault("TPU_APEX_REPLICA_" + f.name.upper(),
                                  str(val))


# ---------------------------------------------------------------------------
# gateway high availability (ISSUE 16): durable control plane + warm-standby
# failover with fenced promotion
# ---------------------------------------------------------------------------

def resolve_gateway(gp=None):
    """GatewayParams + ``TPU_APEX_GATEWAY_<FIELD>`` env overrides — the
    same override-by-env contract as the health/perf/flow/replica
    planes.  Returns a NEW instance; the input is never mutated."""
    import dataclasses

    from pytorch_distributed_tpu.config import GatewayParams

    if gp is None:
        gp = GatewayParams()
    changes: Dict[str, Any] = {}
    for f in dataclasses.fields(gp):
        raw = os.environ.get("TPU_APEX_GATEWAY_" + f.name.upper())
        if raw is None:
            continue
        cur = getattr(gp, f.name)
        if isinstance(cur, bool):
            changes[f.name] = raw.strip().lower() not in (
                "0", "false", "off", "no", "")
        elif isinstance(cur, int) and not isinstance(cur, bool):
            changes[f.name] = int(float(raw))
        elif isinstance(cur, float):
            changes[f.name] = float(raw)
        else:
            changes[f.name] = raw.strip()
    return dataclasses.replace(gp, **changes) if changes else gp


def export_gateway_env(gp) -> None:
    """Export a RESOLVED GatewayParams into the environment so spawn
    children (remote actor mains, the standby runner) resolve the same
    HA plane the topology configured.  setdefault: an operator's
    explicit env wins."""
    import dataclasses

    for f in dataclasses.fields(gp):
        val = getattr(gp, f.name)
        if val != f.default:
            os.environ.setdefault("TPU_APEX_GATEWAY_" + f.name.upper(),
                                  str(val))


def parse_endpoints(spec) -> List[Tuple[str, int]]:
    """``host:port,host:port`` (or a ready-made address/list) -> ordered
    endpoint list for DcnClient failover dialing.  IPv6 is out of scope
    for the fleet CLI (matching fleet.py's coordinator parsing)."""
    if not spec:
        return []
    if isinstance(spec, (list, tuple)):
        if (len(spec) == 2 and isinstance(spec[0], str)
                and isinstance(spec[1], int)):
            return [(spec[0], int(spec[1]))]  # a single ("host", port)
        out: List[Tuple[str, int]] = []
        for item in spec:
            if isinstance(item, str):
                out.extend(parse_endpoints(item))
            else:
                h, p = item
                out.append((h, int(p)))
        return out
    out = []
    for part in str(spec).split(","):
        part = part.strip()
        if not part:
            continue
        host, _, port = part.rpartition(":")
        out.append((host or "127.0.0.1", int(port)))
    return out


def _rec_digest(seq: int, kind: str, data: Dict[str, Any]) -> str:
    """Per-record WAL digest: seq|kind|canonical-json, first 12 hex of
    sha256 — enough to catch torn/bit-rotted lines, cheap to verify on
    every recovery scan."""
    blob = f"{seq}|{kind}|{json.dumps(data, sort_keys=True)}"
    return hashlib.sha256(blob.encode()).hexdigest()[:12]


class GatewayJournal:
    """Append-only fsynced WAL for the gateway's mutable control state
    (ISSUE 16) under ``{log_dir}/gateway/`` — the same shared-storage,
    atomic-rename + digest discipline as the PR-2 checkpoint epochs.

    Layout::

        {log_dir}/gateway/TERM.json          # {"term", "wall", "sha"}
        {log_dir}/gateway/wal-<term>.jsonl   # one JSON record per line
        {log_dir}/gateway/standby/wal-0.jsonl  # standby's applied copy

    ``TERM.json`` is the fencing substrate: it is only ever replaced
    atomically (tmp + ``os.replace``) with a strictly larger term, and
    every HA gateway re-reads it (mtime-gated) before applying writes —
    a resurrected primary whose term is below the on-disk term fences
    itself.  Each WAL line is ``{"seq", "kind", "data", "sha"}`` with a
    per-record digest; recovery scans the newest term's file, SKIPS a
    torn/undigestable trailing record (the ``read_scalars`` discipline)
    and falls back to a counted clean slate on an empty/corrupt journal
    — torn state is never fatal, only warm-start warmth is lost.
    Records carry ABSOLUTE values (cumulative ledgers, incarnation and
    seq high-waters), so applying any suffix — or the whole file twice —
    is idempotent by construction."""

    def __init__(self, root: str, standby: bool = False):
        self.dir = os.path.join(root, "gateway")
        if standby:
            # the standby journals its APPLIED copy of the stream in a
            # subdir so it never touches the primary's term WAL; on the
            # shared log_dir both survive either host
            self.dir = os.path.join(self.dir, "standby")
        os.makedirs(self.dir, exist_ok=True)
        self._standby = standby
        self._lock = threading.Lock()
        self._fh = None
        self.term = 0          # term this journal is appending under
        self.seq = 0           # last appended/applied record seq
        self.base_seq = 0      # first seq held in the in-memory tail
        self.appends = 0
        self.recover_warnings = 0
        # in-memory tail served over T_SYNC; bounded — a standby that
        # falls further behind than this gets base_seq back and re-pulls
        # from there (records are idempotent, so the overlap is safe)
        self._tail: List[Dict[str, Any]] = []
        self._tail_max = 65536

    # -- term file (the fencing substrate) --------------------------------

    def _term_path(self) -> str:
        # the term file always lives at the SHARED top-level gateway dir
        # (even for the standby journal, which writes it on promotion)
        d = os.path.dirname(self.dir) if self._standby else self.dir
        return os.path.join(d, "TERM.json")

    def read_term(self) -> int:
        """Digest-checked read of the on-disk term; torn/corrupt/missing
        reads as 0 with a counted warning (never fatal — a gateway that
        cannot prove a HIGHER term exists keeps leading)."""
        try:
            with open(self._term_path()) as fh:
                doc = json.load(fh)
            term = int(doc["term"])
            want = _rec_digest(term, "term", {"wall": doc["wall"]})
            if doc.get("sha") != want:
                self.recover_warnings += 1
                return 0
            return term
        except FileNotFoundError:
            return 0
        except Exception:
            self.recover_warnings += 1
            return 0

    def write_term(self, term: int) -> None:
        """Atomically publish a new (strictly larger) term — tmp +
        ``os.replace``, digest-stamped, fsynced before the rename so a
        torn publish can never read as valid."""
        path = self._term_path()
        wall = time.time()
        doc = {"term": int(term), "wall": wall,
               "sha": _rec_digest(int(term), "term", {"wall": wall})}
        tmp = path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(doc, fh)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)

    # -- the WAL itself ---------------------------------------------------

    def _wal_path(self, term: int) -> str:
        return os.path.join(self.dir, f"wal-{term:08d}.jsonl")

    def start_term(self, term: int) -> None:
        """Open (append mode) the WAL for ``term``; subsequent appends
        land there.  seq continues from whatever recover() found so the
        (term, seq) pair is globally monotonic."""
        with self._lock:
            if self._fh is not None:
                self._fh.close()
            self.term = int(term)
            self._fh = open(self._wal_path(self.term), "a")

    def append(self, kind: str, data: Dict[str, Any]) -> int:
        """fsynced append of one control record; returns its seq.
        Raises OSError if the backing store is gone — the gateway treats
        a failed append as self-fencing (can't journal => can't lead)."""
        with self._lock:
            if self._fh is None:
                raise OSError("journal not open")
            self.seq += 1
            rec = {"seq": self.seq, "kind": kind, "data": data,
                   "sha": _rec_digest(self.seq, kind, data)}
            self._fh.write(json.dumps(rec, sort_keys=True) + "\n")
            self._fh.flush()
            os.fsync(self._fh.fileno())
            self.appends += 1
            self._tail.append(rec)
            if len(self._tail) > self._tail_max:
                drop = len(self._tail) - self._tail_max
                del self._tail[:drop]
            self.base_seq = self._tail[0]["seq"] if self._tail else self.seq
            return self.seq

    def apply(self, rec: Dict[str, Any]) -> bool:
        """Standby side: persist one pulled record verbatim (same seq
        numbering as the primary) and advance the applied offset.
        Already-applied seqs are ignored — the resync overlap after a
        standby restart is a no-op, not a double-count."""
        seq = int(rec.get("seq", 0))
        with self._lock:
            if seq <= self.seq:
                return False
            if self._fh is None:
                self._fh = open(self._wal_path(0), "a")
            self.seq = seq
            self._fh.write(json.dumps(rec, sort_keys=True) + "\n")
            self._fh.flush()
            os.fsync(self._fh.fileno())
            self.appends += 1
            self._tail.append(rec)
            if len(self._tail) > self._tail_max:
                del self._tail[:len(self._tail) - self._tail_max]
            self.base_seq = self._tail[0]["seq"] if self._tail else self.seq
            return True

    def records_since(self, since: int) -> Tuple[int, List[Dict[str, Any]]]:
        """(base_seq, records with seq > since) from the in-memory tail —
        the T_SYNC reply body.  A ``since`` below base_seq gets the whole
        tail (idempotent records make the overlap harmless)."""
        with self._lock:
            recs = [r for r in self._tail if r["seq"] > since]
            return self.base_seq, recs

    def recover(self) -> Tuple[int, List[Dict[str, Any]]]:
        """Scan this journal dir newest-term-first and return
        ``(term, records)`` of the first file that yields any valid
        records — digest-verifying every line, skipping a torn or
        undigestable TRAILING record, and counting (never raising) a
        clean-slate fallback on empty/corrupt journals."""
        try:
            names = sorted((n for n in os.listdir(self.dir)
                            if n.startswith("wal-")
                            and n.endswith(".jsonl")), reverse=True)
        except OSError:
            self.recover_warnings += 1
            return 0, []
        top_term = max((int(n[len("wal-"):-len(".jsonl")]) for n in names),
                       default=0)
        for name in names:
            recs: List[Dict[str, Any]] = []
            torn = 0
            try:
                with open(os.path.join(self.dir, name)) as fh:
                    lines = fh.read().split("\n")
            except OSError:
                self.recover_warnings += 1
                continue
            for line in lines:
                if not line.strip():
                    continue
                try:
                    rec = json.loads(line)
                    if rec.get("sha") != _rec_digest(
                            int(rec["seq"]), rec["kind"], rec["data"]):
                        raise ValueError("digest mismatch")
                except Exception:
                    torn += 1
                    continue
                recs.append(rec)
            if torn:
                self.recover_warnings += torn
            if recs:
                with self._lock:
                    self.seq = max(int(r["seq"]) for r in recs)
                    self._tail = recs[-self._tail_max:]
                    self.base_seq = self._tail[0]["seq"]
                # the TERM floor is the newest file seen even when that
                # file itself was empty — a bump can never collide
                return top_term, recs
            if name == names[0]:
                # newest journal empty/corrupt: counted clean slate
                self.recover_warnings += 1
        return top_term, []

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None


# the in-process registry handle: FleetTopology sets it at construction
# so the lead learner (which runs in the gateway's own process) joins
# the replica plane through a LocalReplicaChannel instead of dialling
# its own gateway over loopback
_LOCAL_REGISTRY: List[Any] = [None]


def set_local_registry(registry) -> None:
    _LOCAL_REGISTRY[0] = registry


def local_registry():
    return _LOCAL_REGISTRY[0]


# T_RGRAD / T_RPRIO round status codes (int64 ``status`` column)
RSTAT_OK = 0        # round completed; reduced gradient + merge attached
RSTAT_FENCED = 1    # submitter's lease is gone / generation superseded
RSTAT_STALE = 2     # stale round or stale generation: counted reject
RSTAT_TIMEOUT = 3   # round could not complete (wedged registry guard)
RSTAT_NOREG = 4     # no ReplicaRegistry wired on this gateway

# every savez column the replica round codec may ship, either direction
# (the declared wire schema, same contract as WIRE_COLUMNS for EXP
# frames; the codec helpers below are the only writers/readers)
REPLICA_WIRE_COLUMNS = (
    "meta", "ok", "grad", "pidx", "ptd",            # submission
    "status", "generation", "round", "members",     # reply control
    "applied", "epoch_due", "wsrc", "wcount", "widx", "wtd")


def _pack_round(replica: int, generation: int, round_idx: int, ok: bool,
                grad: np.ndarray, pidx: Optional[np.ndarray] = None,
                ptd: Optional[np.ndarray] = None) -> bytes:
    cols = {
        "meta": np.asarray([replica, generation, round_idx], np.int64),
        "ok": np.asarray([1 if ok else 0], np.int64),
        "grad": np.ascontiguousarray(grad, dtype=np.float32),
    }
    if pidx is not None and len(pidx):
        cols["pidx"] = np.ascontiguousarray(pidx, dtype=np.int32)
        cols["ptd"] = np.ascontiguousarray(ptd, dtype=np.float32)
    out = io.BytesIO()
    np.savez(out, **cols)
    return out.getvalue()


def _unpack_round(payload: bytes) -> dict:
    try:
        with np.load(io.BytesIO(payload)) as z:
            cols = {k: z[k] for k in z.files}
    except Exception as e:
        raise ConnectionError(f"unparseable RGRAD payload: {e!r}")
    meta = cols.get("meta")
    if meta is None or meta.shape != (3,) or meta.dtype.kind not in "iu":
        raise ValueError("malformed RGRAD frame: bad meta column")
    return cols


def _pack_round_reply(status: int, generation: int = 0, round_idx: int = 0,
                      grad: Optional[np.ndarray] = None,
                      members: Tuple[int, ...] = (), applied: int = 0,
                      epoch_due: bool = False,
                      writebacks: Optional[List[Tuple[int, np.ndarray,
                                                      np.ndarray]]] = None
                      ) -> bytes:
    cols = {
        "status": np.asarray([status], np.int64),
        "generation": np.asarray([generation], np.int64),
        "round": np.asarray([round_idx], np.int64),
        "members": np.asarray(list(members), np.int64),
        "applied": np.asarray([applied], np.int64),
        "epoch_due": np.asarray([1 if epoch_due else 0], np.int64),
    }
    if grad is not None:
        cols["grad"] = np.ascontiguousarray(grad, dtype=np.float32)
    if writebacks:
        # merged |TD| write-backs, one group per contributing replica in
        # the deterministic merge order: every replica applies ALL
        # groups sequentially, so the N local PER rings stay one
        # logical priority plane
        cols["wsrc"] = np.asarray([s for s, _i, _t in writebacks],
                                  np.int64)
        cols["wcount"] = np.asarray([len(i) for _s, i, _t in writebacks],
                                    np.int64)
        cols["widx"] = np.concatenate(
            [np.asarray(i, np.int32) for _s, i, _t in writebacks])
        cols["wtd"] = np.concatenate(
            [np.asarray(t, np.float32) for _s, _i, t in writebacks])
    out = io.BytesIO()
    np.savez(out, **cols)
    return out.getvalue()


def _unpack_round_reply(payload: bytes) -> dict:
    try:
        with np.load(io.BytesIO(payload)) as z:
            cols = {k: z[k] for k in z.files}
    except Exception as e:
        raise ConnectionError(f"unparseable RGRAD reply: {e!r}")
    out: Dict[str, Any] = {
        "status": int(cols["status"][0]),
        "generation": int(cols.get("generation", [0])[0]),
        "round": int(cols.get("round", [0])[0]),
        "members": [int(m) for m in cols.get("members", [])],
        "applied": int(cols.get("applied", [0])[0]),
        "epoch_due": bool(cols.get("epoch_due", [0])[0]),
        "grad": cols.get("grad"),
    }
    wb: List[Tuple[int, np.ndarray, np.ndarray]] = []
    if "wsrc" in cols and len(cols["wsrc"]):
        off = 0
        for s, n in zip(cols["wsrc"], cols["wcount"]):
            wb.append((int(s), cols["widx"][off:off + int(n)],
                       cols["wtd"][off:off + int(n)]))
            off += int(n)
    out["writebacks"] = wb
    return out


def _pack_prio(replica: int, generation: int, pidx: np.ndarray,
               ptd: np.ndarray) -> bytes:
    out = io.BytesIO()
    np.savez(out,
             meta=np.asarray([replica, generation], np.int64),
             pidx=np.ascontiguousarray(pidx, dtype=np.int32),
             ptd=np.ascontiguousarray(ptd, dtype=np.float32))
    return out.getvalue()


def _pack_noshard_reply() -> bytes:
    """The ONE shard-plane frame this module authors: an SSTAT_NOSHARD
    T_SSAMPLE reply (memory/shard_plane.py owns every other codec and
    the status vocabulary; 3 == shard_plane.SSTAT_NOSHARD — its test
    pins the pair so they cannot drift) for gateways with no ``shards=``
    handler wired."""
    out = io.BytesIO()
    np.savez(out, status=np.asarray([3], np.int64),
             generation=np.asarray([0], np.int64))
    return out.getvalue()


class ReplicaRegistry:
    """Gateway-side membership + round coordinator for the elastic
    multi-learner plane (ISSUE 15).

    **Lease-fenced membership.**  Each replica holds a renewable lease
    stamped with a monotonic GENERATION number (one counter across the
    registry — every acquire, including a rejoin, consumes a fresh
    generation, so generations totally order membership history).  A
    lease neither renewed nor exercised (a round submission is proof of
    life) within ``lease_s`` expires: the member is removed, counted,
    and FENCED — any later gradient or priority write-back stamped with
    its dead generation is a counted reject (``stale_grad_rejected`` /
    ``stale_prio_rejected``), never applied.  A second acquire for the
    same replica id with a HIGHER incarnation evicts the stale holder
    (the double-lease case: a replacement process fencing its own
    half-open predecessor — PR 1's slot fencing lifted to the learner
    plane); equal/lower incarnations are refused.

    **Fault-tolerant rounds.**  ``submit`` blocks until round ``r`` has
    contributions from every live member whose ``joined_round <= r``.
    Membership can shrink while waiting: expiry (dead renewer) or the
    ROUND-STALL rule — once the first contribution lands, members still
    silent after one lease window are expelled (this is how a HUNG
    replica whose background renewer is still faithfully renewing gets
    fenced: leases prove liveness, rounds prove progress).  The round
    then completes over the surviving set: the reduced gradient is the
    mean over the surviving contributions summed in ascending replica
    order (a fixed fp32 reduction order, so an N=1 completion is
    bit-identical to the solo learner's own gradient), and the merged
    per-replica |TD| write-backs ride the reply in the same order so
    every survivor applies the identical priority mutation sequence.

    **Elastic rejoin.**  A mid-training acquire schedules a JOIN
    BARRIER: the round before the joiner's entry round replies
    ``epoch_due`` to every member (rank 0 commits a checkpoint epoch of
    the post-round state — utils/checkpoint.save_epoch), survivors then
    hold at the entry round until the joiner loads that exact epoch and
    ``activate``s (or its ``join_timeout_s`` lapses and the join is
    cancelled).  State convergence is by construction: the joiner
    resumes the very bytes the survivors checkpointed.

    Pure stdlib+numpy — no jax — so tools/chaos_soak.py drills the
    whole plane in milliseconds."""

    def __init__(self, params=None, writer=None):
        self.params = resolve_replica(params)
        self._cond = threading.Condition()
        self._gen = 0
        # replica -> {generation, incarnation, expires, joined_round,
        #             round, renews, born, marks: [(mono, round)]}
        self._members: Dict[int, Dict[str, Any]] = {}
        # fenced generations: replica -> last dead generation (the
        # last-generation-wins check reads the LIVE table; this map is
        # observability for drills)
        self._fenced_gen: Dict[int, int] = {}
        self._rounds: Dict[int, Dict[str, Any]] = {}
        self._round_done = -1
        # replica -> {generation, join_round, deadline}
        self._joining: Dict[int, Dict[str, Any]] = {}
        self._epoch_due: Dict[int, bool] = {}   # round -> commit due
        self._epoch_step: Dict[int, int] = {}   # round -> committed step
        self._oob_writebacks: List[Tuple[int, np.ndarray,
                                         np.ndarray]] = []
        self._churn: List[float] = []  # walls of expiry/fence events
        self._writer = writer
        self._last_emit = 0.0
        self._recorder = flight_recorder.get_recorder("replica-registry")
        # counters (the drill ledger: chaos_soak asserts these EXACTLY)
        self.leases_granted = 0
        self.leases_expired = 0
        self.leases_released = 0
        self.lease_fenced = 0           # double-lease evictions
        self.stale_grad_rejected = 0
        self.stale_prio_rejected = 0
        self.prio_merged_rows = 0
        self.rounds_completed = 0
        self.degraded_completions = 0   # completed over a shrunk set
        self.joins_completed = 0
        self.joins_timed_out = 0

    # -- internals (all under self._cond) -----------------------------------

    def _lease_window(self) -> float:
        return max(0.05, float(self.params.lease_s))

    def _emit_locked(self, force: bool = False) -> None:
        """``replica/*`` scalar rows for mission control (ISSUE 10):
        membership size, current generation, and generation churn
        (lease-consuming events — expiries + fences — in the last 60 s)
        — the series the ``replica_membership`` / ``replica_churn``
        DEFAULT_RULES watch.  Rate-limited; event paths force."""
        if self._writer is None:
            return
        now = time.monotonic()
        if not force and now - self._last_emit < 1.0:
            return
        self._last_emit = now
        wall = time.time()
        cutoff = wall - 60.0
        self._churn = [w for w in self._churn if w >= cutoff]
        try:
            self._writer.scalar("replica/members",
                                float(len(self._members)),
                                step=self._round_done + 1, wall=wall)
            self._writer.scalar("replica/generation", float(self._gen),
                                step=self._round_done + 1, wall=wall)
            self._writer.scalar("replica/generation_churn",
                                float(len(self._churn)),
                                step=self._round_done + 1, wall=wall)
            self._writer.flush()
        except Exception:  # noqa: BLE001 - telemetry is best-effort
            pass

    def _note_churn_locked(self) -> None:
        self._churn.append(time.time())

    def _expire_locked(self, now: float, round_waiting: Optional[int] = None
                       ) -> None:
        """Expire dead leases; with ``round_waiting`` set, also apply
        the round-stall rule to members blocking that round."""
        stalled: List[int] = []
        rnd = self._rounds.get(round_waiting) if round_waiting is not None \
            else None
        for rid, m in list(self._members.items()):
            dead = now > m["expires"]
            reason = "lease-expired"
            if not dead and rnd is not None and not rnd["done"] \
                    and m["joined_round"] <= round_waiting \
                    and rid not in rnd["contribs"] \
                    and rid not in self._joining \
                    and now - rnd["first_at"] > self._lease_window():
                # renewing but not progressing: a hung replica must not
                # wedge the survivors — expelled within one lease window
                dead, reason = True, "round-stall"
            if not dead:
                continue
            del self._members[rid]
            self._fenced_gen[rid] = m["generation"]
            self._joining.pop(rid, None)
            self.leases_expired += 1
            self._note_churn_locked()
            stalled.append(rid)
            self._recorder.record("lease-expired", replica=rid,
                                  generation=m["generation"],
                                  reason=reason)
            print(f"[replica] lease expired: replica {rid} "
                  f"(generation {m['generation']}, {reason})", flush=True)
        if stalled:
            self._emit_locked(force=True)
            self._cond.notify_all()
        # cancel joins whose deadline lapsed (the joiner never loaded
        # its barrier epoch): survivors must proceed
        for rid, j in list(self._joining.items()):
            if now > j["deadline"]:
                del self._joining[rid]
                m = self._members.pop(rid, None)
                if m is not None:
                    self._fenced_gen[rid] = m["generation"]
                self.joins_timed_out += 1
                self._note_churn_locked()
                self._recorder.record("join-timeout", replica=rid)
                self._emit_locked(force=True)
                self._cond.notify_all()

    def _live(self, rid: int, generation: int) -> bool:
        m = self._members.get(rid)
        return m is not None and m["generation"] == generation

    def _required_locked(self, round_idx: int) -> Set[int]:
        return {rid for rid, m in self._members.items()
                if m["joined_round"] <= round_idx}

    # -- lease verbs ---------------------------------------------------------

    def acquire(self, replica: int, incarnation: int) -> dict:
        with self._cond:
            now = time.monotonic()
            self._expire_locked(now)
            held = self._members.get(replica)
            if held is not None:
                if incarnation <= held["incarnation"]:
                    return {"status": "refused",
                            "error": f"replica {replica} already leased "
                                     f"(incarnation {incarnation} <= "
                                     f"{held['incarnation']})"}
                # double-lease: same slot, newer incarnation — fence the
                # stale holder, the newer incarnation wins
                self._fenced_gen[replica] = held["generation"]
                self.lease_fenced += 1
                self._note_churn_locked()
                self._recorder.record("lease-fenced", replica=replica,
                                      old=held["generation"])
            self._gen += 1
            g = self._gen
            open_max = max(self._rounds.keys(), default=self._round_done)
            fresh = self._round_done < 0 and not self._rounds
            if fresh or not (self._members.keys() - {replica}):
                joined = max(0, open_max + 1)
                barrier = None
            else:
                # mid-training join: enter at J, with the round J-1
                # completion carrying the epoch_due flag (rank 0
                # commits the post-(J-1) state the joiner will load)
                joined = open_max + 2
                barrier = joined - 1
                self._epoch_due[barrier] = True
                self._joining[replica] = {
                    "generation": g, "join_round": joined,
                    "deadline": now + max(self.params.join_timeout_s,
                                          self._lease_window())}
            self._members[replica] = {
                "generation": g, "incarnation": int(incarnation),
                "expires": now + self._lease_window(),
                "joined_round": joined, "round": joined - 1,
                "renews": 0, "born": now,
                "marks": [(now, joined - 1)]}
            self.leases_granted += 1
            self._recorder.record("lease-granted", replica=replica,
                                  generation=g, joined_round=joined)
            self._emit_locked(force=True)
            self._cond.notify_all()
            return {"status": "ok", "generation": g,
                    "lease_s": self._lease_window(), "round": joined,
                    "members": sorted(self._members),
                    "epoch_barrier": barrier}

    def renew(self, replica: int, generation: int,
              round_idx: Optional[int] = None) -> dict:
        with self._cond:
            now = time.monotonic()
            self._expire_locked(now)
            if not self._live(replica, generation):
                return {"status": "expired"}
            m = self._members[replica]
            m["expires"] = now + self._lease_window()
            m["renews"] += 1
            if round_idx is not None:
                m["round"] = max(m["round"], int(round_idx))
                m["marks"].append((now, m["round"]))
                del m["marks"][:-8]
            self._emit_locked()
            reply = {"status": "ok", "generation": generation,
                     "members": sorted(self._members)}
            j = self._joining.get(replica)
            if j is not None:
                reply["join"] = {
                    "round": j["join_round"],
                    "epoch_round": j["join_round"] - 1,
                    "epoch_step": self._epoch_step.get(
                        j["join_round"] - 1)}
            return reply

    def release(self, replica: int, generation: int) -> dict:
        with self._cond:
            if self._live(replica, generation):
                m = self._members.pop(replica)
                self._fenced_gen[replica] = m["generation"]
                self._joining.pop(replica, None)
                self.leases_released += 1
                self._recorder.record("lease-released", replica=replica,
                                      generation=generation)
                self._emit_locked(force=True)
                self._cond.notify_all()
            return {"status": "ok"}

    def activate(self, replica: int, generation: int,
                 epoch_step: Optional[int] = None) -> dict:
        """A rejoiner confirms it loaded the barrier epoch: it becomes a
        full member of its join round and the held survivors proceed."""
        with self._cond:
            if not self._live(replica, generation):
                return {"status": "expired"}
            j = self._joining.pop(replica, None)
            if j is not None:
                self.joins_completed += 1
                self._recorder.record("join-activated", replica=replica,
                                      generation=generation,
                                      epoch_step=epoch_step)
            m = self._members[replica]
            now = time.monotonic()
            m["expires"] = now + self._lease_window()
            # restart the entry round's stall clock: the survivors'
            # submissions set first_at while the joiner was still
            # loading the epoch — without this reset, a first-round jit
            # compile longer than one lease window would expel the
            # freshly-activated joiner under the round-stall rule
            rnd = self._rounds.get(m["joined_round"])
            if rnd is not None and not rnd["done"]:
                rnd["first_at"] = now
            self._emit_locked(force=True)
            self._cond.notify_all()
            return {"status": "ok", "round": m["joined_round"],
                    "members": sorted(self._members)}

    def note_epoch(self, replica: int, generation: int, round_idx: int,
                   step: int) -> dict:
        """Rank 0 reports the barrier epoch committed at ``step`` —
        the signal a pending joiner polls for (via ``renew``)."""
        with self._cond:
            if not self._live(replica, generation):
                return {"status": "expired"}
            self._epoch_step[round_idx] = int(step)
            self._epoch_due.pop(round_idx, None)
            self._recorder.record("epoch-committed", round=round_idx,
                                  step=step, by=replica)
            self._cond.notify_all()
            return {"status": "ok"}

    # -- the generation-stamped allreduce round ------------------------------

    def submit(self, replica: int, generation: int, round_idx: int,
               grad: np.ndarray, ok: bool = True,
               pidx: Optional[np.ndarray] = None,
               ptd: Optional[np.ndarray] = None) -> dict:
        """One blocking round contribution; returns the completed
        round's result (or a fenced/stale/timeout status).  The caller's
        serve thread (or the local channel's caller) parks on the
        registry condition; submitting and waiting both count as proof
        of life, so a member blocked on a slow peer is never expired —
        the PEER is, by the round-stall rule."""
        deadline_s = self.params.round_timeout_s or \
            (3.0 * self._lease_window() + 1.0)
        with self._cond:
            now = time.monotonic()
            self._expire_locked(now)
            done = self._rounds.get(round_idx)
            if done is not None and done["done"] \
                    and replica in done["contribs"] \
                    and done["contribs"][replica][0] == generation:
                # idempotent retransmit: this replica already completed
                # this round and its reply ack was lost to a wire blip
                # — hand the retained result back instead of fencing a
                # perfectly live member for retrying
                return done["result"]
            if round_idx <= self._round_done \
                    or (not self._live(replica, generation)):
                stale = not self._live(replica, generation)
                self.stale_grad_rejected += 1
                self._recorder.record("stale-grad-rejected",
                                      replica=replica,
                                      generation=generation,
                                      round=round_idx)
                return {"status": (RSTAT_FENCED if stale
                                   else RSTAT_STALE)}
            rnd = self._rounds.get(round_idx)
            if rnd is None:
                rnd = self._rounds[round_idx] = {
                    "contribs": {}, "first_at": now, "done": False,
                    "result": None,
                    "starting_members": len(self._required_locked(
                        round_idx))}
            rnd["contribs"][replica] = (
                generation, bool(ok),
                np.ascontiguousarray(grad, dtype=np.float32),
                (None if pidx is None or not len(pidx)
                 else (np.ascontiguousarray(pidx, np.int32),
                       np.ascontiguousarray(ptd, np.float32))))
            m = self._members[replica]
            m["round"] = max(m["round"], round_idx)
            m["marks"].append((now, round_idx))
            del m["marks"][:-8]
            self._cond.notify_all()
            deadline = now + deadline_s
            while True:
                now = time.monotonic()
                # waiting in a round is progress: refresh my own lease
                me = self._members.get(replica)
                if me is None or me["generation"] != generation:
                    # fenced while waiting (double-lease eviction)
                    return {"status": RSTAT_FENCED}
                me["expires"] = now + self._lease_window()
                self._expire_locked(now, round_waiting=round_idx)
                if rnd["done"]:
                    return rnd["result"]
                self._try_complete_locked(round_idx)
                if rnd["done"]:
                    return rnd["result"]
                # a PENDING joiner legitimately stretches its entry
                # round past the normal wait (it is loading the barrier
                # epoch, bounded by its own join deadline) — survivors
                # must hold for it, not time out under it
                eff = deadline
                for j in self._joining.values():
                    if j["join_round"] <= round_idx:
                        eff = max(eff, j["deadline"] + 1.0)
                if now > eff:
                    return {"status": RSTAT_TIMEOUT}
                self._cond.wait(0.05)

    def _try_complete_locked(self, round_idx: int) -> None:
        rnd = self._rounds.get(round_idx)
        if rnd is None or rnd["done"]:
            return
        required = self._required_locked(round_idx)
        if not required:
            return
        # only contributions from members STILL live at completion time
        # count (a contributor that died mid-round is dropped from the
        # reduce — its generation is fenced, its gradient with it)
        have = {rid for rid in rnd["contribs"]
                if self._live(rid, rnd["contribs"][rid][0])}
        if not required <= have:
            return
        ids = sorted(required)
        valid = [rid for rid in ids if rnd["contribs"][rid][1]]
        reduced = None
        if valid:
            # fixed fp32 reduction order (ascending replica id): at
            # N=1 the "mean" is grad / 1.0 — bit-identical to the solo
            # learner's own gradient, the degraded-parity contract
            acc = rnd["contribs"][valid[0]][2].astype(np.float32,
                                                      copy=True)
            for rid in valid[1:]:
                acc += rnd["contribs"][rid][2]
            reduced = acc / np.float32(len(valid))
        writebacks = [(rid,) + rnd["contribs"][rid][3]
                      for rid in valid
                      if rnd["contribs"][rid][3] is not None]
        if self._oob_writebacks:
            # fenced-validated out-of-round merges land AFTER the
            # in-round groups, in arrival order — identically on every
            # member, so the logical priority plane never forks
            writebacks.extend(self._oob_writebacks)
            self._oob_writebacks = []
        rnd["result"] = {
            "status": RSTAT_OK,
            "grad": reduced,
            "applied": len(valid),
            "members": list(ids),
            "round": round_idx,
            "epoch_due": bool(self._epoch_due.get(round_idx)),
            "writebacks": writebacks,
        }
        rnd["done"] = True
        self._round_done = max(self._round_done, round_idx)
        self.rounds_completed += 1
        bandwidth.note_round()
        if len(ids) < rnd["starting_members"]:
            self.degraded_completions += 1
            self._recorder.record("round-degraded", round=round_idx,
                                  survivors=ids,
                                  started=rnd["starting_members"])
        # retire old round state (completed results are only read by
        # waiters already parked on them; keep a couple for stragglers)
        for r in [r for r in self._rounds if r < round_idx - 2]:
            del self._rounds[r]
        self._emit_locked()
        self._cond.notify_all()

    def merge_prio(self, replica: int, generation: int, pidx: np.ndarray,
                   ptd: np.ndarray) -> dict:
        """Out-of-round |TD| write-back merge with last-generation-wins
        fencing: live-generation writes queue for the next round's
        merged reply; a zombie's stale-generation write is a counted
        reject and never touches the priority plane."""
        with self._cond:
            self._expire_locked(time.monotonic())
            if not self._live(replica, generation):
                self.stale_prio_rejected += 1
                self._recorder.record("stale-prio-rejected",
                                      replica=replica,
                                      generation=generation,
                                      rows=int(len(pidx)))
                return {"status": "stale"}
            self._oob_writebacks.append(
                (replica, np.ascontiguousarray(pidx, np.int32),
                 np.ascontiguousarray(ptd, np.float32)))
            self.prio_merged_rows += int(len(pidx))
            return {"status": "ok"}

    # -- observability -------------------------------------------------------

    def status_block(self) -> dict:
        """The gateway STATUS ``replicas`` block: membership with lease
        ages + per-replica round rates, the generation counter, and the
        fencing/round ledger — tools/fleet_top.py's replicas panel and
        the chaos drills' exact-counter verdicts both read this."""
        with self._cond:
            now = time.monotonic()
            members = {}
            for rid, m in self._members.items():
                rate = None
                marks = m["marks"]
                if len(marks) >= 2 and marks[-1][0] > marks[0][0] + 0.2:
                    rate = round((marks[-1][1] - marks[0][1])
                                 / (marks[-1][0] - marks[0][0]), 2)
                members[str(rid)] = {
                    "generation": m["generation"],
                    "lease_age": round(
                        max(0.0, now - (m["expires"]
                                        - self._lease_window())), 3),
                    "round": m["round"],
                    "renews": m["renews"],
                    "joining": rid in self._joining,
                    "updates_per_s": rate,
                }
            expected = max(1, int(self.params.replicas))
            return {
                "expected": expected,
                "members": members,
                "degraded": len(members) < expected,
                "generation": self._gen,
                "rounds_completed": self.rounds_completed,
                "degraded_completions": self.degraded_completions,
                "counters": {
                    "leases_granted": self.leases_granted,
                    "leases_expired": self.leases_expired,
                    "leases_released": self.leases_released,
                    "lease_fenced": self.lease_fenced,
                    "stale_grad_rejected": self.stale_grad_rejected,
                    "stale_prio_rejected": self.stale_prio_rejected,
                    "prio_merged_rows": self.prio_merged_rows,
                    "joins_completed": self.joins_completed,
                    "joins_timed_out": self.joins_timed_out,
                },
            }

    # -- wire dispatch (called by DcnGateway serve threads) ------------------

    def handle_lease(self, msg: dict) -> dict:
        action = str(msg.get("action", ""))
        try:
            rid = int(msg.get("replica"))
        except (TypeError, ValueError):
            return {"status": "error", "error": "bad replica id"}
        if action == "acquire":
            return self.acquire(rid, int(msg.get("incarnation", 0)))
        gen = int(msg.get("generation", -1))
        if action == "renew":
            r = msg.get("round")
            return self.renew(rid, gen,
                              int(r) if r is not None else None)
        if action == "release":
            return self.release(rid, gen)
        if action == "activate":
            es = msg.get("epoch_step")
            return self.activate(rid, gen,
                                 int(es) if es is not None else None)
        if action == "epoch":
            return self.note_epoch(rid, gen, int(msg.get("round", -1)),
                                   int(msg.get("step", -1)))
        return {"status": "error", "error": f"unknown action {action!r}"}

    def handle_round(self, payload: bytes) -> bytes:
        try:
            cols = _unpack_round(payload)
        except ValueError:
            return _pack_round_reply(RSTAT_STALE)  # malformed: reject
        rid, gen, rnd = (int(x) for x in cols["meta"])
        pidx, ptd = cols.get("pidx"), cols.get("ptd")
        res = self.submit(rid, gen, rnd, cols.get(
            "grad", np.zeros(0, np.float32)),
            ok=bool(cols.get("ok", [1])[0]),
            pidx=pidx, ptd=ptd)
        if res["status"] != RSTAT_OK:
            return _pack_round_reply(res["status"])
        return _pack_round_reply(
            RSTAT_OK, generation=gen, round_idx=res["round"],
            grad=res["grad"], members=res["members"],
            applied=res["applied"], epoch_due=res["epoch_due"],
            writebacks=res["writebacks"])

    def handle_prio(self, payload: bytes) -> dict:
        try:
            with np.load(io.BytesIO(payload)) as z:
                meta = z["meta"]
                pidx = z["pidx"]
                ptd = z["ptd"]
        except Exception as e:
            raise ConnectionError(f"unparseable RPRIO payload: {e!r}")
        return self.merge_prio(int(meta[0]), int(meta[1]), pidx, ptd)


class ReplicaFenced(RuntimeError):
    """This replica's lease is gone (expired, superseded, or the round
    reply said fenced): its generation can no longer write anything.
    The driver's recovery is rejoin-at-a-new-generation or a nonzero
    exit for the supervisor — never a silent continue."""


class LocalReplicaChannel:
    """In-process channel to a ReplicaRegistry — the lead learner runs
    in the gateway's own process, so its replica-plane traffic skips
    the wire (same surface as ReplicaClient; tests use it too)."""

    def __init__(self, registry: ReplicaRegistry, replica: int,
                 incarnation: Optional[int] = None):
        self.registry = registry
        self.replica = replica
        self.incarnation = (int(incarnation) if incarnation is not None
                            else time.time_ns() // 1_000_000)
        self.generation: Optional[int] = None
        self._granted_lease_s: Optional[float] = None
        self.fenced = threading.Event()
        self._renew_stop = threading.Event()
        self._renew_thread: Optional[threading.Thread] = None
        self._round = 0  # last round index reported on renews

    # -- surface shared with ReplicaClient -----------------------------------

    def acquire(self) -> dict:
        self.incarnation += 1
        reply = self.registry.acquire(self.replica, self.incarnation)
        if reply.get("status") != "ok":
            raise ReplicaFenced(
                f"replica {self.replica} lease refused: "
                f"{reply.get('error')}")
        self.generation = reply["generation"]
        # the renew cadence follows the SERVER'S lease window (it rides
        # the acquire reply): a client configured with a longer window
        # than the registry's would otherwise expire between renews
        self._granted_lease_s = float(reply.get("lease_s", 0.0)) or None
        self.fenced.clear()
        return reply

    def renew(self) -> dict:
        if self.generation is None:
            return {"status": "expired"}
        reply = self.registry.renew(self.replica, self.generation,
                                    self._round)
        if reply.get("status") != "ok":
            self.fenced.set()
        return reply

    def start_renewer(self, period: Optional[float] = None) -> None:
        if self._renew_thread is not None \
                and self._renew_thread.is_alive():
            return
        self._renew_stop.clear()
        p = period or (self.registry.params.renew_s
                       or (self._granted_lease_s
                           or self.registry._lease_window()) / 3.0)

        def _loop() -> None:
            while not self._renew_stop.wait(p):
                if self.fenced.is_set():
                    return
                self.renew()

        self._renew_thread = threading.Thread(
            target=_loop, name=f"replica-renew-{self.replica}",
            daemon=True)
        self._renew_thread.start()

    def submit_round(self, round_idx: int, grad: np.ndarray,
                     ok: bool = True,
                     pidx: Optional[np.ndarray] = None,
                     ptd: Optional[np.ndarray] = None) -> dict:
        if self.generation is None:
            raise ReplicaFenced(f"replica {self.replica} has no lease")
        self._round = round_idx
        res = self.registry.submit(self.replica, self.generation,
                                   round_idx, grad, ok=ok,
                                   pidx=pidx, ptd=ptd)
        if res["status"] in (RSTAT_FENCED, RSTAT_STALE):
            self.fenced.set()
        return res

    def merge_prio(self, pidx: np.ndarray, ptd: np.ndarray,
                   generation: Optional[int] = None) -> dict:
        g = self.generation if generation is None else generation
        if g is None:
            raise ReplicaFenced(f"replica {self.replica} has no lease")
        return self.registry.merge_prio(self.replica, g, pidx, ptd)

    def note_epoch(self, round_idx: int, step: int) -> dict:
        return self.registry.note_epoch(self.replica, self.generation,
                                        round_idx, step)

    def activate(self, epoch_step: Optional[int] = None) -> dict:
        return self.registry.activate(self.replica, self.generation,
                                      epoch_step)

    def members(self) -> List[int]:
        reply = self.renew()
        return list(reply.get("members", []))

    def wait_members(self, n: int, timeout: float = 30.0) -> bool:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if len(self.members()) >= n:
                return True
            time.sleep(0.05)
        return False

    def poll_join(self) -> Optional[dict]:
        return self.renew().get("join")

    def release(self) -> None:
        if self.generation is not None and not self.fenced.is_set():
            self.registry.release(self.replica, self.generation)

    def close(self) -> None:
        self._renew_stop.set()
        if self._renew_thread is not None:
            self._renew_thread.join(2.0)
            self._renew_thread = None


class ReplicaClient:
    """Wire twin of LocalReplicaChannel: one replica host's connection
    to the lead gateway's replica plane.  Two sockets — a control
    connection for the lease verbs (sessionless-adjacent: cheap JSON
    RPCs that must keep flowing while a round blocks) and a round
    connection whose T_RGRAD request parks server-side until the round
    completes.  Transport errors surface as ReplicaFenced after one
    redial attempt: the replica plane's recovery story is leases and
    rejoin, not transparent session resumption — a replica that cannot
    reach the registry for a lease window IS expired."""

    def __init__(self, address: Tuple[str, int], replica: int,
                 params=None, incarnation: Optional[int] = None):
        self.address = address
        self.replica = replica
        self.params = resolve_replica(params)
        self.incarnation = (int(incarnation) if incarnation is not None
                            else time.time_ns() // 1_000_000)
        self.generation: Optional[int] = None
        self._granted_lease_s: Optional[float] = None
        self.fenced = threading.Event()
        self._lease_lock = threading.Lock()
        self._round_lock = threading.Lock()
        self._lease_sock: Optional[socket.socket] = None
        self._round_sock: Optional[socket.socket] = None
        self._renew_stop = threading.Event()
        self._renew_thread: Optional[threading.Thread] = None
        self._round = 0

    def _lease_window(self) -> float:
        return max(0.05, float(self.params.lease_s))

    def _rpc(self, which: str, ftype: int, payload: bytes,
             timeout: float) -> Tuple[int, bytes]:
        lock = self._lease_lock if which == "lease" else self._round_lock
        attr = "_lease_sock" if which == "lease" else "_round_sock"
        with lock:
            for attempt in (0, 1):
                sock = getattr(self, attr)
                try:
                    if sock is None:
                        sock = socket.create_connection(
                            self.address, timeout=5.0)
                        sock.setsockopt(socket.IPPROTO_TCP,
                                        socket.TCP_NODELAY, 1)
                        bandwidth.register_socket(sock, "replica",
                                                  self.replica)
                        setattr(self, attr, sock)
                    sock.settimeout(timeout)
                    _send_frame(sock, ftype, payload)
                    return _recv_frame(sock)
                except (ConnectionError, OSError):
                    try:
                        if sock is not None:
                            sock.close()
                    except OSError:
                        pass
                    setattr(self, attr, None)
                    if attempt:
                        raise

    def _lease_rpc(self, msg: dict,
                   timeout: Optional[float] = None) -> dict:
        rtype, payload = self._rpc(
            "lease", T_RLEASE, json.dumps(msg).encode(),
            timeout or max(5.0, self._lease_window()))
        if rtype != T_RLEASE:
            raise ConnectionError(
                f"expected T_RLEASE reply, got frame type {rtype}")
        try:
            return json.loads(payload.decode())
        except (ValueError, UnicodeDecodeError) as e:
            raise ConnectionError(f"undecodable RLEASE reply: {e}")

    # -- surface (mirrors LocalReplicaChannel) -------------------------------

    def acquire(self) -> dict:
        self.incarnation += 1
        reply = self._lease_rpc({"action": "acquire",
                                 "replica": self.replica,
                                 "incarnation": self.incarnation})
        if reply.get("status") != "ok":
            raise ReplicaFenced(
                f"replica {self.replica} lease refused: "
                f"{reply.get('error')}")
        self.generation = reply["generation"]
        # the renew cadence follows the SERVER'S lease window (it rides
        # the acquire reply): a client configured with a longer window
        # than the registry's would otherwise expire between renews
        self._granted_lease_s = float(reply.get("lease_s", 0.0)) or None
        self.fenced.clear()
        return reply

    def renew(self) -> dict:
        if self.generation is None:
            return {"status": "expired"}
        try:
            reply = self._lease_rpc({"action": "renew",
                                     "replica": self.replica,
                                     "generation": self.generation,
                                     "round": self._round})
        except (ConnectionError, OSError):
            return {"status": "error"}
        if reply.get("status") == "expired":
            self.fenced.set()
        return reply

    def start_renewer(self, period: Optional[float] = None) -> None:
        if self._renew_thread is not None \
                and self._renew_thread.is_alive():
            return
        self._renew_stop.clear()
        p = period or (self.params.renew_s
                       or (self._granted_lease_s
                           or self._lease_window()) / 3.0)

        def _loop() -> None:
            while not self._renew_stop.wait(p):
                if self.fenced.is_set():
                    return
                self.renew()

        self._renew_thread = threading.Thread(
            target=_loop, name=f"replica-renew-{self.replica}",
            daemon=True)
        self._renew_thread.start()

    def submit_round(self, round_idx: int, grad: np.ndarray,
                     ok: bool = True,
                     pidx: Optional[np.ndarray] = None,
                     ptd: Optional[np.ndarray] = None) -> dict:
        if self.generation is None:
            raise ReplicaFenced(f"replica {self.replica} has no lease")
        self._round = round_idx
        timeout = (self.params.round_timeout_s
                   or 3.0 * self._lease_window() + 1.0) + 10.0
        rtype, payload = self._rpc(
            "round", T_RGRAD,
            _pack_round(self.replica, self.generation, round_idx, ok,
                        grad, pidx, ptd),
            timeout)
        if rtype != T_RGRAD:
            raise ConnectionError(
                f"expected T_RGRAD reply, got frame type {rtype}")
        res = _unpack_round_reply(payload)
        if res["status"] in (RSTAT_FENCED, RSTAT_STALE):
            self.fenced.set()
        return res

    def merge_prio(self, pidx: np.ndarray, ptd: np.ndarray,
                   generation: Optional[int] = None) -> dict:
        g = self.generation if generation is None else generation
        if g is None:
            raise ReplicaFenced(f"replica {self.replica} has no lease")
        rtype, payload = self._rpc(
            "lease", T_RPRIO, _pack_prio(self.replica, g, pidx, ptd),
            max(5.0, self._lease_window()))
        if rtype != T_RPRIO:
            raise ConnectionError(
                f"expected T_RPRIO reply, got frame type {rtype}")
        return json.loads(payload.decode())

    def note_epoch(self, round_idx: int, step: int) -> dict:
        return self._lease_rpc({"action": "epoch",
                                "replica": self.replica,
                                "generation": self.generation,
                                "round": round_idx, "step": step})

    def activate(self, epoch_step: Optional[int] = None) -> dict:
        return self._lease_rpc({"action": "activate",
                                "replica": self.replica,
                                "generation": self.generation,
                                "epoch_step": epoch_step})

    def members(self) -> List[int]:
        return list(self.renew().get("members", []))

    def wait_members(self, n: int, timeout: float = 30.0) -> bool:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if len(self.members()) >= n:
                return True
            time.sleep(0.05)
        return False

    def poll_join(self) -> Optional[dict]:
        return self.renew().get("join")

    def release(self) -> None:
        if self.generation is None or self.fenced.is_set():
            return
        try:
            self._lease_rpc({"action": "release",
                             "replica": self.replica,
                             "generation": self.generation})
        except (ConnectionError, OSError):
            pass

    def close(self) -> None:
        self._renew_stop.set()
        if self._renew_thread is not None:
            self._renew_thread.join(2.0)
            self._renew_thread = None
        for attr in ("_lease_sock", "_round_sock"):
            sock = getattr(self, attr)
            if sock is not None:
                try:
                    sock.close()
                except OSError:
                    pass
                setattr(self, attr, None)


# ---------------------------------------------------------------------------
# learner-host gateway
# ---------------------------------------------------------------------------

class DcnGateway:
    """Accepts remote-actor connections on the learner host.

    ``put_chunk`` receives decoded ``[(Transition, priority), ...]`` lists —
    wire it to the single-owner memory's spawn queue (``feed_queue_of``) so
    remote experience merges with local feeders on the learner's drain path.

    Slot registry: each remote actor slot maps to the (incarnation,
    connection) that owns it.  A reconnecting actor fences its own stale
    predecessor by arriving with a higher incarnation (see module
    docstring); connections idle past ``idle_deadline`` seconds — a
    multiple of the clients' heartbeat interval — are presumed dead and
    dropped, which frees their slots without waiting on TCP keepalive.
    """

    def __init__(self, param_store, clock, actor_stats,
                 put_chunk: Callable[[list], None],
                 host: str = "0.0.0.0", port: int = 0,
                 local_actors: int = 0,
                 idle_deadline: Optional[float] = None,
                 faults: Optional[FaultInjector] = None,
                 health: Optional[Callable[[], dict]] = None,
                 profiler: Optional[Callable[[dict], dict]] = None,
                 metrics_sink: Optional[Callable[[dict], int]] = None,
                 flow_params=None,
                 pressure: Optional[Callable[[], float]] = None,
                 flow_writer=None,
                 replicas: Optional[ReplicaRegistry] = None,
                 shards=None,
                 gateway_params=None,
                 log_dir: Optional[str] = None,
                 ha_role: str = "primary",
                 sync_from: Optional[Tuple[str, int]] = None,
                 ha_writer=None,
                 resume_term: Optional[int] = None):
        self.param_store = param_store
        self.clock = clock
        self.actor_stats = actor_stats
        self.put_chunk = put_chunk
        self.local_actors = local_actors
        self._idle_deadline = (_env_float("DCN_IDLE_DEADLINE", 60.0)
                               if idle_deadline is None else idle_deadline)
        self._faults = (faults if faults is not None
                        else FaultInjector.from_env("gateway"))
        # extra STATUS fields from the owning topology (replay fill,
        # queue depth, restart budget, learner rate — things only the
        # learner-host wiring can see); called per STATUS request
        self._health = health
        # on-demand profiling provider (utils/perf.run_profile_window
        # via the owning topology): T_PROFILE requests block their own
        # serve thread for the bounded window and reply with the trace
        # dir; no provider wired -> error reply, never a crash
        self._profiler = profiler
        self.profiles_served = 0
        # T_METRICS sink (utils/telemetry.MissionControl.ingest_remote
        # via the owning topology): receives one pushed batch dict and
        # returns rows absorbed; no sink wired -> counted error reply,
        # never a crash
        self._metrics_sink = metrics_sink
        self.metrics_batches = 0
        self.metrics_rows = 0
        # replica plane (ISSUE 15): the lease-fenced membership registry
        # + gradient-exchange coordinator for N data-parallel learner
        # replicas.  None on non-replicated fleets — the verbs then
        # answer counted errors, never crash a serve thread.
        self._replicas = replicas
        # shard plane (ISSUE 20): duck-typed handler for the shard
        # verbs — a memory.shard_plane.LocalShard on replay-shard
        # hosts, a ShardRegistry on the coordinator.  Duck-typed so
        # this module never imports the plane; None on unsharded
        # fleets — the verbs then answer counted errors, never crash
        # a serve thread, and STATUS carries no shards block at all.
        self._shards = shards
        self._tracer = tracing.get_tracer("gateway")
        self._recorder = flight_recorder.get_recorder("gateway")
        # flow-control plane (ISSUE 11, utils/flow.py): per-slot credit
        # grants on every ack, admission control + the brownout ladder.
        # Inert without a ``pressure`` provider (the governor never
        # leaves healthy, no credit field rides the wire), so bare
        # test/tool gateways behave exactly as before.
        self._flow = None
        if flow.resolve_flow(flow_params).enabled:
            self._flow = flow.GatewayFlow(
                flow_params, pressure=pressure,
                recorder=self._recorder, writer=flow_writer)
        self._born = time.monotonic()
        self._srv = socket.create_server((host, port))
        self._srv.settimeout(0.25)
        self.port = self._srv.getsockname()[1]
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._slots: Dict[int, Tuple[int, socket.socket]] = {}
        self._tick_seq: Dict[int, int] = {}  # per-slot dedup high-water
        self._last_seen: Dict[int, float] = {}  # slot -> last frame (mono)
        self._slots_lock = threading.Lock()
        self._conns: Set[socket.socket] = set()
        self.connections = 0
        self.chunks_in = 0
        self.status_served = 0
        self.fenced = 0  # stale predecessors evicted by higher incarnations
        # health-sentinel ingest counters: schema-invalid EXP frames
        # rejected (counted warning + ack, never a session teardown) and
        # transitions quarantined per source slot — both surfaced by the
        # T_STATUS verb so fleet_top shows WHICH actor is poisoning
        self.frames_rejected = 0
        self.quarantined: Dict[str, int] = {}
        self._validators: Dict[str, Any] = {}
        # gateway HA plane (ISSUE 16): durable control journal + warm
        # standby + fenced promotion.  Entirely absent unless a resolved
        # GatewayParams enables it AND a log_dir exists to journal under
        # — the default single-gateway fleet stays byte-identical on the
        # wire (no term/sync fields, no TERM/WAL files, no STATUS block).
        self._gp = resolve_gateway(gateway_params)
        self._ha = bool(self._gp.enabled and log_dir)
        self._ha_log_dir = log_dir
        self._role = ("standby" if (self._ha and ha_role == "standby")
                      else "primary")
        # a standby refuses session verbs (counted) until promoted, so
        # failing-over clients land on the ConnectionError -> redial
        # path, never the terminal DcnRefused path
        self._serving = not (self._ha and self._role == "standby")
        self._sync_from = sync_from
        self._ha_writer = ha_writer
        self.term = 0
        self.promotions = 0
        self.gateway_term_fenced = 0  # writes rejected on a stale term
        self.standby_refused = 0
        self.failover_lost = 0  # acked-but-undrained rows lost in failover
        self.sync_served = 0
        self.promoted = threading.Event()
        self._term_fenced = False
        self._journal_dead = False
        self._term_checked = 0.0
        # re-read TERM.json at most this often on the write path: bounds
        # how long a fenced primary can run before noticing, well inside
        # the lease window that gates any promotion in the first place
        self._term_check_every = min(0.05, max(0.01, self._gp.lease_s / 10))
        self._journal: Optional[GatewayJournal] = None
        # absolute ingest totals carried across terms (seeded from the
        # journal / sync stream; own-plane counters add on top)
        self._ha_carry: Dict[str, int] = {}
        self._inc_floor: Dict[int, int] = {}  # journal-seeded slot fencing
        self._ha_thread: Optional[threading.Thread] = None
        self._ha_state_every = max(0.05, min(0.5, self._gp.sync_s))
        self._ha_state_last = 0.0
        self._sync_seq = 0
        self._sync_term = 0
        self._last_sync_ok = time.monotonic()
        if self._ha:
            self._ha_init(resume_term)
        # all state above must exist before the first connection lands
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="dcn-accept", daemon=True)
        self._accept_thread.start()

    # -- server loops -------------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, addr = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            # slot is unknown until HELLO; the serve loop re-registers
            bandwidth.register_socket(conn, "gateway")
            self.connections += 1
            with self._slots_lock:
                self._conns.add(conn)
            t = threading.Thread(target=self._serve, args=(conn, addr),
                                 name=f"dcn-conn-{addr}", daemon=True)
            t.start()
            # prune threads of departed peers — actor churn is expected
            self._threads = [x for x in self._threads if x.is_alive()]
            self._threads.append(t)

    def _clock_payload(self, slot: Optional[int] = None) -> bytes:
        msg = {
            "learner_step": int(self.clock.learner_step.value),
            "stop": bool(self.clock.stop.is_set()),
            # gateway wall clock: remote clients estimate their offset
            # to the learner host off the reply midpoint (NTP-style),
            # so tools/timeline.py can align cross-host events on one
            # clock.  Old peers ignore the extra key.
            "wall": time.time(),
        }
        if self._flow is not None and slot is not None:
            # flow control rides the ack (ISSUE 11): ``credits`` is how
            # many chunks this slot may send before its next grant
            # (absent while healthy = unlimited — old peers and calm
            # fleets see the exact pre-flow wire); ``brownout`` tells
            # the client host which shed tier the ladder is on.
            grant = self._flow.grant(slot)
            if grant is not None:
                msg["credits"] = grant
            tier = self._flow.governor.tier
            if tier:
                msg["brownout"] = tier
        return json.dumps(msg).encode()

    # -- gateway HA plane (ISSUE 16) ----------------------------------------

    def _ha_init(self, resume_term: Optional[int]) -> None:
        """Role-split HA bring-up.  Primary: recover the journal, bump +
        publish the term, warm-seed tick dedup / incarnation floors /
        ledger carry from the recovered records.  Standby: recover its
        own applied-copy journal (the resync offset) and start the sync
        loop.  ``resume_term`` is the drill hook for a RESURRECTED
        primary: it believes the stale term it is given and must
        discover the on-disk one through the fencing path — it never
        bumps, never writes TERM.json, never opens a WAL."""
        if self._role == "standby":
            self._journal = GatewayJournal(self._ha_log_dir, standby=True)
            _term, recs = self._journal.recover()
            self._seed_records(recs)
            self._sync_seq = self._journal.seq
            self._ha_thread = threading.Thread(
                target=self._ha_loop, name="dcn-ha-sync", daemon=True)
            self._ha_thread.start()
            return
        self._journal = GatewayJournal(self._ha_log_dir)
        if resume_term is not None:
            self.term = int(resume_term)
            return
        disk = self._journal.read_term()
        rec_term, recs = self._journal.recover()
        self.term = max(disk, rec_term) + 1
        self._journal.write_term(self.term)
        self._journal.start_term(self.term)
        self._seed_records(recs)
        self._ha_append("start", {"term": self.term})
        self._recorder.record("gateway-term", term=self.term,
                              warm=len(recs))

    def _ha_append(self, kind: str, data: Dict[str, Any]) -> None:
        if self._journal is None:
            return
        try:
            self._journal.append(kind, data)
        except OSError:
            # can't journal => can't lead: losing the shared log dir is
            # indistinguishable from being the partitioned side of a
            # split brain, so writes self-fence from here on (counted
            # per rejected frame in gateway_term_fenced)
            self._journal_dead = True

    def _ha_write_ok(self) -> bool:
        """May this gateway still apply session writes?  False once a
        HIGHER term is visible on disk (a standby promoted over us) or
        our own journal died — the structural split-brain guarantee."""
        if self._term_fenced or self._journal_dead:
            return False
        now = time.monotonic()
        if now - self._term_checked >= self._term_check_every:
            self._term_checked = now
            disk = self._journal.read_term() if self._journal else 0
            if disk > self.term:
                self._term_fenced = True
                self._recorder.record("gateway-fenced",
                                      term=self.term, disk=disk)
                print(f"[dcn] gateway term {self.term} fenced by "
                      f"on-disk term {disk}", flush=True)
                return False
        return True

    def _session_gate(self, ftype: int) -> None:
        """Pre-dispatch HA gate for SESSION verbs only (sessionless
        probes always answer).  An unpromoted standby refuses with a
        counted connection drop — the client's redial path then cycles
        to the next endpoint, never the terminal DcnRefused path — and
        a fenced stale-term gateway's writes/grants are counted rejects
        that are NEVER applied."""
        if ftype in (T_STATUS, T_PROFILE, T_METRICS, T_RLEASE,
                     T_RGRAD, T_RPRIO, T_SYNC, T_SSAMPLE, T_SMASS,
                     T_SPRIO, T_BYE):
            return
        if not self._serving:
            self.standby_refused += 1
            raise ConnectionError(
                "standby gateway: sessions refused before promotion")
        if not self._ha_write_ok():
            self.gateway_term_fenced += 1
            self._recorder.record("stale-term-write",
                                  ftype=ftype, term=self.term)
            raise ConnectionError("gateway term fenced")

    def _ha_ledger(self) -> Dict[str, int]:
        """ABSOLUTE cumulative ingest-side totals across terms: the
        journal carry (what previous terms accounted) plus this
        process's own counters — what the state records persist and the
        sync stream ships, so re-applying any suffix is idempotent."""
        led = {"ingested": int(self._ha_carry.get("ingested", 0)),
               "shed": int(self._ha_carry.get("shed", 0)),
               "quarantined": int(self._ha_carry.get("quarantined", 0)),
               "ingested_bytes":
                   int(self._ha_carry.get("ingested_bytes", 0)),
               "rejected_bytes":
                   int(self._ha_carry.get("rejected_bytes", 0)),
               "shed_bytes": int(self._ha_carry.get("shed_bytes", 0))}
        if self._flow is not None:
            led["ingested"] += int(self._flow.ingested_rows)
            led["shed"] += int(sum(self._flow.shed_rows.values()))
            # byte legs (ISSUE 18) ride the same absolute-cumulative
            # contract as the row legs, so re-applying any journal
            # suffix stays idempotent
            led["ingested_bytes"] += int(self._flow.ingested_bytes)
            led["rejected_bytes"] += int(self._flow.rejected_bytes)
            led["shed_bytes"] += int(self._flow.shed_bytes)
        with self._slots_lock:
            led["quarantined"] += int(sum(self.quarantined.values()))
        return led

    def _ha_note_state(self) -> None:
        """Rate-limited composite state record on the serve path: tick
        dedup high-waters, clock counters, the cumulative ledger and the
        failover-lost count — everything a warm restart or a promoting
        standby needs to continue the control plane without double
        counting.  One fsynced append per ``_ha_state_every`` window,
        amortized across every chunk in it (bench: gateway_ha_overhead)."""
        if not self._serving or self._journal_dead or self._term_fenced:
            return
        now = time.monotonic()
        if now - self._ha_state_last < self._ha_state_every:
            return
        self._ha_state_last = now
        with self._slots_lock:
            ticks = {str(s): int(q) for s, q in self._tick_seq.items()}
        self._ha_append("state", {
            "tick_seq": ticks,
            "clock": {
                "learner_step": int(self.clock.learner_step.value),
                "actor_step": int(self.clock.actor_step.value)},
            "chunks_in": int(self._ha_carry.get("chunks_in", 0))
            + self.chunks_in,
            "lost": self.failover_lost,
            "ledger": self._ha_ledger()})

    def _seed_records(self, recs: List[Dict[str, Any]]) -> None:
        """Apply journal/sync records to local control state.  Every
        field is an ABSOLUTE value applied through max(), so any replay
        — a restarted standby re-pulling from an old offset, a recovery
        scan over a file containing duplicates — lands exactly once."""
        for rec in recs:
            kind, data = rec.get("kind"), rec.get("data") or {}
            if kind == "slot":
                s = int(data.get("slot", -1))
                inc = int(data.get("inc", -1))
                if s >= 0:
                    with self._slots_lock:
                        if inc > self._inc_floor.get(s, -1):
                            self._inc_floor[s] = inc
            elif kind == "state":
                with self._slots_lock:
                    for s, q in (data.get("tick_seq") or {}).items():
                        si = int(s)
                        if int(q) > self._tick_seq.get(si, -1):
                            self._tick_seq[si] = int(q)
                led = data.get("ledger") or {}
                for k in ("ingested", "shed", "quarantined",
                          "ingested_bytes", "rejected_bytes",
                          "shed_bytes"):
                    v = int(led.get(k, 0))
                    if v > self._ha_carry.get(k, 0):
                        self._ha_carry[k] = v
                ci = int(data.get("chunks_in", 0))
                if ci > self._ha_carry.get("chunks_in", 0):
                    self._ha_carry["chunks_in"] = ci
                lost = int(data.get("lost", 0))
                if lost > self.failover_lost:
                    self.failover_lost = lost

    def _apply_record(self, rec: Dict[str, Any]) -> None:
        """Standby side: digest-check one pulled record, persist it to
        the applied-copy journal (dup seqs are no-ops) and seed state."""
        try:
            if rec.get("sha") != _rec_digest(
                    int(rec["seq"]), rec["kind"], rec["data"]):
                return
        except (KeyError, TypeError, ValueError):
            return
        if self._journal is not None and not self._journal.apply(rec):
            return
        self._seed_records([rec])

    def _ha_emit(self, stale: float) -> None:
        """The standby's health scalar: ``gateway/sync_stale`` is 1.0
        while the primary is unreachable and 0.0 when healthy — the
        telemetry DEFAULT_RULES ``gateway_failover`` alert fires on
        sustained staleness and RESOLVES once the promoted standby keeps
        reporting 0.  Non-HA fleets never report the tag, so the rule is
        inert there (absence rules never fire for never-seen tags)."""
        if self._ha_writer is None:
            return
        try:
            wall = time.time()
            self._ha_writer.scalar("gateway/sync_stale", float(stale),
                                   step=self._sync_seq, wall=wall)
            self._ha_writer.scalar("gateway/term", float(self.term),
                                   step=self._sync_seq, wall=wall)
            self._ha_writer.flush()
        except Exception:  # noqa: BLE001 - telemetry must not kill HA
            pass

    def _sync_once(self) -> bool:
        """One sessionless T_SYNC pull from the primary; returns False
        on any wire/reply failure (the promotion clock's input)."""
        timeout = max(0.5, self._gp.sync_s * 4)
        try:
            sock = socket.create_connection(self._sync_from,
                                            timeout=timeout)
        except OSError:
            return False
        bandwidth.register_socket(sock, "sync")
        try:
            sock.settimeout(timeout)
            _send_frame(sock, T_SYNC,
                        json.dumps({"since": self._sync_seq}).encode())
            rtype, payload = _recv_frame(sock)
            if rtype != T_SYNC:
                return False
            reply = json.loads(payload.decode())
        except (ConnectionError, OSError, ValueError):
            return False
        finally:
            try:
                sock.close()
            except OSError:
                pass
        if reply.get("error"):
            return False
        self._sync_term = max(self._sync_term, int(reply.get("term", 0)))
        for rec in reply.get("records", []):
            self._apply_record(rec)
        self._sync_seq = max(self._sync_seq,
                             int(reply.get("seq", self._sync_seq)))
        return True

    def _promote(self) -> None:
        """Fenced promotion: CAS-bump the on-disk term above everything
        this standby has seen (disk, stream, self), open the new term's
        WAL continuing the global seq numbering, and start serving.  Any
        resurrected predecessor now reads a higher term and fences."""
        disk = self._journal.read_term() if self._journal else 0
        new_term = max(disk, self._sync_term, self.term) + 1
        jr = GatewayJournal(self._ha_log_dir)
        jr.seq = self._journal.seq if self._journal else 0
        try:
            jr.write_term(new_term)
            jr.start_term(new_term)
        except OSError:
            # no shared log dir => cannot prove leadership => stay a
            # (non-serving) standby rather than risk split brain
            self._journal_dead = True
            return
        old, self._journal = self._journal, jr
        if old is not None:
            old.close()
        self.term = new_term
        self.promotions += 1
        self._role = "primary"
        self._serving = True
        self._ha_append("promote", {"term": new_term})
        self._ha_note_state()
        self.promoted.set()
        self._recorder.record("gateway-promoted", term=new_term)
        print(f"[dcn] standby promoted to gateway term {new_term}",
              flush=True)

    def _ha_loop(self) -> None:
        """Warm-standby loop: pull the journal stream on the sync
        cadence; once the pull has failed for one lease window, promote.
        After promotion the loop keeps journaling state and emitting the
        healthy scalar so the ``gateway_failover`` alert resolves."""
        gp = self._gp
        while not self._stop.is_set():
            if self._serving:
                self._ha_note_state()
                self._ha_emit(0.0)
            elif self._sync_once():
                self._last_sync_ok = time.monotonic()
                self._ha_emit(0.0)
            else:
                self._ha_emit(1.0)
                if (time.monotonic() - self._last_sync_ok) > gp.lease_s:
                    self._promote()
            self._stop.wait(gp.sync_s)

    def note_failover_lost(self, rows: int) -> None:
        """Count acked-but-undrained rows that died with the old
        primary's ingest queue.  Only the wiring that discards that
        queue knows the number (the drill, or a fleet restart path) —
        counting it HERE keeps the conservation ledger exact across a
        failover instead of letting the rows silently vanish."""
        self.failover_lost += int(rows)
        self._recorder.record("failover-lost", rows=int(rows))

    @property
    def flow(self):
        """The gateway's GatewayFlow plane (None when disabled) — read
        by drills (tools/chaos_soak.py conservation verdict) and tests."""
        return self._flow

    @property
    def active_slots(self) -> Dict[int, int]:
        """Snapshot of {slot: incarnation} for supervision/chaos asserts."""
        with self._slots_lock:
            return {s: inc for s, (inc, _c) in self._slots.items()}

    def status_snapshot(self) -> dict:
        """The live health plane's one read: slot states + incarnations +
        heartbeat ages, clocks, gateway counters, and whatever the owning
        topology's ``health`` provider adds (replay fill, ingest queue
        depth, restart budget, learner step rate).  Slot fields are taken
        under the registry lock so the snapshot is internally consistent;
        the health extras are best-effort reads of a live system."""
        now = time.monotonic()
        with self._slots_lock:
            slots = {
                str(s): {
                    "incarnation": inc,
                    "heartbeat_age": round(
                        now - self._last_seen.get(s, now), 3),
                }
                for s, (inc, _c) in self._slots.items()
            }
        snap = {
            "wall": time.time(),
            "uptime": round(now - self._born, 3),
            "learner_step": int(self.clock.learner_step.value),
            "actor_step": int(self.clock.actor_step.value),
            "stop": bool(self.clock.stop.is_set()),
            "local_actors": self.local_actors,
            "slots": slots,
            "connections": self.connections,
            "chunks_in": self.chunks_in,
            "fenced": self.fenced,
            "metrics_batches": self.metrics_batches,
            "metrics_rows": self.metrics_rows,
            "frames_rejected": self.frames_rejected,
            "quarantined": dict(self.quarantined),
        }
        if self._flow is not None:
            # flow-control plane (ISSUE 11): overload state + brownout
            # tier, per-slot credits/shed/drop-share and the
            # conservation ledger — fleet_top's ``flow:`` panel line
            snap["flow"] = self._flow.status_block(
                quarantined=sum(snap["quarantined"].values()))
        wire_blk = bandwidth.status_block()
        if wire_blk is not None:
            # bandwidth X-ray (ISSUE 18): per-link byte/frame totals,
            # bytes/transition + bytes/round, and the byte-ledger
            # verdict joined from the flow block's conservation —
            # fleet_top's ``wire:`` panel line
            if self._flow is not None:
                cons = snap.get("flow", {}).get("conservation", {})
                wire_blk["ledger"] = {
                    k: cons[k] for k in (
                        "acked_bytes", "ingested_bytes",
                        "rejected_bytes", "shed_bytes",
                        "accounted_bytes", "bytes_balanced")
                    if k in cons}
            snap["wire"] = wire_blk
        if self._replicas is not None:
            # replica plane (ISSUE 15): membership/generation/lease ages
            # + the fencing ledger — fleet_top's ``replicas:`` panel
            # line and the chaos drills' exact-counter verdicts
            snap["replicas"] = self._replicas.status_block()
        if self._shards is not None and hasattr(self._shards,
                                                "status_block"):
            # shard plane (ISSUE 20): membership/mass-share/lease ages
            # + the degradation ledger — fleet_top's ``shards:`` panel
            # line and the shard drills' exact-counter verdicts.  Only
            # the coordinator's registry has a status_block; shard
            # HOSTS (a LocalShard handler) report through their lease
            # renews instead.  Absent with sharding off: unsharded
            # peers observe zero new fields anywhere.
            snap["shards"] = self._shards.status_block()
        if self._ha:
            # gateway HA plane (ISSUE 16): role/term/sync lag + the
            # failover ledger — fleet_top's ``gateway:`` panel line and
            # the failover drill's exact-counter verdicts.  Absent with
            # HA off: pre-HA peers observe zero new fields anywhere.
            snap["gateway"] = {
                "role": self._role,
                "term": self.term,
                "serving": self._serving,
                "fenced": bool(self._term_fenced or self._journal_dead),
                "term_fenced": self.gateway_term_fenced,
                "standby_refused": self.standby_refused,
                "promotions": self.promotions,
                "failover_lost": self.failover_lost,
                "sync_served": self.sync_served,
                "sync_seq": self._sync_seq,
                "sync_term": self._sync_term,
                "sync_age": round(now - self._last_sync_ok, 3),
                "journal_seq": (self._journal.seq
                                if self._journal else 0),
                "journal_appends": (self._journal.appends
                                    if self._journal else 0),
                "recover_warnings": (self._journal.recover_warnings
                                     if self._journal else 0),
                "carry": {k: int(v)
                          for k, v in self._ha_carry.items()},
            }
        if self._health is not None:
            try:
                snap.update(self._health() or {})
            except Exception as e:  # noqa: BLE001 - health is best-effort
                snap["health_error"] = repr(e)
        return snap

    def _claim_slot(self, ind: Optional[int], incarnation: int,
                    conn: socket.socket) -> Optional[str]:
        """Register a remote actor's global slot; returns an error string
        on a conflict.  A slot held by a LOWER incarnation is not a
        conflict — it is this actor's own half-open predecessor (a
        partition or mid-RPC gateway blip left it behind), so the old
        connection is fenced off and the slot re-keyed; without this a
        reconnecting actor crash-loops against its own ghost until the
        RestartBudget drains (utils/supervision.py docstring).  Equal or
        lower incarnations refuse: duplicate live actors silently skew
        the fleet-wide Ape-X epsilon schedule.

        Known limit of wall-clock incarnation bases: a MISCONFIGURED
        genuine duplicate (two hosts claiming overlapping slot ranges)
        that starts later carries a higher incarnation and evicts the
        live owner; the evicted side reconnects below the thief, is
        refused, and its supervisor respawns it with a fresh higher
        base — mutual eviction that drains both RestartBudgets and
        fails both hosts fast with nonzero exits.  Noisy fail-fast, not
        the silent epsilon skew: distinguishing a live duplicate from a
        dead predecessor's replacement would need gateway-side liveness
        probing, which the idle-deadline reaper only provides after the
        fact."""
        if ind is None:
            return None
        evict: Optional[socket.socket] = None
        with self._slots_lock:
            if ind < self.local_actors:
                return (f"actor slot {ind} is local to the learner host "
                        f"(local_actors={self.local_actors})")
            if self._ha and incarnation <= self._inc_floor.get(ind, -1):
                # journal-seeded fencing (ISSUE 16): a zombie actor
                # process dialing the PROMOTED gateway with an
                # incarnation at or below the floor the old primary
                # journaled is its own fenced predecessor — refusing
                # here is the slot-fencing contract surviving failover
                return (f"actor slot {ind} incarnation {incarnation} "
                        f"fenced by journaled floor "
                        f"{self._inc_floor[ind]}")
            held = self._slots.get(ind)
            if held is not None:
                held_inc, held_conn = held
                if incarnation <= held_inc:
                    return (f"actor slot {ind} already connected "
                            f"(incarnation {incarnation} <= {held_inc})")
                evict = held_conn
                self.fenced += 1
                self._recorder.record("fence", slot=ind,
                                      old=held_inc, new=incarnation)
            self._slots[ind] = (incarnation, conn)
            self._last_seen[ind] = time.monotonic()
            if self._ha and incarnation > self._inc_floor.get(ind, -1):
                self._inc_floor[ind] = incarnation
        if evict is not None:
            # outside the lock: unblock the predecessor's serve thread;
            # its release is identity-checked so it cannot free OUR claim
            try:
                evict.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
        self._recorder.record("slot-claimed", slot=ind,
                              incarnation=incarnation)
        return None

    def _quarantine(self, slot: Optional[int], items: list) -> list:
        """The DCN leg of the ingest quarantine (utils/health.py):
        validate a decoded chunk per-transition and divert offenders to
        ``{log_dir}/quarantine/`` with a per-slot counter, so remote
        experience gets exactly the same admission control as the local
        spawn-queue path — and ``fleet_top`` can name the poisoning
        actor.  Returns the clean remainder (possibly empty)."""
        from pytorch_distributed_tpu.utils import health

        if not items or not health.quarantine_active():
            return items
        src = f"slot{slot}" if slot is not None else "anon"
        validator = self._validators.get(src)
        if validator is None:
            validator = self._validators[src] = health.ChunkValidator()
        items, bad = validator.filter(items)
        if bad:
            with self._slots_lock:
                self.quarantined[src] = (self.quarantined.get(src, 0)
                                         + len(bad))
            self._recorder.record("chunk-quarantined", slot=slot,
                                  n=len(bad), reason=bad[0][2])
            health.get_quarantine(f"gateway-{src}").put(
                bad, trace_id=getattr(items, "trace_id", 0))
        return items

    def _fresh_tick(self, slot: Optional[int], seq: Optional[int]) -> bool:
        """Dedup retransmitted T_TICKs: a tick whose T_CLOCK ack was lost
        mid-blip is resent after reconnect, and applying it twice would
        inflate the fleet-wide actor-step count (the learner's
        max_replay_ratio gate) and the episode stats.  Seq numbers are
        wall-clock-based like incarnations, so a replacement process
        starts above its predecessor's high-water mark.  The map is not
        cleared on slot release — it must outlive fencing and reconnects
        — but a gateway RESTART forgets it: an ack lost across a restart
        is the one residual double-count window (failure model)."""
        if slot is None or seq is None:
            return True
        with self._slots_lock:
            if seq <= self._tick_seq.get(slot, -1):
                return False
            self._tick_seq[slot] = seq
            return True

    def _release_slot(self, slot: Optional[int],
                      conn: socket.socket) -> None:
        with self._slots_lock:
            self._conns.discard(conn)
            if slot is None:
                return
            held = self._slots.get(slot)
            if held is not None and held[1] is conn:
                del self._slots[slot]
                self._recorder.record("slot-released", slot=slot,
                                      incarnation=held[0])

    def _serve(self, conn: socket.socket, addr) -> None:
        slot: Optional[int] = None
        if self._idle_deadline and self._idle_deadline > 0:
            conn.settimeout(self._idle_deadline)
        try:
            with conn:
                while not self._stop.is_set():
                    ftype, payload = _recv_frame(conn)
                    if self._ha:
                        # HA gate first: an unpromoted standby or a
                        # fenced stale-term gateway must refuse session
                        # verbs BEFORE any of their side effects
                        self._session_gate(ftype)
                    if ftype not in (T_STATUS, T_PROFILE, T_METRICS,
                                     T_RLEASE, T_RGRAD, T_RPRIO, T_SYNC,
                                     T_SSAMPLE, T_SMASS, T_SPRIO):
                        # STATUS/PROFILE/METRICS probes and the replica
                        # plane are outside the wire fault plane: a
                        # monitor polling the gateway must neither shift
                        # a deterministic drill's frame schedule nor
                        # absorb a fault meant for session traffic, and
                        # replica drills inject at the replica driver
                        # (REPLICA_FAULTS) where kill/hang/crash are the
                        # real failure modes
                        payload = self._faults.frame(payload)
                    if slot is not None:
                        # plain GIL-atomic write: heartbeat-age reads in
                        # status_snapshot tolerate a one-frame race
                        self._last_seen[slot] = time.monotonic()
                    if ftype == T_BYE:
                        return
                    elif ftype == T_STATUS:
                        # health probe: answered before any HELLO — a
                        # monitoring CLI must never consume an actor slot
                        self.status_served += 1
                        _send_frame(conn, T_STATUS, json.dumps(
                            self.status_snapshot()).encode())
                    elif ftype == T_PROFILE:
                        # on-demand profiling, sessionless like STATUS.
                        # Blocking THIS serve thread for the bounded
                        # window is free concurrency-wise (one thread
                        # per connection); concurrent requests are
                        # refused by the provider's one-window lock.
                        msg = self._json(payload) if payload else {}
                        if self._profiler is None:
                            reply = {"error": "no profiler wired on "
                                              "this gateway"}
                        else:
                            try:
                                reply = self._profiler(msg) or {}
                            except Exception as e:  # noqa: BLE001
                                reply = {"error":
                                         f"profiler failed: {e!r}"}
                        self.profiles_served += 1
                        self._recorder.record(
                            "profile-served",
                            ok=("error" not in reply),
                            seconds=msg.get("seconds"))
                        _send_frame(conn, T_PROFILE,
                                    json.dumps(reply).encode())
                    elif ftype == T_METRICS:
                        # fleet-host scalar push, sessionless like
                        # STATUS.  The reply always carries the
                        # gateway's wall clock — the pusher's NTP-style
                        # offset estimator reads it off the RPC
                        # midpoint, which is what lets remote rows land
                        # on the learner host's time axis.
                        msg = self._json(payload) if payload else {}
                        if self._metrics_sink is None:
                            reply = {"accepted": 0,
                                     "error": "no metrics sink wired "
                                              "on this gateway"}
                        else:
                            try:
                                n = int(self._metrics_sink(msg) or 0)
                                reply = {"accepted": n}
                                self.metrics_rows += n
                            except Exception as e:  # noqa: BLE001
                                reply = {"accepted": 0,
                                         "error":
                                         f"metrics sink failed: {e!r}"}
                        self.metrics_batches += 1
                        reply["wall"] = time.time()
                        if self._flow is not None \
                                and self._flow.governor.tier >= 1:
                            # brownout tier 1: the telemetry rung.  The
                            # reply tells the pusher to shed ITS side
                            # (counted there) so metrics traffic stops
                            # competing with the experience plane.
                            reply["brownout"] = self._flow.governor.tier
                        _send_frame(conn, T_METRICS,
                                    json.dumps(reply).encode())
                    elif ftype == T_RLEASE:
                        # replica lease verbs (ISSUE 15), sessionless-
                        # adjacent like STATUS: no actor-slot claim —
                        # the lease TABLE is the membership
                        msg = self._json(payload) if payload else {}
                        if self._replicas is None:
                            reply = {"status": "error",
                                     "error": "no replica registry "
                                              "wired on this gateway"}
                        else:
                            try:
                                reply = self._replicas.handle_lease(msg)
                            except Exception as e:  # noqa: BLE001
                                reply = {"status": "error",
                                         "error": f"registry failed: "
                                                  f"{e!r}"}
                        _send_frame(conn, T_RLEASE,
                                    json.dumps(reply).encode())
                    elif ftype == T_RGRAD:
                        # the generation-stamped allreduce round:
                        # blocking THIS serve thread until the round
                        # completes (or fences) is free concurrency-wise
                        # — one thread per connection, and the registry
                        # bounds the wait with the round-stall rule
                        if self._replicas is None:
                            _send_frame(conn, T_RGRAD,
                                        _pack_round_reply(RSTAT_NOREG))
                        else:
                            _send_frame(conn, T_RGRAD,
                                        self._replicas.handle_round(
                                            payload))
                    elif ftype == T_RPRIO:
                        # out-of-round |TD| write-back merge with
                        # last-generation-wins fencing (the zombie
                        # replica's writes die HERE, counted)
                        if self._replicas is None:
                            reply = {"status": "error",
                                     "error": "no replica registry "
                                              "wired on this gateway"}
                        else:
                            reply = self._replicas.handle_prio(payload)
                        _send_frame(conn, T_RPRIO,
                                    json.dumps(reply).encode())
                    elif ftype == T_SSAMPLE:
                        # shard-local sample leg of the two-level draw
                        # (ISSUE 20), sessionless-adjacent like the
                        # replica verbs; the codec and the generation
                        # fence live in memory/shard_plane.py — the
                        # handler object owns both sides of the frame
                        if self._shards is None or not hasattr(
                                self._shards, "handle_ssample"):
                            _send_frame(conn, T_SSAMPLE,
                                        _pack_noshard_reply())
                        else:
                            _send_frame(conn, T_SSAMPLE,
                                        self._shards.handle_ssample(
                                            payload))
                    elif ftype == T_SMASS:
                        # shard membership verbs (coordinator) or the
                        # mass poll (shard host) — plain JSON either way
                        msg = self._json(payload) if payload else {}
                        if self._shards is None:
                            reply = {"status": "error",
                                     "error": "no shard plane wired "
                                              "on this gateway"}
                        else:
                            try:
                                reply = self._shards.handle_smass(msg)
                            except Exception as e:  # noqa: BLE001
                                reply = {"status": "error",
                                         "error": f"shard plane "
                                                  f"failed: {e!r}"}
                        _send_frame(conn, T_SMASS,
                                    json.dumps(reply).encode())
                    elif ftype == T_SPRIO:
                        # cross-shard |TD| write-back with
                        # last-generation-wins fencing (a zombie
                        # learner's writes die HERE, counted)
                        if self._shards is None or not hasattr(
                                self._shards, "handle_sprio"):
                            reply = {"status": "error",
                                     "error": "no shard plane wired "
                                              "on this gateway"}
                        else:
                            reply = self._shards.handle_sprio(payload)
                        _send_frame(conn, T_SPRIO,
                                    json.dumps(reply).encode())
                    elif ftype == T_SYNC:
                        # gateway HA control-plane pull (ISSUE 16),
                        # sessionless like STATUS: the warm standby asks
                        # for journal records past its applied offset
                        msg = self._json(payload) if payload else {}
                        if (not self._ha or self._journal is None
                                or not self._serving
                                or self._term_fenced):
                            reply = {"error":
                                     "no HA journal serving on this "
                                     "gateway"}
                        else:
                            since = int(msg.get("since", 0))
                            base, recs = \
                                self._journal.records_since(since)
                            reply = {"term": self.term,
                                     "seq": self._journal.seq,
                                     "base_seq": base,
                                     "records": recs,
                                     "wall": time.time()}
                        self.sync_served += 1
                        _send_frame(conn, T_SYNC,
                                    json.dumps(reply).encode())
                    elif ftype == T_EXP:
                        # byte-ledger granularity is the FRAME: every
                        # acked EXP payload lands in exactly one of
                        # {rejected, shed, ingested} byte buckets
                        # (quarantine is a row-level refinement inside
                        # the ingested frame).  Header-free, matching
                        # the client's acked_bytes count at encode.
                        exp_nbytes = len(payload)
                        try:
                            items = decode_chunk(payload)
                        except ConnectionError:
                            raise
                        except ValueError as e:
                            # WELL-FRAMED but schema-invalid (missing/
                            # truncated/wrong-dtype columns): a malformed
                            # peer.  Dropping the connection would only
                            # make it retransmit the same poison until
                            # its retransmit cap kills it — count, warn,
                            # ack, and drop the FRAME instead; the
                            # session survives.
                            self.frames_rejected += 1
                            if self._flow is not None:
                                # acked below — the frame's bytes must
                                # land in the rejected ledger bucket
                                self._flow.note_rejected_bytes(exp_nbytes)
                            self._recorder.record("frame-rejected",
                                                  slot=slot,
                                                  error=str(e)[:200])
                            if self.frames_rejected <= 3:
                                print(f"[dcn] rejected malformed EXP "
                                      f"frame from slot {slot}: {e}",
                                      flush=True)
                            _send_frame(conn, T_CLOCK,
                                        self._clock_payload(slot))
                            continue
                        except Exception as e:
                            # byte-level corruption np.load itself chokes
                            # on: drop the connection — the client's
                            # retransmit carries a clean copy (the wire
                            # failure model; never decode garbage)
                            raise ConnectionError(
                                f"undecodable EXP frame: {e!r}")
                        if isinstance(items, tracing.TracedChunk) \
                                and not (self._flow is not None
                                         and self._flow.governor.tier
                                         >= 2):
                            # actor flush -> gateway receipt: the wire
                            # hop.  Suppressed at brownout tier >= 2
                            # off the gateway's OWN governor (the
                            # process-local flow.trace_shed latch is
                            # only ever set by a DcnClient, which the
                            # gateway process doesn't host) — covers
                            # chunks from actors that haven't latched
                            # the tier yet.
                            self._tracer.record_hop("gateway", items.born,
                                                    items.trace_id)
                        admitted = (self._flow is None
                                    or self._flow.admit(
                                        slot, len(items),
                                        nbytes=exp_nbytes))
                        if admitted:
                            if self._flow is not None:
                                # ingested-BYTES counts the whole
                                # admitted frame even if quarantine
                                # empties it (the rows land in the
                                # quarantined row bucket; the bytes
                                # stay frame-granular)
                                self._flow.note_ingested_bytes(
                                    exp_nbytes)
                            items = self._quarantine(slot, items)
                        else:
                            # the gateway's ONE declared experience shed
                            # point (brownout tier 3, bucket dry —
                            # counted + recorded in GatewayFlow.admit):
                            # ack so the peer doesn't retransmit the
                            # very load being shed
                            items = []
                        if items:
                            bandwidth.note_transitions(len(items))
                            if self._flow is not None:
                                # ingested = admitted AND clean of the
                                # quarantine: each row lands in exactly
                                # one conservation bucket
                                self._flow.note_ingested(len(items))
                            try:
                                self.put_chunk(items)
                            except ValueError:
                                # memory queue already closed: the run is
                                # over; answer with the stop-carrying
                                # clock instead of dying with a traceback
                                pass
                        self.chunks_in += 1
                        _send_frame(conn, T_CLOCK, self._clock_payload(slot))
                        if self._ha:
                            self._ha_note_state()
                    elif ftype == T_GETP:
                        try:
                            (min_version,) = struct.unpack("!Q", payload)
                        except struct.error as e:
                            raise ConnectionError(
                                f"undecodable GETP frame: {e}")
                        got = self.param_store.fetch(min_version)
                        if got is None:
                            _send_frame(conn, T_PARAMS,
                                        struct.pack("!Q", 0))
                        else:
                            flat, version = got
                            _send_frame(
                                conn, T_PARAMS,
                                struct.pack("!Q", version)
                                + np.ascontiguousarray(
                                    flat, dtype=np.float32).tobytes())
                    elif ftype == T_PING:
                        # the ack carries the slot's fresh credit grant:
                        # heartbeats are how a credit-blocked client
                        # learns it may drain its ring again (throttled
                        # never reads as dead OR stays blocked forever)
                        _send_frame(conn, T_CLOCK, self._clock_payload(slot))
                    elif ftype == T_TICK:
                        msg = self._json(payload)
                        try:
                            steps = int(msg.get("actor_steps", 0))
                            seq = msg.get("seq")
                            seq = int(seq) if seq is not None else None
                            kv = {k: float(v) for k, v
                                  in (msg.get("stats") or {}).items()}
                        except (TypeError, ValueError) as e:
                            raise ConnectionError(
                                f"undecodable TICK frame: {e}")
                        if self._fresh_tick(slot, seq):
                            if steps:
                                self.clock.add_actor_steps(steps)
                            if kv:
                                self.actor_stats.add(**kv)
                        if self._flow is not None:
                            # cumulative client flow counters (minted/
                            # dropped/buffered) — idempotent outside the
                            # dedup gate, so a retransmitted tick can
                            # never double-count drops
                            self._flow.on_client_report(
                                slot, msg.get("flow"))
                        _send_frame(conn, T_CLOCK, self._clock_payload(slot))
                        if self._ha:
                            self._ha_note_state()
                    elif ftype == T_HELLO:
                        msg = self._json(payload)
                        try:
                            ind = msg.get("process_ind")
                            ind = int(ind) if ind is not None else None
                            inc = int(msg.get("incarnation", 0))
                        except (TypeError, ValueError) as e:
                            raise ConnectionError(
                                f"undecodable HELLO frame: {e}")
                        err = self._claim_slot(ind, inc, conn)
                        if err is not None:
                            reply = json.loads(self._clock_payload())
                            reply["error"] = err
                            _send_frame(conn, T_CLOCK,
                                        json.dumps(reply).encode())
                            return
                        slot = ind
                        # the accept loop registered this conn slotless
                        bandwidth.register_socket(conn, "gateway", slot)
                        if self._ha and ind is not None:
                            # journal the claim (absolute incarnation:
                            # idempotent) so the standby fences stale
                            # actor incarnations across a failover
                            self._ha_append("slot",
                                            {"slot": ind, "inc": inc})
                        _send_frame(conn, T_CLOCK, self._clock_payload(slot))
                    else:
                        raise ConnectionError(f"bad frame type {ftype}")
        except (ConnectionError, OSError):
            return  # peer went away (or idled out); churn is expected
        finally:
            self._release_slot(slot, conn)

    @staticmethod
    def _json(payload: bytes) -> dict:
        try:
            return json.loads(payload.decode())
        except (ValueError, UnicodeDecodeError) as e:
            raise ConnectionError(f"undecodable control frame: {e}")

    def close(self) -> None:
        self._stop.set()
        try:
            self._srv.close()
        except OSError:
            pass
        # the kernel only releases the listening port once the accept
        # thread leaves its accept() syscall — join it, or an immediate
        # rebind on the same port (restart_gateway) races into EADDRINUSE
        self._accept_thread.join(2.0)
        if self._ha_thread is not None:
            self._ha_thread.join(max(2.0, self._gp.sync_s * 4))
        if self._journal is not None:
            self._journal.close()
        with self._slots_lock:
            conns = list(self._conns)
        for c in conns:
            # unblock serve threads parked in recv so join() is prompt
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
        for t in self._threads:
            t.join(1.0)


def feed_queue_of(memory_handles) -> Callable[[list], None]:
    """The gateway->memory bridge: single-owner learner-side memories
    (QueueOwner, DeviceReplayIngest) drain a spawn queue of
    ``[(Transition, priority)]`` chunks; remote chunks enter that same
    queue.  Multi-writer shared rings (SharedReplay/NativeRingReplay) take
    direct feeds — their ``feed`` is already cross-process safe."""
    learner_side = memory_handles.learner_side
    if getattr(learner_side, "_q", None) is not None:
        # late-bound: Topology._use_thread_queue may swap the queue object
        # between construction and run
        def _enqueue(items: list) -> None:
            learner_side._q.put(items)
        return _enqueue

    def _direct(items: list) -> None:
        if isinstance(items, tracing.TracedChunk):
            # multi-writer rings feed inline on the serve thread — the
            # "feed" hop collapses into the gateway receipt, record it so
            # the trace still closes for shared-ring memory types
            tracing.get_tracer("feeder").record_hop(
                "feed", items.born, items.trace_id)
        for t, p in items:
            learner_side.feed(t, p)
    return _direct


# ---------------------------------------------------------------------------
# health-plane client
# ---------------------------------------------------------------------------

def _sessionless_rpc(address: Tuple[str, int], ftype: int, payload: bytes,
                     timeout: float, what: str,
                     retry_after_send: bool = True) -> dict:
    """Shared core of the sessionless helpers (ISSUE 16 satellite):
    one bounded round-trip on a fresh connection, with a SINGLE retry
    so a monitor probing a half-dead gateway mid-failover — one that
    accepts the connection and never replies — gets a clean
    ConnectionError after ~2 timeouts instead of wedging forever.  The
    per-call ``settimeout`` bounds every recv; the retry opens a fresh
    connection (the promoted standby may be answering by then).
    ``retry_after_send`` False restricts the retry to connect-phase
    failures for verbs whose server-side work must not run twice
    (T_PROFILE holds the one-window profiler lock)."""
    last: Optional[BaseException] = None
    for attempt in (0, 1):
        try:
            sock = socket.create_connection(address, timeout=timeout)
            bandwidth.register_socket(sock, "probe")
        except OSError as e:
            last = e
            if attempt == 0:
                time.sleep(min(0.2, timeout / 10.0))
            continue
        sent = False
        try:
            sock.settimeout(timeout)
            _send_frame(sock, ftype, payload)
            sent = True
            rtype, reply = _recv_frame(sock)
            if rtype != ftype:
                raise ConnectionError(
                    f"expected {what} reply, got frame type {rtype}")
            try:
                return json.loads(reply.decode())
            except (ValueError, UnicodeDecodeError) as e:
                raise ConnectionError(f"undecodable {what} reply: {e}")
        except (ConnectionError, OSError) as e:
            last = e
            if attempt == 1 or (sent and not retry_after_send):
                raise
            time.sleep(min(0.2, timeout / 10.0))
        finally:
            try:
                sock.close()
            except OSError:
                pass
    raise ConnectionError(f"{what} request to {address} failed: {last!r}")


def fetch_status(address: Tuple[str, int], timeout: float = 5.0) -> dict:
    """One STATUS round-trip against a gateway — the read side of the
    live health plane (tools/fleet_top.py).  Deliberately sessionless:
    no HELLO, no slot claim, a fresh connection per probe so a monitor
    keeps working across gateway restarts exactly when it matters most.
    Every socket operation is bounded by ``timeout`` and the probe is
    retried ONCE on a fresh connection (a gateway mid-failover may
    accept and die before replying).  Raises ConnectionError/OSError
    when the gateway stays unreachable."""
    return _sessionless_rpc(address, T_STATUS, b"", timeout, "T_STATUS")


def fetch_profile(address: Tuple[str, int], seconds: float = 3.0,
                  label: Optional[str] = None, role: str = "learner",
                  timeout: Optional[float] = None) -> dict:
    """One T_PROFILE round-trip: trigger a bounded XLA profiler window
    on the learner host and return the reply ({"trace_dir", "seconds"}
    on success, {"error": ...} otherwise).  Sessionless like
    ``fetch_status`` — no HELLO, no slot claim — and sits OUTSIDE the
    fault-injection plane, so profiling a drilled fleet never shifts
    the drill schedule.  The reply wait covers the window plus generous
    slack: the process's FIRST-ever profiler session pays a one-time
    init that can exceed a minute on a saturated small host
    (utils/perf.prewarm_profiler amortizes it at fleet startup when
    the perf plane is enabled, but a bare fleet stays cold until the
    first request).  The server clamps ``seconds``
    (PerfParams.profile_window_max), so a typo'd duration errs on the
    reply arriving early, not never."""
    if timeout is None:
        timeout = float(seconds) + 180.0
    msg: Dict[str, Any] = {"seconds": float(seconds), "role": role}
    if label is not None:
        msg["label"] = str(label)
    # retry only covers the connect phase: once the request is on the
    # wire the server may already hold the one-window profiler lock, and
    # a blind retry would answer "profiler busy" instead of the result
    return _sessionless_rpc(address, T_PROFILE, json.dumps(msg).encode(),
                            timeout, "T_PROFILE", retry_after_send=False)


def push_metrics(address: Tuple[str, int], rows: list,
                 offset: Optional[float] = None,
                 host: Optional[str] = None,
                 timeout: float = 10.0) -> dict:
    """One T_METRICS round-trip: push a batch of scalar rows (the
    MetricsWriter JSONL schema — plain dicts) into the learner-host
    aggregator.  Sessionless like ``fetch_status`` — no HELLO, no slot
    claim — and OUTSIDE the fault-injection plane, so the telemetry
    path never shifts a drill schedule.  ``offset`` is the pusher's
    estimated clock offset to the gateway (seconds to ADD to this
    host's walls); the reply carries ``accepted`` and the gateway's
    ``wall`` for the next offset estimate
    (utils/telemetry.MetricsPusher owns the estimator and cadence)."""
    msg: Dict[str, Any] = {"rows": list(rows)}
    if offset is not None:
        msg["offset"] = float(offset)
    if host is not None:
        msg["host"] = str(host)
    # full single-retry: re-pushing the same rows is at worst a
    # duplicate scalar sample on the same wall clock, and the pusher's
    # own catch-up window already tolerates that; wedging the stats
    # thread on a half-dead gateway is the failure that matters
    return _sessionless_rpc(address, T_METRICS, json.dumps(msg).encode(),
                            timeout, "T_METRICS")


# ---------------------------------------------------------------------------
# actor-host client + adapters
# ---------------------------------------------------------------------------

def redial_backoff(rng, prev: float, cap: float = 1.0,
                   base: float = 0.05) -> float:
    """Decorrelated-jitter backoff (the AWS 'decorrelated jitter'
    scheme): next delay is uniform in ``[base, prev * 3]``, capped.
    Drawn from the CLIENT'S OWN seeded RNG stream — the fix for the
    reconnect thundering herd: the old deterministic doubling gave
    every client the identical redial schedule, so N replicas killed
    by one fault redialled the gateway in lockstep.  Seeding by slot
    keeps seeded ``DCN_FAULTS`` drills reproducible (the schedule is a
    pure function of the slot, not of wall clock) while two clients
    with different slots spread their redial times
    (tests/test_replicas.py asserts both properties)."""
    hi = max(prev * 3.0, base * 1.001)
    return float(min(cap, rng.uniform(base, hi)))


class DcnDisconnected(ConnectionError):
    """Terminal session loss: the reconnect budget is spent (or the
    client is closing).  Subclasses ConnectionError so transport-level
    best-effort paths (final flushes) swallow it, while the actor's main
    loop surfaces it as a nonzero exit for the RestartBudget."""


class DcnRefused(RuntimeError):
    """The gateway answered the HELLO with an error (slot conflict,
    local-slot claim).  A distinct type so supervisors can classify it
    as a session condition without catching unrelated RuntimeErrors —
    notably faults.InjectedCrash, which must never be mistaken for a
    network problem."""


class DcnClient:
    """One connection to the gateway, shared by the adapters of one actor
    process.  All requests are synchronous request/reply under a lock;
    every reply refreshes the cached learner clock.

    A send/recv failure mid-RPC enters the reconnect path: redial with
    exponential backoff (bounded by ``reconnect_timeout``), re-HELLO with
    a bumped incarnation — fencing off this client's own half-open
    predecessor on the gateway — then retransmit the one unacknowledged
    frame.  The caller never observes the blip; a terminal failure raises
    ``DcnDisconnected`` and latches ``disconnected``.

    ``stop`` and ``disconnected`` are disjoint: ``stop`` means the
    learner's clock declared the run over (exit 0); ``disconnected``
    means the session died (exit nonzero, supervision restarts us).

    A background heartbeat thread pings after ``heartbeat_interval`` idle
    seconds so a partitioned gateway is detected (and reconnected to)
    even while the actor is busy between RPCs, and so the gateway's idle
    deadline never reaps a healthy-but-quiet actor.
    """

    def __init__(self, address: Tuple[str, int], process_ind: int = 0,
                 connect_timeout: float = 60.0, retries: int = 20,
                 incarnation: Optional[int] = None,
                 heartbeat_interval: Optional[float] = None,
                 reply_deadline: Optional[float] = None,
                 reconnect_timeout: Optional[float] = None,
                 faults: Optional[FaultInjector] = None):
        # ordered endpoint list (ISSUE 16): a single ``(host, port)`` is
        # the pre-HA contract, byte-identical behaviour; a list (or a
        # "h:p,h:p" string) dials in order, and the redial path cycles
        # to the NEXT endpoint on failure — failover to the promoted
        # standby rides the exact PR-1 re-HELLO/incarnation/
        # unacked-resend machinery, and the PR-11 cumulative flow
        # counters make the resend idempotent across gateways.
        self.endpoints = parse_endpoints(address) or [address]
        self._ep = 0
        self.failovers = 0
        self.address = self.endpoints[0]
        self.process_ind = process_ind
        self._lock = threading.RLock()
        self.learner_step = 0
        self.stop = threading.Event()          # learner said stop (T_CLOCK)
        self.disconnected = threading.Event()  # session terminally lost
        # wall-clock-derived base so a REPLACEMENT process (fresh object,
        # no memory of its predecessor's count) still fences a half-open
        # slot left by the old incarnation; reconnects bump it by 1
        self.incarnation = (int(incarnation) if incarnation is not None
                            else time.time_ns() // 1_000_000)
        # tick dedup sequence, same wall-clock base trick: the gateway
        # drops a retransmitted tick (seq <= its per-slot high-water)
        # instead of double-counting actor steps/stats, and a replacement
        # process's fresh counter still lands above its predecessor's
        self._tick_seq = time.time_ns() // 1_000_000
        self.reconnects = 0
        # ---- flow control (ISSUE 11, utils/flow.py): ``credits`` is
        # the gateway's latest per-ack grant — None means the gateway
        # sent no credit field (healthy state, or a pre-flow gateway):
        # unlimited, the exact pre-ISSUE-11 behaviour.  At grant 0 the
        # client parks chunks in a bounded drop-oldest ring instead of
        # blocking the actor (newest experience wins; drops counted +
        # provenance-stamped) and keeps heartbeating, so throttled
        # never reads as dead and never deadlocks.
        self._flow_params = flow.resolve_flow()
        self.credits: Optional[int] = None
        self.flow_ring = flow.DropOldestRing(
            self._flow_params.client_ring, owner=process_ind)
        self.flow_minted_rows = 0   # rows offered to send_chunk
        self.flow_acked_rows = 0    # rows the wire acknowledged
        self.flow_acked_bytes = 0   # EXP payload bytes acknowledged
        self._flow_blocked_logged = False
        # estimated wall-clock offset to the gateway host (seconds to ADD
        # to local time.time() to land on the gateway's clock), derived
        # NTP-style from T_CLOCK replies' ``wall`` against the RPC
        # midpoint and EWMA-smoothed; recorded as ``clock_sync`` flight-
        # recorder events so tools/timeline.py can align this host's
        # blackbox/metrics rows onto the learner-host clock
        self.clock_offset: Optional[float] = None
        self._offset_logged: Optional[float] = None
        self._closed = False
        self._faults = (faults if faults is not None
                        else FaultInjector.from_env("client"))
        self._heartbeat_interval = (
            _env_float("DCN_HEARTBEAT_INTERVAL", 10.0)
            if heartbeat_interval is None else heartbeat_interval)
        self._reply_deadline = (
            _env_float("DCN_REPLY_DEADLINE", 180.0)
            if reply_deadline is None else reply_deadline)
        self._reconnect_timeout = (
            _env_float("DCN_RECONNECT_TIMEOUT", 30.0)
            if reconnect_timeout is None else reconnect_timeout)
        self._recorder = flight_recorder.get_recorder(
            f"dcn-client-{process_ind}")
        # slot-seeded redial jitter stream (see redial_backoff): each
        # slot's backoff schedule is deterministic in isolation but
        # decorrelated from its neighbours', so a mass disconnect never
        # redials the gateway in lockstep
        self._redial_rng = np.random.default_rng((0xDC2, process_ind))
        self._last_rpc = time.monotonic()
        deadline = time.monotonic() + connect_timeout
        delay = 0.1
        while True:
            try:
                self.address = self.endpoints[self._ep]
                self._sock = socket.create_connection(self.address,
                                                      timeout=30.0)
                bandwidth.register_socket(self._sock, "client",
                                          process_ind)
                break
            except OSError:
                if time.monotonic() > deadline or retries <= 0:
                    raise
                retries -= 1
                # cycle the endpoint list: the next dial may be the
                # standby already serving (no-op with one endpoint)
                self._ep = (self._ep + 1) % len(self.endpoints)
                time.sleep(delay)
                delay = min(delay * 2, 2.0)
        self._configure(self._sock)
        self._request(T_HELLO, self._hello_payload())
        self._hb_stop = threading.Event()
        self._hb_thread: Optional[threading.Thread] = None
        if self._heartbeat_interval and self._heartbeat_interval > 0:
            self._hb_thread = threading.Thread(
                target=self._heartbeat_loop,
                name=f"dcn-heartbeat-{process_ind}", daemon=True)
            self._hb_thread.start()

    # -- session plumbing ---------------------------------------------------

    def _configure(self, sock: socket.socket) -> None:
        # bounded reply wait: legitimate backpressure stalls under this
        # deadline; a frozen/partitioned peer trips it into the reconnect
        # path instead of stalling the actor forever (<=0 restores the
        # old unbounded-blocking behaviour)
        sock.settimeout(self._reply_deadline
                        if self._reply_deadline and self._reply_deadline > 0
                        else None)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)

    def _hello_payload(self) -> bytes:
        return json.dumps({"role": "actor",
                           "process_ind": self.process_ind,
                           "incarnation": self.incarnation}).encode()

    def _handle_reply(self, rtype: int, rpayload: bytes,
                      rpc_mid: Optional[float] = None) -> None:
        if rtype != T_CLOCK:
            return
        msg = json.loads(rpayload.decode())
        if rpc_mid is not None and "wall" in msg:
            sample = float(msg["wall"]) - rpc_mid
            self.clock_offset = (sample if self.clock_offset is None
                                 else 0.9 * self.clock_offset
                                 + 0.1 * sample)
            if (self._offset_logged is None
                    or abs(self.clock_offset
                           - self._offset_logged) > 0.05):
                # logged on first estimate and on >50 ms drift — the
                # timeline reads the LAST clock_sync of the role's ring
                self._offset_logged = self.clock_offset
                self._recorder.record(
                    "clock_sync", offset=round(self.clock_offset, 6),
                    slot=self.process_ind)
        self.learner_step = int(msg["learner_step"])
        if self._flow_params.enabled:
            # absent credit field = healthy/legacy gateway = unlimited
            c = msg.get("credits")
            self.credits = int(c) if c is not None else None
            tier = int(msg.get("brownout", 0) or 0)
            if tier != flow.brownout_tier():
                # latch the ladder tier for this process's shed hooks
                # (RemoteStats / QueueFeeder trace minting)
                flow.set_brownout(tier)
                self._recorder.record("brownout", tier=tier,
                                      slot=self.process_ind)
        if msg.get("stop"):
            self.stop.set()
        if "error" in msg:  # e.g. actor-slot conflict at HELLO
            self.disconnected.set()
            raise DcnRefused(f"gateway refused: {msg['error']}")

    def _terminal(self, why: str) -> DcnDisconnected:
        # a close()-initiated abort is not a session LOSS: latching
        # ``disconnected`` here would let a heartbeat racing a clean
        # shutdown flip a run-complete exit into EXIT_DISCONNECTED
        # (fleet._remote_actor_main reads the flag after close())
        if not self._closed:
            self.disconnected.set()
            # the actor is about to exit EXIT_DISCONNECTED: leave the
            # post-mortem NOW, while the session history is still in
            # memory (utils/flight_recorder.py failure paths)
            self._recorder.record("dcn-terminal", slot=self.process_ind,
                                  why=why, reconnects=self.reconnects)
            flight_recorder.dump_all(
                f"DcnDisconnected slot {self.process_ind}: {why}")
        return DcnDisconnected(
            f"DCN session to {self.address} lost (slot "
            f"{self.process_ind}): {why}")

    def _reconnect(self) -> Tuple[int, bytes]:
        """Redial + re-HELLO under the request lock; returns the HELLO
        reply on success, raises DcnDisconnected when the budget is spent
        (or the client is stopping — with the run over or the process
        closing there is nothing left to deliver)."""
        try:
            self._sock.close()
        except OSError:
            pass
        deadline = time.monotonic() + self._reconnect_timeout
        delay = 0.05
        while True:
            if self._closed:
                raise self._terminal("client closed")
            if self.stop.is_set():
                raise self._terminal("stop already set")
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise self._terminal(
                    f"reconnect budget ({self._reconnect_timeout:.1f}s) "
                    f"exhausted")
            addr = self.endpoints[self._ep]
            try:
                sock = socket.create_connection(
                    addr, timeout=max(0.1, min(5.0, remaining)))
            except OSError:
                # failover (ISSUE 16): cycle to the next endpoint — a
                # dead primary's slot in the list is skipped within one
                # backoff step (no-op with a single endpoint)
                self._ep = (self._ep + 1) % len(self.endpoints)
                time.sleep(min(delay, max(0.0, remaining)))
                delay = redial_backoff(self._redial_rng, delay)
                continue
            # the HELLO exchange is budgeted by the reconnect deadline,
            # not the (much longer) reply deadline: a frozen gateway whose
            # kernel backlog still accepts connects must not stretch a
            # 30 s reconnect budget into a 180 s reply wait
            sock.settimeout(max(0.1, min(self._reply_deadline, remaining)
                                if self._reply_deadline
                                and self._reply_deadline > 0
                                else remaining))
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            bandwidth.register_socket(sock, "client", self.process_ind)
            self.incarnation += 1
            try:
                _send_frame(sock, T_HELLO, self._hello_payload())
                rtype, rpayload = _recv_frame(sock)
            except (ConnectionError, OSError):
                try:
                    sock.close()
                except OSError:
                    pass
                # an accepted-then-dropped HELLO is what an unpromoted
                # standby answers with — keep cycling until it promotes
                # (or the budget spends)
                self._ep = (self._ep + 1) % len(self.endpoints)
                time.sleep(min(delay, max(0.0, remaining)))
                delay = redial_backoff(self._redial_rng, delay)
                continue
            self._configure(sock)  # restore the steady-state reply deadline
            self._sock = sock
            self.reconnects += 1
            if addr != self.address:
                # the session moved gateways: the counted failover event
                self.failovers += 1
                self._recorder.record("failover", slot=self.process_ind,
                                      frm=list(self.address),
                                      to=list(addr))
                self.address = addr
            self._recorder.record("reconnect", slot=self.process_ind,
                                  incarnation=self.incarnation,
                                  count=self.reconnects)
            try:
                self._handle_reply(rtype, rpayload)
            except DcnRefused as e:
                # HELLO refused: the slot is held at >= our incarnation —
                # a live duplicate actor owns it; retrying cannot win
                raise self._terminal(str(e)) from e
            return rtype, rpayload

    # reconnect-then-REFUSAL cycles one RPC may consume before the frame
    # is declared poison: each counted cycle means the session redialled
    # FINE and the gateway then actively dropped this exact frame again
    # (a chunk it can never decode, a serve-side crash on apply) —
    # without a cap the actor livelocks forever instead of exiting for
    # the supervisor.  Reply TIMEOUTS never count: a deadline trip is
    # legitimate ingest backpressure (or a frozen peer, which the
    # reconnect budget handles) and must stall the actor, not kill it.
    _MAX_RETRANSMITS = 5

    def _request(self, ftype: int, payload: bytes) -> Tuple[int, bytes]:
        with self._lock:
            if self.disconnected.is_set() or self._closed:
                raise self._terminal("session already closed")
            retransmits = 0
            rpc_mid = None
            while True:
                try:
                    wire = self._faults.frame(payload)
                    t_send = time.time()
                    _send_frame(self._sock, ftype, wire)
                    rtype, rpayload = _recv_frame(self._sock)
                    rpc_mid = (t_send + time.time()) / 2.0
                    break
                except (ConnectionError, OSError) as e:
                    timed_out = isinstance(e, socket.timeout)
                    if (not timed_out
                            and retransmits >= self._MAX_RETRANSMITS):
                        raise self._terminal(
                            f"frame type {ftype} refused "
                            f"{retransmits}x across fresh sessions "
                            f"(poison frame?)")
                    reply = self._reconnect()
                    if ftype == T_HELLO:
                        # the reconnect's own HELLO already (re)established
                        # the session — retransmitting would bounce off the
                        # fresh claim as a same-incarnation duplicate
                        rtype, rpayload = reply
                        break
                    if not timed_out:
                        retransmits += 1
                    # loop retransmits the one unacked frame
            self._last_rpc = time.monotonic()
            self._handle_reply(rtype, rpayload, rpc_mid=rpc_mid)
            return rtype, rpayload

    # -- heartbeats ---------------------------------------------------------

    def _heartbeat_loop(self) -> None:
        interval = self._heartbeat_interval
        while not self._hb_stop.wait(min(interval / 4.0, 1.0)):
            if self.disconnected.is_set():
                return
            if time.monotonic() - self._last_rpc < interval:
                continue
            try:
                self.ping()
            except (ConnectionError, OSError):
                return  # terminal states are latched by the request path
            # anything else (notably faults.InjectedCrash) propagates:
            # a crash drill must die loudly, never as a quiet hb death

    def ping(self) -> int:
        """Heartbeat RPC; refreshes the cached learner clock."""
        self._request(T_PING, b"")
        return self.learner_step

    # -- RPC surface --------------------------------------------------------

    def _flow_blocked(self) -> bool:
        return (self._flow_params.enabled and self.credits is not None
                and self.credits <= 0)

    def _send_exp(self, items: list) -> None:
        """One credit-consuming EXP round-trip (the reply re-grants).

        Byte ledger (ISSUE 18): the payload is encoded ONCE and its
        bytes counted ONCE after the ack — ``_request``'s retransmits
        resend the same frame, so ``flow_acked_bytes`` is
        retransmit-idempotent by construction (exactly like the row
        count below)."""
        if self.credits is not None:
            self.credits -= 1
        payload = encode_chunk(items)
        self._request(T_EXP, payload)
        self.flow_acked_rows += len(items)
        self.flow_acked_bytes += len(payload)

    def send_chunk(self, items: list) -> None:
        """Ship one chunk, credit-aware (ISSUE 11).  With send credit
        (or a gateway that grants none — healthy/legacy) this is the
        usual synchronous RPC, draining any ring backlog first so
        experience stays ordered.  At grant 0 the chunk parks in the
        bounded drop-oldest ring and the call RETURNS — the actor keeps
        ticking (its heartbeats keep the session claimed and fetch the
        next grant), the ring's oldest rows are the counted,
        provenance-stamped cost of sustained overload."""
        self.flow_minted_rows += len(items)
        with self._lock:
            if self._flow_blocked():
                if self.flow_ring.put(items) and not self._flow_blocked_logged:
                    self._flow_blocked_logged = True
                    print(f"[dcn] slot {self.process_ind}: credit-blocked "
                          f"ring full — shedding oldest experience "
                          f"(counted; newest wins)", flush=True)
                return
            # drain the backlog first (oldest buffered chunk precedes
            # this one on the wire); every reply refreshes the grant,
            # so a re-throttle mid-drain parks the rest again
            while len(self.flow_ring):
                buffered = self.flow_ring.pop()
                if buffered is None:
                    break
                self._send_exp(buffered)
                if self._flow_blocked():
                    self.flow_ring.put(items)
                    return
            self._send_exp(items)

    def flow_report(self) -> Dict[str, int]:
        """Cumulative flow counters for the T_TICK report (idempotent
        by construction — the gateway's conservation ledger reads
        them)."""
        return {"minted": self.flow_minted_rows,
                "acked": self.flow_acked_rows,
                "acked_bytes": self.flow_acked_bytes,
                "dropped": self.flow_ring.dropped_rows,
                "buffered": self.flow_ring.buffered_rows}

    def get_params(self, min_version: int
                   ) -> Optional[Tuple[np.ndarray, int]]:
        _, payload = self._request(T_GETP, struct.pack("!Q", min_version))
        (version,) = struct.unpack("!Q", payload[:8])
        if version == 0:
            return None
        return np.frombuffer(payload[8:], dtype=np.float32).copy(), version

    def tick(self, actor_steps: int = 0,
             stats: Optional[Dict[str, float]] = None) -> int:
        msg: Dict[str, Any] = {"actor_steps": actor_steps}
        if stats:
            msg["stats"] = stats
        if self._flow_params.enabled and self.flow_minted_rows:
            # cumulative (not delta) flow counters: a retransmitted
            # tick re-ships the same totals, so the gateway-side
            # conservation ledger is dedup-proof by construction
            msg["flow"] = self.flow_report()
        with self._lock:
            # seq assigned under the request lock so ticks hit the wire
            # in seq order; a retransmit reuses the SAME payload bytes,
            # which is exactly what lets the gateway spot the duplicate
            self._tick_seq += 1
            msg["seq"] = self._tick_seq
            self._request(T_TICK, json.dumps(msg).encode())
        return self.learner_step

    def close(self) -> None:
        try:
            # best-effort final drain of the credit-blocked backlog:
            # whatever the grant allows ships, the rest stays counted
            # in the ring (``buffered`` in the last flow report)
            if len(self.flow_ring) and not self.disconnected.is_set():
                with self._lock:
                    while not self._flow_blocked():
                        buffered = self.flow_ring.pop()
                        if buffered is None:
                            break
                        self._send_exp(buffered)
        except (ConnectionError, OSError):
            pass
        self._closed = True
        if self._hb_thread is not None:
            self._hb_stop.set()
            self._hb_thread.join(2.0)
        try:
            with self._lock:
                _send_frame(self._sock, T_BYE, b"")
                self._sock.close()
        except (ConnectionError, OSError):
            pass


class _ChunkSink:
    """Duck-types the queue end QueueFeeder writes to: ``put(items)``
    becomes one EXP frame."""

    def __init__(self, client: DcnClient):
        self._client = client

    def put(self, items: list) -> None:
        self._client.send_chunk(items)


class RemoteMemory(QueueFeeder):
    """Actor-side feed endpoint over DCN: QueueFeeder's chunk buffering,
    with the spawn queue replaced by the wire."""

    def __init__(self, client: DcnClient, chunk: int = 64):
        super().__init__(_ChunkSink(client), chunk=chunk)


class RemoteParamStore:
    """Read surface of agents/param_store.py ParamStore over DCN."""

    def __init__(self, client: DcnClient):
        self._client = client

    def fetch(self, min_version: int = 0
              ) -> Optional[Tuple[np.ndarray, int]]:
        return self._client.get_params(min_version)

    # ParamStore.wait is written purely against self.fetch, so the poll
    # loop (startup blocking, stop-event handling, timeout) is shared
    # verbatim rather than re-implemented.
    wait = ParamStore.wait


class _StepShim:
    """Duck-types ``mp.Value`` for the clock's learner_step reads."""

    def __init__(self, client: DcnClient):
        self._client = client

    @property
    def value(self) -> int:
        return self._client.learner_step


class RemoteClock:
    """GlobalClock surface for remote actors.  ``add_actor_steps``
    accumulates locally and flushes to the gateway on a count/time cadence —
    a per-env-step RPC would put one RTT in the rollout hot loop; the
    learner-step view is refreshed by every flush (and by every experience
    chunk ack), so ``done()`` staleness is bounded by the cadence, matching
    the reference's tolerance for stale clock reads (reference
    dqn_actor.py:62 reads an unlocked mp.Value)."""

    def __init__(self, client: DcnClient, flush_every: int = 256,
                 max_age: float = 2.0):
        self._client = client
        self._flush_every = flush_every
        self._max_age = max_age
        self._pending = 0
        self._last_flush = time.monotonic()
        self.learner_step = _StepShim(client)
        # hang-watchdog progress board (utils/supervision.ProgressBoard),
        # attached by fleet._remote_actor_main so the actor-host
        # supervisor can see this worker's liveness — same duck surface
        # as GlobalClock.bump_progress
        self.progress = None

    def bump_progress(self, label: str, n: int = 1) -> None:
        if self.progress is not None:
            self.progress.bump(label, n)

    @property
    def stop(self) -> threading.Event:
        return self._client.stop

    def add_actor_steps(self, n: int = 1) -> int:
        self._pending += n
        now = time.monotonic()
        if (self._pending >= self._flush_every
                or now - self._last_flush > self._max_age):
            self.flush()
        return self._client.learner_step

    def flush(self) -> None:
        pending, self._pending = self._pending, 0
        self._last_flush = time.monotonic()
        try:
            self._client.tick(actor_steps=pending)
        except (ConnectionError, OSError):
            # terminal disconnect (transient ones retry inside the
            # client): keep the steps — they are the fleet-wide Ape-X
            # step count, and done() ends this loop via ``disconnected``,
            # so a dropped tick would silently undercount the run
            self._pending += pending

    def done(self, steps: int) -> bool:
        if (self._client.stop.is_set()
                or self._client.disconnected.is_set()):
            return True
        if time.monotonic() - self._last_flush > self._max_age:
            self.flush()
        return self._client.learner_step >= steps


class RemoteStats:
    """ActorStats.add surface: forwards accumulator increments inline —
    actors already batch their stats on the ``actor_freq`` cadence
    (agents/actor.py flush_stats), so one RPC per flush is the right
    granularity.  At brownout tier >= 1 (the telemetry rung of the
    ISSUE-11 ladder, latched off gateway replies) stat pushes are shed
    — counted via ``flow.note_shed`` — so reporting traffic yields to
    the experience plane first."""

    def __init__(self, client: DcnClient):
        self._client = client

    def add(self, **kv: float) -> None:
        if flow.telemetry_shed():
            flow.note_shed("stats", 1)
            return
        try:
            self._client.tick(stats={k: float(v) for k, v in kv.items()})
        except (ConnectionError, OSError):
            pass
