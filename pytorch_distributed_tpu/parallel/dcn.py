"""DCN transport: cross-host experience ingestion + parameter publication.

No reference equivalent — the reference's entire communication backend is
single-machine ``torch.multiprocessing`` shared memory (reference main.py:13,
core/memories/shared_memory.py:30-37; SURVEY.md §2 "distributed communication
backend").  On a TPU pod the learner host owns the mesh and remote actor
hosts cannot share pages with it, so the three shared-state mechanisms the
reference relies on become one explicit wire protocol over DCN
(host-to-host Ethernet/ICI-external network):

- **experience in** — actors stream fixed-schema transition chunks to the
  learner host's ``DcnGateway``, which forwards them into the same
  single-owner spawn queue the local feeders use (memory/feeder.py,
  memory/device_replay.py): the learner drains local and remote experience
  through one path.
- **weights out** — the gateway answers versioned parameter requests from
  the learner's ``ParamStore`` snapshot; remote actors poll on their
  ``actor_sync_freq`` cadence exactly like local ones (reference
  dqn_actor.py:176-178), with staleness bounded by cadence + one RTT.
- **clocks/stats** — the global learner step rides back on every reply
  (actors need it only for termination, reference dqn_actor.py:62), and
  actor-step/stat increments are batched client-side so the hot loop never
  blocks on the network.

Wire format: 1-byte frame type + 8-byte big-endian payload length, then the
payload — JSON for control frames, ``np.savez`` for experience chunks, raw
fp32 for parameter snapshots.  No pickle on the wire: frames are
schema-checked, so a gateway never executes peer-controlled code.

Client-side adapters (``RemoteMemory``, ``RemoteParamStore``,
``RemoteClock``, ``RemoteStats``) present the exact surfaces the actor
harness binds to (agents/actor.py), so ``run_dqn_actor``/``run_ddpg_actor``
run unmodified on a remote host.
"""

from __future__ import annotations

import io
import json
import socket
import struct
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from pytorch_distributed_tpu.agents.param_store import ParamStore
from pytorch_distributed_tpu.memory.feeder import QueueFeeder
from pytorch_distributed_tpu.utils.experience import Transition

# ---------------------------------------------------------------------------
# framing
# ---------------------------------------------------------------------------

_HDR = struct.Struct("!BQ")

T_HELLO = 1    # JSON {role, process_ind}            -> T_CLOCK
T_EXP = 2      # savez transition chunk              -> T_CLOCK
T_GETP = 3     # !Q min_version                      -> T_PARAMS
T_PARAMS = 4   # !Q version + raw fp32 (empty = no newer snapshot)
T_CLOCK = 5    # JSON {learner_step, stop}
T_TICK = 6     # JSON {actor_steps, stats?}          -> T_CLOCK
T_BYE = 7      # empty                               -> (close)

_MAX_FRAME = 1 << 31  # 2 GiB — far above any chunk; rejects garbage lengths


def _send_frame(sock: socket.socket, ftype: int, payload: bytes) -> None:
    sock.sendall(_HDR.pack(ftype, len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        part = sock.recv(n - len(buf))
        if not part:
            raise ConnectionError("peer closed")
        buf.extend(part)
    return bytes(buf)


def _recv_frame(sock: socket.socket) -> Tuple[int, bytes]:
    ftype, length = _HDR.unpack(_recv_exact(sock, _HDR.size))
    if length > _MAX_FRAME:
        raise ConnectionError(f"oversized frame: {length}")
    return ftype, _recv_exact(sock, length) if length else b""


# ---------------------------------------------------------------------------
# experience chunk encoding: columnar, no pickle
# ---------------------------------------------------------------------------

_FIELDS = ("state0", "action", "reward", "gamma_n", "state1", "terminal1")


def encode_chunk(items: List[Tuple[Transition, Optional[float]]]) -> bytes:
    """Stack a chunk of (transition, priority) into one savez payload.
    ``priority`` None (uniform / new-sample-max semantics) encodes as NaN."""
    cols = {f: np.stack([np.asarray(getattr(t, f)) for t, _ in items])
            for f in _FIELDS}
    cols["priority"] = np.array(
        [np.nan if p is None else float(p) for _, p in items],
        dtype=np.float32)
    out = io.BytesIO()
    np.savez(out, **cols)
    return out.getvalue()


def decode_chunk(payload: bytes
                 ) -> List[Tuple[Transition, Optional[float]]]:
    with np.load(io.BytesIO(payload)) as z:
        cols = {k: z[k] for k in z.files}
    n = len(cols["priority"])
    items: List[Tuple[Transition, Optional[float]]] = []
    for i in range(n):
        t = Transition(*(cols[f][i] for f in _FIELDS))
        p = cols["priority"][i]
        items.append((t, None if np.isnan(p) else float(p)))
    return items


# ---------------------------------------------------------------------------
# learner-host gateway
# ---------------------------------------------------------------------------

class DcnGateway:
    """Accepts remote-actor connections on the learner host.

    ``put_chunk`` receives decoded ``[(Transition, priority), ...]`` lists —
    wire it to the single-owner memory's spawn queue (``feed_queue_of``) so
    remote experience merges with local feeders on the learner's drain path.
    """

    def __init__(self, param_store, clock, actor_stats,
                 put_chunk: Callable[[list], None],
                 host: str = "0.0.0.0", port: int = 0,
                 local_actors: int = 0):
        self.param_store = param_store
        self.clock = clock
        self.actor_stats = actor_stats
        self.put_chunk = put_chunk
        self.local_actors = local_actors
        self._srv = socket.create_server((host, port))
        self._srv.settimeout(0.25)
        self.port = self._srv.getsockname()[1]
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._active_slots: set = set()
        self._slots_lock = threading.Lock()
        self.connections = 0
        self.chunks_in = 0
        # all state above must exist before the first connection lands
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="dcn-accept", daemon=True)
        self._accept_thread.start()

    # -- server loops -------------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, addr = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self.connections += 1
            t = threading.Thread(target=self._serve, args=(conn, addr),
                                 name=f"dcn-conn-{addr}", daemon=True)
            t.start()
            # prune threads of departed peers — actor churn is expected
            self._threads = [x for x in self._threads if x.is_alive()]
            self._threads.append(t)

    def _clock_payload(self) -> bytes:
        return json.dumps({
            "learner_step": int(self.clock.learner_step.value),
            "stop": bool(self.clock.stop.is_set()),
        }).encode()

    def _claim_slot(self, ind: Optional[int]) -> Optional[str]:
        """Register a remote actor's global slot; returns an error string on
        a conflict (slot owned by the learner host's local actors or already
        held by a live connection — duplicate slots silently skew the
        fleet-wide Ape-X epsilon schedule)."""
        if ind is None:
            return None
        with self._slots_lock:
            if ind < self.local_actors:
                return (f"actor slot {ind} is local to the learner host "
                        f"(local_actors={self.local_actors})")
            if ind in self._active_slots:
                return f"actor slot {ind} already connected"
            self._active_slots.add(ind)
        return None

    def _serve(self, conn: socket.socket, addr) -> None:
        slot: Optional[int] = None
        try:
            with conn:
                while not self._stop.is_set():
                    ftype, payload = _recv_frame(conn)
                    if ftype == T_BYE:
                        return
                    elif ftype == T_EXP:
                        try:
                            self.put_chunk(decode_chunk(payload))
                        except ValueError:
                            # memory queue already closed: the run is over;
                            # answer with the stop-carrying clock instead of
                            # dying with a traceback
                            pass
                        self.chunks_in += 1
                        _send_frame(conn, T_CLOCK, self._clock_payload())
                    elif ftype == T_GETP:
                        (min_version,) = struct.unpack("!Q", payload)
                        got = self.param_store.fetch(min_version)
                        if got is None:
                            _send_frame(conn, T_PARAMS,
                                        struct.pack("!Q", 0))
                        else:
                            flat, version = got
                            _send_frame(
                                conn, T_PARAMS,
                                struct.pack("!Q", version)
                                + np.ascontiguousarray(
                                    flat, dtype=np.float32).tobytes())
                    elif ftype == T_TICK:
                        msg = json.loads(payload.decode())
                        steps = int(msg.get("actor_steps", 0))
                        if steps:
                            self.clock.add_actor_steps(steps)
                        kv = msg.get("stats")
                        if kv:
                            self.actor_stats.add(
                                **{k: float(v) for k, v in kv.items()})
                        _send_frame(conn, T_CLOCK, self._clock_payload())
                    elif ftype == T_HELLO:
                        msg = json.loads(payload.decode())
                        ind = msg.get("process_ind")
                        err = self._claim_slot(ind)
                        if err is not None:
                            reply = json.loads(self._clock_payload())
                            reply["error"] = err
                            _send_frame(conn, T_CLOCK,
                                        json.dumps(reply).encode())
                            return
                        slot = ind
                        _send_frame(conn, T_CLOCK, self._clock_payload())
                    else:
                        raise ConnectionError(f"bad frame type {ftype}")
        except (ConnectionError, OSError):
            return  # peer went away; Ape-X tolerates actor churn
        finally:
            if slot is not None:
                with self._slots_lock:
                    self._active_slots.discard(slot)

    def close(self) -> None:
        self._stop.set()
        try:
            self._srv.close()
        except OSError:
            pass
        for t in self._threads:
            t.join(1.0)


def feed_queue_of(memory_handles) -> Callable[[list], None]:
    """The gateway->memory bridge: single-owner learner-side memories
    (QueueOwner, DeviceReplayIngest) drain a spawn queue of
    ``[(Transition, priority)]`` chunks; remote chunks enter that same
    queue.  Multi-writer shared rings (SharedReplay/NativeRingReplay) take
    direct feeds — their ``feed`` is already cross-process safe."""
    learner_side = memory_handles.learner_side
    if getattr(learner_side, "_q", None) is not None:
        # late-bound: Topology._use_thread_queue may swap the queue object
        # between construction and run
        def _enqueue(items: list) -> None:
            learner_side._q.put(items)
        return _enqueue

    def _direct(items: list) -> None:
        for t, p in items:
            learner_side.feed(t, p)
    return _direct


# ---------------------------------------------------------------------------
# actor-host client + adapters
# ---------------------------------------------------------------------------

class DcnClient:
    """One connection to the gateway, shared by the adapters of one actor
    process.  All requests are synchronous request/reply under a lock; every
    reply refreshes the cached learner clock."""

    def __init__(self, address: Tuple[str, int], process_ind: int = 0,
                 connect_timeout: float = 60.0, retries: int = 20):
        self.address = address
        self.process_ind = process_ind
        self._lock = threading.RLock()
        self.learner_step = 0
        self.stop = threading.Event()
        deadline = time.monotonic() + connect_timeout
        delay = 0.1
        while True:
            try:
                self._sock = socket.create_connection(address, timeout=30.0)
                break
            except OSError:
                if time.monotonic() > deadline or retries <= 0:
                    raise
                retries -= 1
                time.sleep(delay)
                delay = min(delay * 2, 2.0)
        # blocking from here on: a slow gateway (learner jit compile,
        # ingest-queue backpressure) must stall the actor — the correct
        # flow control — not masquerade as a dead peer; death is detected
        # by TCP reset/close, same as the local runtime monitor
        self._sock.settimeout(None)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._request(T_HELLO, json.dumps(
            {"role": "actor", "process_ind": process_ind}).encode())

    def _request(self, ftype: int, payload: bytes) -> Tuple[int, bytes]:
        with self._lock:
            try:
                _send_frame(self._sock, ftype, payload)
                rtype, rpayload = _recv_frame(self._sock)
            except (ConnectionError, OSError):
                # learner host gone: treat as global stop, as the runtime
                # monitor would locally (runtime.py _monitor)
                self.stop.set()
                raise
        if rtype == T_CLOCK:
            msg = json.loads(rpayload.decode())
            self.learner_step = int(msg["learner_step"])
            if msg.get("stop"):
                self.stop.set()
            if "error" in msg:  # e.g. actor-slot conflict at HELLO
                self.stop.set()
                raise RuntimeError(f"gateway refused: {msg['error']}")
        return rtype, rpayload

    def send_chunk(self, items: list) -> None:
        self._request(T_EXP, encode_chunk(items))

    def get_params(self, min_version: int
                   ) -> Optional[Tuple[np.ndarray, int]]:
        _, payload = self._request(T_GETP, struct.pack("!Q", min_version))
        (version,) = struct.unpack("!Q", payload[:8])
        if version == 0:
            return None
        return np.frombuffer(payload[8:], dtype=np.float32).copy(), version

    def tick(self, actor_steps: int = 0,
             stats: Optional[Dict[str, float]] = None) -> int:
        msg: Dict[str, Any] = {"actor_steps": actor_steps}
        if stats:
            msg["stats"] = stats
        self._request(T_TICK, json.dumps(msg).encode())
        return self.learner_step

    def close(self) -> None:
        try:
            with self._lock:
                _send_frame(self._sock, T_BYE, b"")
                self._sock.close()
        except OSError:
            pass


class _ChunkSink:
    """Duck-types the queue end QueueFeeder writes to: ``put(items)``
    becomes one EXP frame."""

    def __init__(self, client: DcnClient):
        self._client = client

    def put(self, items: list) -> None:
        self._client.send_chunk(items)


class RemoteMemory(QueueFeeder):
    """Actor-side feed endpoint over DCN: QueueFeeder's chunk buffering,
    with the spawn queue replaced by the wire."""

    def __init__(self, client: DcnClient, chunk: int = 64):
        super().__init__(_ChunkSink(client), chunk=chunk)


class RemoteParamStore:
    """Read surface of agents/param_store.py ParamStore over DCN."""

    def __init__(self, client: DcnClient):
        self._client = client

    def fetch(self, min_version: int = 0
              ) -> Optional[Tuple[np.ndarray, int]]:
        return self._client.get_params(min_version)

    # ParamStore.wait is written purely against self.fetch, so the poll
    # loop (startup blocking, stop-event handling, timeout) is shared
    # verbatim rather than re-implemented.
    wait = ParamStore.wait


class _StepShim:
    """Duck-types ``mp.Value`` for the clock's learner_step reads."""

    def __init__(self, client: DcnClient):
        self._client = client

    @property
    def value(self) -> int:
        return self._client.learner_step


class RemoteClock:
    """GlobalClock surface for remote actors.  ``add_actor_steps``
    accumulates locally and flushes to the gateway on a count/time cadence —
    a per-env-step RPC would put one RTT in the rollout hot loop; the
    learner-step view is refreshed by every flush (and by every experience
    chunk ack), so ``done()`` staleness is bounded by the cadence, matching
    the reference's tolerance for stale clock reads (reference
    dqn_actor.py:62 reads an unlocked mp.Value)."""

    def __init__(self, client: DcnClient, flush_every: int = 256,
                 max_age: float = 2.0):
        self._client = client
        self._flush_every = flush_every
        self._max_age = max_age
        self._pending = 0
        self._last_flush = time.monotonic()
        self.learner_step = _StepShim(client)

    @property
    def stop(self) -> threading.Event:
        return self._client.stop

    def add_actor_steps(self, n: int = 1) -> int:
        self._pending += n
        now = time.monotonic()
        if (self._pending >= self._flush_every
                or now - self._last_flush > self._max_age):
            self.flush()
        return self._client.learner_step

    def flush(self) -> None:
        pending, self._pending = self._pending, 0
        self._last_flush = time.monotonic()
        try:
            self._client.tick(actor_steps=pending)
        except (ConnectionError, OSError):
            pass  # stop is set by the client; done() will see it

    def done(self, steps: int) -> bool:
        if self._client.stop.is_set():
            return True
        if time.monotonic() - self._last_flush > self._max_age:
            self.flush()
        return self._client.learner_step >= steps


class RemoteStats:
    """ActorStats.add surface: forwards accumulator increments inline —
    actors already batch their stats on the ``actor_freq`` cadence
    (agents/actor.py flush_stats), so one RPC per flush is the right
    granularity."""

    def __init__(self, client: DcnClient):
        self._client = client

    def add(self, **kv: float) -> None:
        try:
            self._client.tick(stats={k: float(v) for k, v in kv.items()})
        except (ConnectionError, OSError):
            pass
