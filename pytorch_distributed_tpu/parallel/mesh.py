"""Device-mesh construction and sharding vocabulary.

No reference equivalent: the reference is single-GPU with process-level
actor fan-out only (SURVEY.md §2 "parallelism strategies").  This is the
TPU-native distribution backbone: a logical ``jax.sharding.Mesh`` over all
chips with two axes —

- ``dp`` (data parallel): carries the learner batch; gradients are
  all-reduced across it over ICI (XLA inserts the collective when the batch
  is dp-sharded and params are replicated);
- ``mp`` (model parallel): tensor-sharded layers on models wide enough to
  pay for it — the DTQN FFN is Megatron-split over this axis when
  ``mp_size > 1`` (parallel/tensor_parallel.py);
- ``ep`` (expert parallel): MoE expert kernels shard their leading expert
  dim over it, the combine einsum closing with a psum over ep
  (models/moe.py + parallel/expert_parallel.py).

Multi-host pods: call ``jax.distributed.initialize`` first
(``init_multihost``), then the same mesh code spans all hosts' devices —
DCN between hosts, ICI within.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(dp_size: int = -1, mp_size: int = 1, sp_size: int = 1,
              ep_size: int = 1, pp_size: int = 1, devices=None) -> Mesh:
    """Logical mesh over the chips: ``dp`` (data parallel), ``sp``
    (sequence/context parallel — ring attention shards the time axis over
    it, ops/ring_attention.py), ``mp`` (tensor parallel), ``ep``
    (expert parallel — MoE expert kernels shard over it,
    parallel/expert_parallel.py) and ``pp`` (pipeline parallel — stacked
    transformer blocks shard their layer axis over it and microbatches
    flow stage-to-stage via ppermute, parallel/pipeline.py)."""
    explicit = devices is not None
    devices = list(devices if explicit else jax.devices())
    n = len(devices)
    model_axes = mp_size * sp_size * ep_size * pp_size
    if dp_size == -1:
        assert n % model_axes == 0, (
            f"{n} devices not divisible by mp*sp*ep*pp={model_axes}")
        dp_size = n // model_axes
    used = dp_size * model_axes
    assert used <= n, (
        f"mesh dp{dp_size}xsp{sp_size}xmp{mp_size}xep{ep_size}xpp{pp_size}"
        f" needs more than {n} devices")
    if used < n and not explicit:
        # an undersized explicit mesh over the default device set silently
        # strands chips — make the throughput loss visible
        import warnings

        warnings.warn(
            f"mesh dp{dp_size}xsp{sp_size}xmp{mp_size}xep{ep_size}"
            f"xpp{pp_size} uses {used} of {n} available devices; "
            f"{n - used} chip(s) idle", stacklevel=2)
    grid = np.array(devices[:used]).reshape(dp_size, sp_size, mp_size,
                                            ep_size, pp_size)
    return Mesh(grid, ("dp", "sp", "mp", "ep", "pp"))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Leading-axis (batch) sharding over dp."""
    return NamedSharding(mesh, P("dp"))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def init_multihost(coordinator_address: Optional[str] = None,
                   num_processes: int = 1, process_id: int = 0) -> None:
    """Bring up the DCN layer for a multi-host pod
    (jax.distributed; the TPU equivalent of a NCCL/MPI world init)."""
    if num_processes > 1:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
