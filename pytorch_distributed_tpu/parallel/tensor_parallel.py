"""Tensor parallelism: Megatron-style FFN sharding over the mesh ``mp`` axis.

No reference equivalent (the reference is single-GPU; SURVEY.md §2
"parallelism strategies" lists tensor parallelism as NOT present there) —
this is the TPU-native capability that makes the mesh's ``mp`` axis real
for the one model family wide enough to use it: the DTQN transformer
(models/dtqn.py).

Design: sharding annotations only, no manual collectives.  Each block's
FFN expand kernel (``Dense_2``, dim -> 4*dim) is column-sharded over mp and
its contract kernel (``Dense_3``, 4*dim -> dim) is row-sharded; everything
else (attention, embeddings, heads, optimizer scalars) replicates.  Under
``jit`` XLA's SPMD partitioner then runs each FFN matmul on 1/mp of the
hidden dim per chip and inserts the one all-reduce (psum over mp, on ICI)
at the contract output — the standard Megatron dataflow, expressed the JAX
way.  Because the Adam moments mirror the param tree, the same
path-suffix rule shards them identically, so optimizer memory for the FFN
also drops by 1/mp per chip.
"""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# flax auto-names the four Dense calls in models/dtqn.py::_Block in call
# order: Dense_0 = qkv, Dense_1 = attention out-proj, Dense_2 = FFN
# expand, Dense_3 = FFN contract.
_FFN_EXPAND, _FFN_CONTRACT = "Dense_2", "Dense_3"


def _path_strings(path) -> list:
    out = []
    for p in path:
        for attr in ("key", "name", "idx"):
            if hasattr(p, attr):
                out.append(str(getattr(p, attr)))
                break
        else:
            out.append(str(p))
    return out


def _spec_for_path(path) -> P:
    keys = _path_strings(path)
    for i, k in enumerate(keys):
        if not k.startswith("_Block_"):
            continue
        tail = keys[i + 1:]
        if _FFN_EXPAND in tail:
            # kernel (dim, 4*dim): split the output features; its bias
            # (4*dim,) follows the same split
            return P(None, "mp") if tail[-1] == "kernel" else P("mp")
        if _FFN_CONTRACT in tail:
            # kernel (4*dim, dim): split the contraction dim — XLA closes
            # it with a psum over mp; bias (dim,) stays replicated
            return P("mp", None) if tail[-1] == "kernel" else P()
    return P()


def dtqn_state_shardings(state: Any, mesh: Mesh) -> Any:
    """A NamedSharding pytree for a DTQN TrainState (params, target params
    and Adam moments all share the param paths, so one suffix rule shards
    all three); pass to ``ShardedLearner(state_shardings=...)``."""
    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: NamedSharding(mesh, _spec_for_path(path)), state)
