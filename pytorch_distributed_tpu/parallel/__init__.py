from pytorch_distributed_tpu.parallel.mesh import make_mesh, batch_sharding, replicated
from pytorch_distributed_tpu.parallel.learner import ShardedLearner

__all__ = ["make_mesh", "batch_sharding", "replicated", "ShardedLearner"]
