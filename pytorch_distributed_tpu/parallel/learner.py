"""Sharded learner: the train step compiled over the device mesh.

This is the TPU-native answer to the reference's "multi-learner hook" —
where ``num_learners > 1`` in the reference would race unsynchronized Adam
steps on one shared CUDA model (reference main.py:83-94, SURVEY.md "known
quirks"), here scaling the learner means *one* jit-compiled update whose
batch is sharded across the mesh's dp axis; XLA partitions the forward/
backward per chip and inserts the gradient all-reduce over ICI.  Params,
optimizer state and the target net are replicated; donated so the whole
TrainState updates in place in HBM.

Usage:
    learner = ShardedLearner(step_fn, mesh)          # step_fn from ops.losses
    state = learner.place(state)                     # replicate onto mesh
    state, metrics, td = learner.step(state, batch)  # batch: host np arrays

Health contract: the ``step_fn``s the factory hands over are wrapped by
the in-jit finite guard (utils/health.finite_guard, on by default) — a
non-finite step returns the INPUT state selected through unchanged,
``metrics["learner/skipped"]`` = 1 and a zeroed ``td``.  The guard is a
per-leaf in-graph select, so it composes transparently with everything
here: donation (the select resolves before outputs), dp-sharded batches,
tensor/expert/pipeline state shardings, and the ICI all-reduce.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax

from pytorch_distributed_tpu.parallel.mesh import batch_sharding, replicated
from pytorch_distributed_tpu.utils.experience import Batch


class ShardedLearner:
    def __init__(self, step_fn: Callable, mesh: Optional[jax.sharding.Mesh],
                 donate: bool = True, state_shardings=None):
        """``state_shardings``: optional NamedSharding pytree matching the
        TrainState — e.g. parallel/tensor_parallel.dtqn_state_shardings for
        a Megatron-split FFN over mp.  Default replicates the state."""
        self.mesh = mesh
        self._serialize_collectives = (
            mesh is not None
            and mesh.devices.flat[0].platform == "cpu"
            and mesh.size > 1)
        if mesh is None:
            self._step = jax.jit(step_fn,
                                 donate_argnums=(0,) if donate else ())
            self._batch_sharding = None
        else:
            self._batch_sharding = batch_sharding(mesh)
            self._state_sharding = (replicated(mesh)
                                    if state_shardings is None
                                    else state_shardings)
            # dp-sharded batch + (replicated | tensor-sharded) state; XLA
            # lowers the gradient reduction to an ICI all-reduce (plus the
            # mp psums when FFN kernels are split) automatically.
            self._step = jax.jit(
                step_fn,
                in_shardings=(self._state_sharding, self._batch_sharding),
                out_shardings=(self._state_sharding, replicated(mesh),
                               self._batch_sharding),
                donate_argnums=(0,) if donate else (),
            )

    def place(self, state: Any) -> Any:
        """Move a host-initialised TrainState onto the mesh (replicated)."""
        if self.mesh is None:
            return jax.device_put(state)
        return jax.device_put(state, self._state_sharding)

    def shard_batch(self, batch: Batch) -> Batch:
        if self._batch_sharding is None:
            return jax.device_put(batch)
        dp = self.mesh.shape["dp"]
        bsz = batch.reward.shape[0]
        if bsz % dp != 0:
            raise ValueError(
                f"batch_size {bsz} must be divisible by the mesh dp axis "
                f"({dp}) for data-parallel sharding")
        return jax.device_put(batch, self._batch_sharding)

    def step(self, state, batch: Batch):
        out = self._step(state, self.shard_batch(batch))
        if self._serialize_collectives:
            # XLA's CPU collective thunks rendezvous on a shared thread
            # pool; several queued multi-device programs can starve each
            # other into the 40 s rendezvous abort.  Blocking per step only
            # on the CPU simulation keeps the 8-virtual-device test path
            # deterministic; TPU keeps full async dispatch.
            jax.block_until_ready(out[0])
        return out

    def host_params(self, state) -> Any:
        """Fetch the current online params to host memory for publication to
        actors (the explicit versioned-publication replacing the reference's
        implicit shared-CUDA visibility, SURVEY.md §7 "hard parts").

        Actor-side inference must run on published host copies — NOT on the
        mesh-sharded TrainState — both because actors live in other
        processes and because issuing dependent multi-device programs
        against in-flight collective state can deadlock the CPU backend's
        rendezvous (and serialises the TPU pipeline).
        """
        return jax.device_get(state.params)


class ReplicaExchange:
    """Cross-host twin of the in-host dp ``psum`` above (ISSUE 15): the
    glue between the jitted grad/apply split
    (ops/losses.build_dqn_grad_and_apply) and the DCN replica channel
    (parallel/dcn.py ReplicaRegistry / ReplicaClient).

    Two-tier reduction story: WITHIN a host, gradients all-reduce over
    ICI inside the jitted step — ``ShardedLearner`` stays the fast path
    and nothing here touches it.  ACROSS hosts, the replica driver
    (agents/learner.py) ravels the (already ICI-reduced) gradient
    pytree to one fp32 vector, submits it as a generation-stamped round
    through the gateway, and unravels the survivors' mean back.  The
    ravel template is captured from the first local gradient, so the
    exchange needs no a-priori knowledge of the param tree."""

    def __init__(self, channel):
        self.channel = channel
        self.rounds = 0
        self.degraded_rounds = 0
        self.last_members: list = []

    def exchange(self, round_idx: int, grads, ok: bool = True,
                 pidx=None, ptd=None) -> tuple:
        """One allreduce round: returns ``(reply, reduced_grads)`` —
        ``reduced_grads`` is None when the round applied nothing (all
        contributions non-finite: the skipped-step case).  Fenced/stale/
        timeout statuses are returned in ``reply`` for the driver to
        classify (rejoin vs exit); this layer only moves bytes."""
        import numpy as np
        from jax.flatten_util import ravel_pytree

        host_grads = jax.device_get(grads)
        flat, unravel = ravel_pytree(host_grads)
        reply = self.channel.submit_round(
            round_idx, np.asarray(flat, dtype=np.float32), ok=ok,
            pidx=pidx, ptd=ptd)
        from pytorch_distributed_tpu.parallel.dcn import RSTAT_OK

        if reply["status"] != RSTAT_OK:
            return reply, None
        self.rounds += 1
        members = list(reply.get("members", []))
        if self.last_members and len(members) < len(self.last_members):
            self.degraded_rounds += 1
        self.last_members = members
        if reply.get("applied", 0) <= 0 or reply.get("grad") is None:
            return reply, None
        return reply, unravel(np.asarray(reply["grad"],
                                         dtype=np.float32))
