"""Expert parallelism: MoE expert kernels sharded over the mesh ``ep`` axis.

No reference equivalent (SURVEY.md §2 "parallelism strategies" lists
expert parallelism as NOT present in the single-GPU reference) — this is
the sharding rule that makes the mesh's ``ep`` axis real for the MoE DTQN
(models/moe.py).

Same design stance as parallel/tensor_parallel.py: sharding annotations
only, no manual collectives.  Every MoeFfn parameter carries a leading
expert dim (w1 (E,D,H), b1 (E,H), w2 (E,H,D), b2 (E,D)) and is split over
``ep`` on that axis; router kernels, attention, embeddings and optimizer
scalars replicate.  Under jit XLA's SPMD partitioner then runs each
device's expert slice locally and closes the combine einsum's contraction
over E with one psum over ep (models/moe.py docstring walks the
dataflow).  The Adam moments mirror the param tree, so the same
path-suffix rule shards them identically — optimizer memory for the
experts also drops by 1/ep per chip.
"""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pytorch_distributed_tpu.parallel.tensor_parallel import _path_strings

_EXPERT_LEAVES = ("w1", "b1", "w2", "b2")


def _spec_for_path(path, leaf) -> P:
    keys = _path_strings(path)
    if "moe" in keys and keys[-1] in _EXPERT_LEAVES:
        # leading expert dim over ep; everything else per-expert local
        return P("ep", *([None] * (leaf.ndim - 1)))
    return P()


def moe_state_shardings(state: Any, mesh: Mesh) -> Any:
    """A NamedSharding pytree for a DtqnMoeModel TrainState (params,
    target params and Adam moments share the param paths, so one suffix
    rule shards all three); pass to ``ShardedLearner(state_shardings=...)``.
    """
    ep = mesh.shape["ep"]
    for path, leaf in jax.tree_util.tree_flatten_with_path(state)[0]:
        if _spec_for_path(path, leaf) != P():
            # fail up front with a readable message, not deep inside
            # XLA's partitioner (mirrors the depth%pp / seq_len%sp guards)
            assert leaf.shape[0] % ep == 0, (
                f"moe_experts={leaf.shape[0]} must divide over the mesh "
                f"ep axis ({ep})")
            break
    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: NamedSharding(mesh, _spec_for_path(path, leaf)),
        state)
