"""Multi-host fleet runner: one learner host + N actor hosts over DCN.

The reference runs everything on one machine through
``torch.multiprocessing`` (reference main.py:13,58-106); its topology ends
at the box.  A TPU deployment splits naturally: the host attached to the
mesh runs the learner (plus evaluator/logger and optionally some local
actors), and any number of CPU-only hosts run actor fan-out, connected by
the DCN wire protocol (parallel/dcn.py).  Fleet-wide semantics match the
single-host run:

- ``opt.num_actors`` is the TOTAL actor count across hosts — the Ape-X
  exploration schedule (reference dqn_actor.py:33-36) spans the fleet, each
  actor taking its global ``process_ind`` slot;
- the global learner clock terminates every loop on every host (reference
  dqn_actor.py:62), carried by gateway replies;
- stats aggregate into the learner host's accumulators, so the logger and
  TensorBoard streams look identical to a single-host run.

Roles (one per invocation, mirroring how NCCL/MPI launchers assign ranks):

    python -m pytorch_distributed_tpu.fleet --role learner \
        --config 4 --port 5555 --local-actors 2
    python -m pytorch_distributed_tpu.fleet --role actors \
        --config 4 --coordinator learnerhost:5555 \
        --actor-base 2 --actor-count 6

For TPU pods where multiple hosts each own chips (v4-32+), set
``parallel_params.multihost`` so the learner program itself spans hosts via
``jax.distributed`` (parallel/mesh.py init_multihost); the fleet layer here
is about scaling the *actor* side and is orthogonal.

Failure model (details in parallel/dcn.py; drills in tests/test_chaos.py,
randomized soak in tools/chaos_soak.py):

- **Survives**: a gateway/learner-host blip or restart (actors redial
  with backoff, re-claim their slots via incarnation fencing, and resend
  their one unacked experience chunk — at-least-once delivery); an actor
  crash (its slot frees on disconnect, the replacement re-claims it,
  paid from the slot's RestartBudget); a partition that heals within
  ``DCN_RECONNECT_TIMEOUT``; a half-open predecessor connection left by
  any of the above (fenced off by the reconnector's higher incarnation).
- **Lost**: stats ticks in flight when a session dies (bounded by the
  flush cadence; actor-step counts are re-queued client-side), plus the
  possibility of a duplicated chunk when an EXP ack was lost.  Tick
  retransmits are seq-deduplicated at the gateway, so step counts and
  stats do not double-count across blips (residual window: an ack lost
  across a gateway restart, which forgets the dedup map).
- **Terminal**: a partition outliving the reconnect budget, or a slot
  genuinely held by a live duplicate — the actor exits
  ``EXIT_DISCONNECTED`` (never a fake "run complete"), the supervisor
  here spends its RestartBudget, and a slot out of budget fails the host
  fast with a nonzero exit for the outer orchestrator.

Fault injection for drills rides env vars (``DCN_FAULTS_CLIENT`` /
``DCN_FAULTS_GATEWAY``, spawn children inherit them) or the
``--faults-client`` / ``--faults-gateway`` CLI knobs below; see
utils/faults.py for the spec grammar.
"""

from __future__ import annotations

import argparse
import multiprocessing as mp
import os
import signal
import sys
import threading
import time
from typing import List, Optional

from pytorch_distributed_tpu.config import Options, build_options
from pytorch_distributed_tpu.runtime import Topology

_CTX = mp.get_context("spawn")


class FleetTopology(Topology):
    """Learner-host topology: the usual local workers (minus remote actor
    slots) plus a DcnGateway bridging remote hosts into the shared plane."""

    def __init__(self, opt: Options, local_actors: int = 0, port: int = 0,
                 spec=None):
        super().__init__(opt, spec=spec)
        self.local_actors = min(local_actors, opt.num_actors)
        # learner-step-rate sampling state for the health snapshot: STATUS
        # requests land on concurrent gateway serve threads
        self._rate_lock = threading.Lock()
        self._rate_prev = None  # (monotonic, learner_step) of last probe
        # the schedule local actor slots actually run, post-downgrade
        # (resolve may warn about a downgrade — once, here, not per
        # STATUS probe)
        from pytorch_distributed_tpu.factory import resolve_actor_backend

        self._actor_backend = resolve_actor_backend(
            opt, self.inference_server)
        # elastic multi-learner plane (ISSUE 15): the lease-fenced
        # membership registry + round coordinator rides THIS gateway;
        # the lead learner (replica 0, this process) joins through the
        # module-local handle instead of dialling loopback
        if self.replica.replicas > 1:
            from pytorch_distributed_tpu.parallel.dcn import (
                ReplicaRegistry, set_local_registry,
            )

            self.replica_registry = ReplicaRegistry(
                self.replica,
                writer=(self.mission._writer
                        if self.mission is not None else None))
            set_local_registry(self.replica_registry)
        self.gateway = self._make_gateway(port)
        self.port = self.gateway.port
        if self.perf.enabled:
            # warm the profiler's one-time session init NOW, while the
            # learner is still compiling (GIL mostly released), so the
            # first T_PROFILE answers at window speed — cold, it can
            # take a minute+ on a saturated small host (utils/perf.
            # prewarm_profiler has the measurement)
            from pytorch_distributed_tpu.utils import perf

            perf.prewarm_profiler()

    def _make_gateway(self, port: int):
        """Single construction point, shared with restart_gateway — a
        post-restart gateway must be configured identically to the
        original or recovery behaviour silently diverges mid-run."""
        from pytorch_distributed_tpu.parallel.dcn import (
            DcnGateway, feed_queue_of,
        )

        return DcnGateway(
            self.param_store, self.clock, self.actor_stats,
            put_chunk=feed_queue_of(self.handles), port=port,
            local_actors=self.local_actors,
            health=self._health_snapshot,
            profiler=self._profile_request,
            metrics_sink=self._metrics_sink,
            flow_params=self.flow,
            pressure=self._flow_pressure,
            # overload transitions land in the run's scalar stream so
            # the DEFAULT_RULES ``overload_shed`` alert (and the
            # incident timeline) can see them; mission-off runs keep
            # the flight-recorder leg only
            flow_writer=(self.mission._writer
                         if self.mission is not None else None),
            replicas=self.replica_registry,
            # gateway HA plane (ISSUE 16): resolved by Topology.__init__
            # (and exported to spawn children); with the plane off the
            # extra kwargs are inert and the gateway is byte-identical
            gateway_params=self.gateway_ha,
            log_dir=(self.opt.log_dir if self.gateway_ha.enabled
                     else None),
            ha_writer=(self.mission._writer
                       if self.mission is not None else None))

    def _flow_pressure(self) -> float:
        """The overload governor's input signal: ingest-queue
        utilization of the learner-side memory (the exact backlog a
        slow learner grows), 0.0 when the queue is unreadable —
        unknown pressure must read healthy, never shedding."""
        ls = self.handles.learner_side
        q = getattr(ls, "_q", None)
        bound = int(getattr(ls, "max_queue_chunks", 0) or 0)
        if q is None or bound <= 0 or not hasattr(q, "qsize"):
            return 0.0
        try:
            return min(1.0, q.qsize() / bound)
        except (NotImplementedError, OSError):
            return 0.0  # macOS mp queues have no qsize

    def _metrics_sink(self, payload: dict) -> int:
        """T_METRICS provider: remote hosts' scalar batches land in the
        mission-control aggregator (utils/telemetry.py).  Plane
        disabled -> absorb nothing (the gateway replies accepted:0; the
        pusher side only runs when ITS plane is enabled, so this is the
        mixed-config case, not the steady state)."""
        if self.mission is None:
            return 0
        return self.mission.ingest_remote(payload)

    def _profile_request(self, msg: dict) -> dict:
        """T_PROFILE provider (parallel/dcn.py): a bounded
        ``utils/profiling.trace`` window captured from THIS process —
        the learner host parent, which owns the accelerator, so the
        trace shows the real XLA activity of the running learner (and
        the co-located inference server / gateway threads).  Other
        roles run in other processes (often other hosts) with no
        profiler listener; asking for them is a clean error, not a
        silently-wrong trace of the wrong process."""
        from pytorch_distributed_tpu.utils import perf

        role = str(msg.get("role", "learner"))
        if role != "learner":
            return {"error": f"role {role!r} not profilable over "
                             f"T_PROFILE: only the learner host process "
                             f"(the accelerator owner) captures XLA "
                             f"traces"}
        label = msg.get("label") or time.strftime("tprofile_%H%M%S")
        return perf.run_profile_window(
            os.path.join(self.opt.log_dir, "profiles"),
            label=str(label), seconds=msg.get("seconds", 3.0),
            max_seconds=self.perf.profile_window_max)

    def _health_snapshot(self) -> dict:
        """Topology-level fields for the gateway's STATUS verb: the parts
        of the health plane only the learner-host wiring can see.  Reads
        are best-effort snapshots of live structures (sizes, counters) —
        racing the learner by one step is fine, blocking it is not."""
        h: dict = {"run_id": self.opt.refs}
        ls = self.handles.learner_side
        try:  # size/capacity are properties; a device ring raises
            size = int(ls.size)  # pre-attach — skip, don't crash STATUS
            h["replay_size"] = size
            cap = int(getattr(ls, "capacity", 0))
            if cap:
                h["replay_capacity"] = cap
                h["replay_fill"] = round(size / cap, 4)
        except Exception:  # noqa: BLE001
            pass
        q = getattr(ls, "_q", None)
        if q is not None and hasattr(q, "qsize"):
            try:
                h["ingest_queue_depth"] = int(q.qsize())
                h["ingest_queue_bound"] = int(
                    getattr(ls, "max_queue_chunks", 0))
            except (NotImplementedError, OSError):
                pass  # macOS mp queues have no qsize
        now = time.monotonic()
        step = int(self.clock.learner_step.value)
        astep = int(self.clock.actor_step.value)
        # per-LOCAL-actor vector-tick marks off the watchdog's progress
        # board (each actor bumps once per tick / per fused dispatch's
        # K ticks), so the panel can attribute the fleet rate to slots
        n_envs = max(1, self.opt.env_params.num_envs_per_actor)
        marks = {i: self.progress_board.marks(f"actor-{i}")
                 for i in range(self.local_actors)}
        with self._rate_lock:
            prev = self._rate_prev
            # advance the window anchor only after it has real width:
            # concurrent probers (a fleet_top refresh loop + a CI probe)
            # would otherwise shrink each other's windows to a few ms,
            # quantizing the rate into 0-or-thousands flapping
            if prev is None or now - prev[0] >= 0.5:
                self._rate_prev = (now, step, astep, marks)
        if prev is not None and now > prev[0]:
            h["learner_steps_per_sec"] = round(
                (step - prev[1]) / (now - prev[0]), 3)
            # the fleet-wide env-frames rate off the same window: the
            # shared actor clock sums every host's ticks, so this is
            # the live Ape-X actor/learner balance read (per-process
            # actor/env_frames_per_s rows live in each actor's metrics
            # stream; remote processes can't reach this registry)
            h["actor_frames_per_sec"] = round(
                (astep - prev[2]) / (now - prev[0]), 3)
            prev_marks = prev[3] if len(prev) > 3 else {}
            if marks:
                # ISSUE-7 satellite: per-actor env frames/s + the
                # schedule each slot actually runs (post-downgrade),
                # rendered by fleet_top's perf panel.  A respawned
                # slot's marks reset (note_start) — clamp at 0 rather
                # than report a negative rate for that window.
                h["actors"] = {
                    str(i): {
                        "env_frames_per_sec": round(max(
                            0.0, (m - prev_marks.get(i, 0)) * n_envs
                            / (now - prev[0])), 1),
                        "backend": self._actor_backend,
                    } for i, m in marks.items()}
        # health-sentinel counters (utils/health.py): learner-side guard
        # skips and rollbacks ride the shared clock; quarantine counts
        # come from this process's registry (the learner-side ingest
        # boundaries — the gateway's own per-slot counts are already in
        # the base snapshot); hang kills from the runtime watchdog
        from pytorch_distributed_tpu.utils import health

        h["health_sentinel"] = {
            "skipped_steps": int(self.clock.skipped_steps.value),
            "rollbacks": int(self.clock.rollbacks.value),
            "hang_kills": int(self.hang_kills),
            # gateway-* sources are excluded: the gateway's own per-slot
            # dict (base snapshot "quarantined") already carries them
            "quarantined_local": {
                s: n for s, n in health.quarantine_counts().items()
                if not s.startswith("gateway-")},
        }
        budget = self._restart_budget
        if budget is not None:
            # scope is honest in the name: the runtime monitor only
            # supervises the learner host's LOCAL actor slots
            # (ind < local_actors); remote slots are supervised by their
            # own actor host's RestartBudget, which never reaches here
            h["local_restart_budget_remaining"] = {
                str(s): r for s, r in budget.remaining().items()}
        # perf plane (utils/perf.py, TPU_APEX_PERF=1): last-drained
        # MFU/rate/watermark values of every monitor in THIS process
        # (learner, thread-backend local actors, inference server) —
        # fleet_top's live perf read
        from pytorch_distributed_tpu.utils import perf

        psnap = perf.status_snapshot()
        if psnap:
            h["perf"] = psnap
        # anakin panel block (ISSUE 12): the co-located loop's vitals —
        # duty cycle / rollout rate off the learner monitor's gauges
        # (present when the perf plane is on), ring fill off the host
        # accounting either way; fleet_top renders it and the ``--json``
        # consumers read it verbatim
        if getattr(self, "anakin", False):
            snap = (psnap or {}).get("learner", {})
            h["anakin"] = {
                "backend": "anakin",
                "duty_cycle": snap.get("anakin/duty_cycle"),
                "rollout_frames_per_s":
                    snap.get("anakin/rollout_frames_per_s"),
                "replay_fill": snap.get("anakin/replay_fill",
                                        h.get("replay_fill")),
                "mfu": snap.get("learner/mfu"),
            }
        # mission control (ISSUE 10): per-rule alert states + recent
        # fleet series — fleet_top's alert panel/sparklines and the
        # ``--json`` blocks CI asserts on come from HERE, not from the
        # probe re-tailing metrics files itself
        if self.mission is not None:
            h.update(self.mission.status_block())
        return h

    def _worker_specs(self):
        # local actor slots are [0, local_actors); remote hosts take the
        # higher process_inds (flatter Ape-X epsilons, the exploratory end)
        specs = [s for s in super()._worker_specs()
                 if s[0] != "actor" or s[1] < self.local_actors]
        return specs

    def _pre_close(self) -> None:
        # stop accepting/serving before the learner-side queue closes:
        # an in-flight EXP put on a closed queue would kill a serve thread
        self.gateway.close()
        if self.replica_registry is not None:
            # drop the module-local handle: a LATER topology in this
            # process (test suites, embedders) must not silently wire
            # its lead learner to this closed run's registry
            from pytorch_distributed_tpu.parallel.dcn import (
                local_registry, set_local_registry,
            )

            if local_registry() is self.replica_registry:
                set_local_registry(None)

    def restart_gateway(self) -> None:
        """Tear the gateway down and rebind on the same port — the
        recovery drill for a learner-host network blip (and the chaos
        harness's kill-gateway lever).  Remote actors ride through it:
        their clients redial, re-HELLO with bumped incarnations, and
        resend their unacked chunks (parallel/dcn.py failure model)."""
        port = self.gateway.port
        self.gateway.close()
        self.gateway = self._make_gateway(port)

    def run(self, backend: str = "process") -> None:
        try:
            super().run(backend=backend)
        finally:
            self.gateway.close()  # idempotent; covers pre-run failures


def run_fleet_learner(opt: Options, local_actors: int = 0, port: int = 5555,
                      backend: str = "process") -> FleetTopology:
    topo = FleetTopology(opt, local_actors=local_actors, port=port)
    print(f"[fleet] learner host up: gateway on port {topo.port}, "
          f"{topo.local_actors}/{opt.num_actors} actors local")
    topo.run(backend=backend)
    return topo


# ---------------------------------------------------------------------------
# replica learner host (ISSUE 15)
# ---------------------------------------------------------------------------

def run_replica_host(opt: Options, coordinator: str,
                     replica_id: int) -> None:
    """One remote learner replica: dials the lead gateway's replica
    plane (lease + generation-stamped rounds) and trains the shared
    model data-parallel (agents/learner.py run_replica_learner).  Exit
    codes mirror the actor host contract: run complete exits 0; a
    terminal fence whose rejoin failed exits EXIT_DISCONNECTED so an
    outer supervisor can respawn the replica — which will re-lease at a
    new generation and sync from the join-barrier epoch."""
    from pytorch_distributed_tpu.factory import probe_env
    from pytorch_distributed_tpu.agents.clocks import (
        GlobalClock, LearnerStats,
    )
    from pytorch_distributed_tpu.agents.learner import run_replica_learner
    from pytorch_distributed_tpu.agents.param_store import ParamStore
    from pytorch_distributed_tpu.parallel.dcn import ReplicaFenced
    from pytorch_distributed_tpu.utils import flight_recorder
    from pytorch_distributed_tpu.utils.helpers import tree_size
    from pytorch_distributed_tpu.utils.supervision import EXIT_DISCONNECTED

    opt.replica_params.coordinator = coordinator
    flight_recorder.configure(opt.log_dir, run_id=opt.refs)
    spec = probe_env(opt)
    from pytorch_distributed_tpu.factory import build_model, init_params

    store = ParamStore(tree_size(init_params(
        opt, spec, build_model(opt, spec), seed=opt.seed)))
    clock = GlobalClock()
    # SIGTERM = preemption notice, same contract as every other host
    # (runtime.py / run_fleet_actors): drain the round loop, publish +
    # commit, release the lease, exit 0 — the next incarnation rejoins
    # through the epoch barrier
    if threading.current_thread() is threading.main_thread():
        try:
            signal.signal(signal.SIGTERM,
                          lambda s, f: clock.stop.set())
        except (ValueError, OSError):  # pragma: no cover
            pass
    print(f"[fleet] replica host up: replica {replica_id} -> "
          f"{coordinator}")
    try:
        run_replica_learner(opt, spec, replica_id, None, store,
                            clock, LearnerStats(),
                            replica_id=replica_id)
    except (ReplicaFenced, ConnectionError, OSError) as e:
        print(f"[fleet] replica-{replica_id} lost its lease/session "
              f"({e}); exiting {EXIT_DISCONNECTED} for the supervisor")
        flight_recorder.dump_all(
            f"replica-{replica_id} fenced/disconnected")
        sys.exit(EXIT_DISCONNECTED)


# ---------------------------------------------------------------------------
# gateway standby host (ISSUE 16)
# ---------------------------------------------------------------------------

def run_gateway_standby(opt: Options, coordinator: str,
                        port: int = 0) -> None:
    """``--role gateway-standby``: a warm standby gateway for the HA
    plane (parallel/dcn.py, GatewayParams).  It pulls the primary's
    journaled control plane over sessionless T_SYNC, refuses session
    verbs (counted) until the primary's lease expires, then PROMOTES:
    CAS-bumps the term on the SHARED ``{log_dir}/gateway/`` dir — the
    same shared-storage requirement checkpoint resume already has — and
    starts serving, fencing any resurrected predecessor.

    The standby hosts its own param store/clock/stats and spools
    promoted-era experience into a bounded drop-oldest buffer (counted)
    — control-plane continuity that keeps actors alive and accounted
    while an orchestrator restarts a full learner host against the
    checkpoint store; it does not itself train.  SIGTERM drains and
    exits 0 like every other host role."""
    import collections

    from pytorch_distributed_tpu.factory import (
        build_model, init_params, probe_env,
    )
    from pytorch_distributed_tpu.agents.clocks import (
        ActorStats, GlobalClock,
    )
    from pytorch_distributed_tpu.agents.param_store import ParamStore
    from pytorch_distributed_tpu.parallel.dcn import (
        DcnGateway, parse_endpoints, resolve_gateway,
    )
    from pytorch_distributed_tpu.utils import flight_recorder
    from pytorch_distributed_tpu.utils.helpers import tree_size

    gp = resolve_gateway(opt.gateway_params)
    if not gp.enabled:
        raise SystemExit(
            "--role gateway-standby needs the HA plane on: set "
            "TPU_APEX_GATEWAY_ENABLED=1 (or opt.gateway_params.enabled)")
    flight_recorder.configure(opt.log_dir, run_id=opt.refs)
    spec = probe_env(opt)
    store = ParamStore(tree_size(init_params(
        opt, spec, build_model(opt, spec), seed=opt.seed)))
    clock = GlobalClock()
    spool: collections.deque = collections.deque(maxlen=4096)
    spooled = [0]

    def _spool(items: list) -> None:
        spool.append(items)
        spooled[0] += len(items)

    bind_host, bind_port = "0.0.0.0", port
    if gp.standby:
        eps = parse_endpoints(gp.standby)
        if eps:
            bind_host, bind_port = eps[0]
    primary = parse_endpoints(coordinator)[0]
    gw = DcnGateway(store, clock, ActorStats(), put_chunk=_spool,
                    host=bind_host, port=bind_port,
                    gateway_params=gp, log_dir=opt.log_dir,
                    ha_role="standby", sync_from=primary)
    # SIGTERM drain flag: a plain threading.Event polled around an
    # interruptible sleep (the run_fleet_actors pattern) — the handler
    # must NOT take the mp clock lock the main thread would be parked
    # on inside ``clock.stop.wait`` (signal-handler self-deadlock)
    host_stop = threading.Event()
    if threading.current_thread() is threading.main_thread():
        try:
            signal.signal(signal.SIGTERM,
                          lambda s, f: host_stop.set())
        except (ValueError, OSError):  # pragma: no cover
            pass
    print(f"[fleet] gateway standby up on port {gw.port}, syncing "
          f"{primary[0]}:{primary[1]} (lease {gp.lease_s:g}s)")
    try:
        while not host_stop.is_set() and not clock.stop.is_set():
            time.sleep(0.5)
    finally:
        role = gw.status_snapshot().get("gateway", {})
        gw.close()
        print(f"[fleet] gateway standby exiting: role "
              f"{role.get('role')!r} term {role.get('term')} "
              f"(spooled {spooled[0]} rows post-promotion)")


def run_replay_shard_host(opt: Options, coordinator: str,
                          shard_id: int, port: int = 0) -> None:
    """``--role replay-shard``: one replay ring shard of the sharded
    priority plane (ISSUE 20, memory/shard_plane.py).  The host owns a
    whole ``PrioritizedReplay`` and serves the two-level sample's
    shard-local leg over T_SSAMPLE/T_SPRIO on its own gateway; actors
    stream T_EXP chunks AT this host (experience samples where it
    LANDS — the INES topology), and every ingest ack renews the shard's
    coordinator lease with the updated cumulative ingest report, so the
    registry's conservation ledger is exact at every chunk boundary: a
    crash loses only unacked — hence actor-counted — rows.

    A restarted shard id re-leases at a fresh generation in ``joining``
    (routed ingest, no sample mass) and activates once its ring is
    warm — the rejoin barrier.  SIGTERM releases the lease (rows move
    to the ``shard_lost`` bucket, counted) and exits 0."""
    import numpy as np

    from pytorch_distributed_tpu.factory import probe_env
    from pytorch_distributed_tpu.agents.clocks import (
        ActorStats, GlobalClock,
    )
    from pytorch_distributed_tpu.agents.param_store import ParamStore
    from pytorch_distributed_tpu.memory.prioritized import (
        PrioritizedReplay,
    )
    from pytorch_distributed_tpu.memory.shard_plane import (
        LocalShard, ShardLease, resolve_shard,
    )
    from pytorch_distributed_tpu.parallel.dcn import (
        DcnGateway, parse_endpoints,
    )
    from pytorch_distributed_tpu.utils import flight_recorder

    sp = resolve_shard(opt.shard_params)
    if sp.shards <= 1:
        raise SystemExit(
            "--role replay-shard needs the shard plane on: set "
            "TPU_APEX_SHARD_SHARDS >= 2 (or opt.shard_params.shards)")
    flight_recorder.configure(opt.log_dir, run_id=opt.refs)
    spec = probe_env(opt)
    mp_ = opt.memory_params
    state_dtype = np.uint8 if mp_.state_dtype == "uint8" else np.float32
    shard_capacity = max(1, -(-int(mp_.memory_size) // sp.shards))
    shard = LocalShard(shard_id, PrioritizedReplay(
        capacity=shard_capacity,
        state_shape=spec.state_shape,
        action_shape=spec.action_shape,
        state_dtype=state_dtype,
        action_dtype=spec.action_dtype,
        priority_exponent=mp_.priority_exponent,
        importance_weight=mp_.priority_weight,
        importance_anneal_steps=opt.agent_params.steps))
    lease = ShardLease(
        parse_endpoints(coordinator or sp.coordinator)[0],
        shard_id, incarnation=int(time.time() * 1000) & 0x7FFFFFFF,
        capacity=shard_capacity)
    lease.acquire()
    shard.generation = lease.generation

    def _report() -> dict:
        rep = shard.mass()
        rep["mass"] = rep["total"]
        rep["fill"] = (rep["size"] / shard_capacity
                       if shard_capacity else 0.0)
        return rep

    def _ingest(items: list) -> None:
        # renew-WITH-updated-ingest before the gateway acks the chunk:
        # the registry ledger moves in the same step the rows become
        # ours, so a crash between acks is exactly the unacked chunk
        for tr, pr in items:
            shard.feed(tr, pr)
        if lease.joining and shard.ingested_rows > 0:
            lease.activate()  # ring is warm: cross the rejoin barrier
        lease.renew(_report())

    gw = DcnGateway(ParamStore(4), GlobalClock(), ActorStats(),
                    put_chunk=_ingest, port=port, shards=shard)
    host_stop = threading.Event()
    if threading.current_thread() is threading.main_thread():
        try:
            signal.signal(signal.SIGTERM, lambda s, f: host_stop.set())
        except (ValueError, OSError):  # pragma: no cover
            pass
    renew_s = sp.renew_s if sp.renew_s > 0 else max(0.05, sp.lease_s / 3)
    print(f"[fleet] replay shard {shard_id} up on port {gw.port} "
          f"(generation {lease.generation}, capacity {shard_capacity}, "
          f"{'joining' if lease.joining else 'member'}, lease "
          f"{sp.lease_s:g}s)")
    try:
        while not host_stop.is_set():
            if host_stop.wait(renew_s):
                break
            try:
                if lease.joining and shard.ingested_rows > 0:
                    lease.activate()
                if not lease.renew(_report()):
                    # expired under us (partition outlived the lease):
                    # re-lease at a fresh generation and rejoin
                    lease.acquire()
                    shard.generation = lease.generation
                    print(f"[fleet] shard {shard_id} lease expired; "
                          f"rejoined at generation {lease.generation} "
                          f"(joining={lease.joining})", flush=True)
            except (ConnectionError, OSError) as e:
                print(f"[fleet] shard {shard_id} coordinator "
                      f"unreachable: {e!r}", flush=True)
    finally:
        shard.alive = False  # drain: answer SSTAT_DEAD, never silence
        try:
            lease.release()
        except (ConnectionError, OSError):
            pass
        gw.close()
        print(f"[fleet] replay shard {shard_id} exiting: "
              f"{shard.ingested_rows} rows ingested, "
              f"{shard.stale_rejected} stale write-backs rejected")


# ---------------------------------------------------------------------------
# actor host
# ---------------------------------------------------------------------------

def _remote_actor_main(opt: Options, coordinator: str, process_ind: int,
                       progress=None) -> None:
    """One remote rollout worker: DCN adapters in place of the shared-memory
    plane, then the standard actor loop (agents/actor.py) unmodified.

    Exit code reflects WHAT ended the loop (utils/supervision.py codes):
    the learner's stop flag exits 0 (run complete — the supervisor frees
    the slot for good), a terminal session loss exits EXIT_DISCONNECTED
    (the supervisor respawns the slot from its RestartBudget).  Before
    the stop/disconnected split, a gateway blip read as "run complete"
    and silently drained the whole remote fleet with zero restarts
    consumed."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", "cpu")

    from pytorch_distributed_tpu.factory import get_worker, probe_env
    from pytorch_distributed_tpu.parallel.dcn import (
        DcnClient, DcnRefused, RemoteClock, RemoteMemory, RemoteParamStore,
        RemoteStats,
    )
    from pytorch_distributed_tpu.utils import flight_recorder
    from pytorch_distributed_tpu.utils.supervision import EXIT_DISCONNECTED

    flight_recorder.configure(opt.log_dir, run_id=opt.refs)
    recorder = flight_recorder.get_recorder(f"actor-{process_ind}")
    # ``--coordinator`` accepts an ORDERED endpoint list
    # ("primary:5555,standby:5556") when the gateway HA plane is on
    # (ISSUE 16): the client dials in order and fails over to the
    # promoted standby on terminal disconnect.  A plain host:port is
    # the unchanged single-gateway contract.
    from pytorch_distributed_tpu.parallel.dcn import parse_endpoints

    endpoints = parse_endpoints(coordinator)
    recorder.record("session-start", coordinator=coordinator)
    try:
        client = DcnClient(endpoints, process_ind=process_ind)
    except (ConnectionError, OSError, DcnRefused) as e:
        # no session was ever established (gateway unreachable, or the
        # HELLO was refused — slot conflict): still a network/learner-host
        # condition, not an actor-code crash, so classify it the same
        # way; anything else (an InjectedCrash drill, a setup bug)
        # propagates as the crash it is
        print(f"[fleet] actor-{process_ind} could not establish its DCN "
              f"session ({e}); exiting {EXIT_DISCONNECTED}")
        recorder.record("session-refused", error=repr(e))
        flight_recorder.dump_all(
            f"actor-{process_ind} could not establish DCN session")
        sys.exit(EXIT_DISCONNECTED)
    memory = RemoteMemory(client)
    clock = RemoteClock(client)
    # hang-watchdog liveness: the actor harness bumps
    # clock.bump_progress per vector tick; the shared board's marks are
    # read by run_fleet_actors' supervisor (utils/supervision.py)
    clock.progress = progress
    try:
        spec = probe_env(opt)
        get_worker("actor", opt.agent_type)(
            opt, spec, process_ind, memory, RemoteParamStore(client), clock,
            RemoteStats(client))
    except (ConnectionError, OSError):
        # a terminal DcnDisconnected escapes the actor loop through its
        # highest-frequency RPC (send_chunk) — swallow it iff the client
        # latched the loss, so the exit-code split below classifies it
        # as EXIT_DISCONNECTED, not an anonymous crash; anything else is
        # a genuine transport bug and must crash loudly
        if not client.disconnected.is_set():
            raise
    finally:
        try:
            memory.flush()
            clock.flush()
        except (ConnectionError, OSError):
            pass
        client.close()
    if client.disconnected.is_set() and not client.stop.is_set():
        print(f"[fleet] actor-{process_ind} lost its DCN session; "
              f"exiting {EXIT_DISCONNECTED} for the supervisor")
        # the client already dumped when it latched the loss
        # (DcnClient._terminal); this records how the ROLE ended
        recorder.record("session-lost", reconnects=client.reconnects)
        flight_recorder.dump_all(
            f"actor-{process_ind} DCN session lost")
        sys.exit(EXIT_DISCONNECTED)
    recorder.record("run-complete", reconnects=client.reconnects)


def run_fleet_actors(opt: Options, coordinator: str, actor_base: int,
                     actor_count: int, backend: str = "process",
                     max_restarts: int = 3) -> List[int]:
    """Run ``actor_count`` rollout workers holding global process_inds
    ``[actor_base, actor_base + actor_count)``.

    Process backend supervises with the same RestartBudget policy as the
    learner host's runtime monitor (utils/supervision.py): a crashed actor
    respawns in place — its gateway slot frees when its connection drops,
    so the replacement re-claims it — up to ``max_restarts`` per slot;
    clean exits (the run finished) are final.  Returns the list of slots
    abandoned with their budget exhausted (empty = clean host run; the
    CLI exits nonzero otherwise so an outer orchestrator sees the
    failure instead of a learner silently training with a reduced
    fleet)."""
    assert actor_base + actor_count <= opt.num_actors, (
        f"actor slots [{actor_base}, {actor_base + actor_count}) exceed "
        f"fleet num_actors={opt.num_actors}")

    from pytorch_distributed_tpu.factory import prebuild_native
    from pytorch_distributed_tpu.utils import health, telemetry
    from pytorch_distributed_tpu.utils.supervision import ProgressBoard

    prebuild_native(opt)  # once, before N workers race the same g++

    # mission-control push leg (ISSUE 10): this host's actors write
    # their scalar rows to the LOCAL log dir; when the metrics plane is
    # on, a MetricsPusher tails that stream and ships scalar-window
    # deltas to the learner-host aggregator over the sessionless
    # T_METRICS verb, clock-offset-aligned — the fleet-level series
    # cover remote hosts, not just the gateway host.
    pusher = None
    mparams = telemetry.resolve_metrics(opt.metrics_params)
    if mparams.enabled:
        from pytorch_distributed_tpu.parallel.dcn import parse_endpoints

        # the pusher pins the FIRST endpoint; its sessionless push has
        # per-call timeouts + a single retry (parallel/dcn.py), so a
        # promotion window costs dropped batches, not a wedged thread
        pusher = telemetry.MetricsPusher(parse_endpoints(coordinator)[0],
                                         opt.log_dir, mparams)
        pusher.start()

    # hang watchdog (health sentinel): per-slot liveness marks bumped by
    # the remote actors' RemoteClock; stale marks past hang_deadline get
    # the worker SIGKILLed and respawned as EXIT_HUNG from the same
    # RestartBudget as a crash.  Process backend only (threads cannot be
    # killed); hang_deadline=0 (default) disables the pass.
    hp = health.resolve(opt.health_params)
    board = ProgressBoard([f"actor-{actor_base + i}"
                           for i in range(actor_count)])

    thread_exits: dict = {}  # slot -> nonzero exit (thread backend only)

    def spawn(ind: int):
        board.note_start(f"actor-{ind}")
        if backend == "process":
            w = _CTX.Process(target=_remote_actor_main,
                             args=(opt, coordinator, ind, board),
                             name=f"fleet-actor-{ind}", daemon=True)
        else:
            def _thread_main(ind=ind):
                from pytorch_distributed_tpu.utils.supervision import (
                    EXIT_CRASH,
                )

                try:
                    _remote_actor_main(opt, coordinator, ind)
                except SystemExit as e:
                    # threading machinery swallows SystemExit, which
                    # would let a session-loss exit read as a clean run
                    # — record it so the join loop can fail loudly
                    thread_exits[ind] = int(e.code or 0)
                except BaseException:
                    # a genuine crash (incl. an InjectedCrash drill) must
                    # not vanish into a dead thread's stderr either
                    thread_exits[ind] = EXIT_CRASH
                    raise

            w = threading.Thread(target=_thread_main,
                                 name=f"fleet-actor-{ind}", daemon=True)
        w.start()
        return w

    workers = {actor_base + i: spawn(actor_base + i)
               for i in range(actor_count)}
    print(f"[fleet] actor host up: {actor_count} actors "
          f"(slots {actor_base}..{actor_base + actor_count - 1}) -> "
          f"{coordinator}")
    if backend != "process":
        for w in workers.values():
            w.join()
        bad = {ind: code for ind, code in thread_exits.items() if code}
        if pusher is not None:
            pusher.stop()  # final tail drain rides the stop
        if bad:
            raise RuntimeError(
                f"actor host FAILED (thread backend): worker exit codes "
                f"{bad} — see utils/supervision.describe_exit")
        return []

    from pytorch_distributed_tpu.utils import flight_recorder
    from pytorch_distributed_tpu.utils.supervision import (
        EXIT_HUNG, RestartBudget, describe_exit,
    )

    flight_recorder.configure(opt.log_dir, export_env=True,
                              run_id=opt.refs)
    host_recorder = flight_recorder.get_recorder("fleet-host")
    budget = RestartBudget(max_restarts=max_restarts, backoff=True)
    for ind in workers:
        budget.note_birth(ind)
    # SIGTERM = the host is being preempted: actor hosts hold no
    # checkpointable state (the learner host owns the epoch store), so
    # the right drain is to stop respawning and terminate the rollout
    # workers promptly — their unflushed chunks are the bounded loss the
    # failure model already declares (parallel/dcn.py "Lost").
    host_stop = threading.Event()
    prev_term = None
    if threading.current_thread() is threading.main_thread():
        try:
            prev_term = signal.signal(
                signal.SIGTERM, lambda s, f: host_stop.set())
        except (ValueError, OSError):  # pragma: no cover
            prev_term = None
    pending: dict = {}  # slot -> respawn-at deadline (crash backoff)
    abandoned: List[int] = []
    while (workers or pending) and not host_stop.is_set():
        time.sleep(0.5)
        now = time.monotonic()
        for ind, at in list(pending.items()):
            if now >= at:
                del pending[ind]
                workers[ind] = spawn(ind)
                budget.note_birth(ind)
        for ind, w in list(workers.items()):
            if w.is_alive():
                continue
            if w.exitcode == 0:
                del workers[ind]  # run complete for this slot
                continue
            delay = budget.request_restart(ind)
            if delay is not None:
                print(f"[fleet] actor-{ind} died "
                      f"({describe_exit(w.exitcode)}); "
                      f"restart {budget.count(ind)}/{max_restarts} "
                      f"in {delay:.0f}s")
                host_recorder.record("worker-restarted", slot=ind,
                                     exit=w.exitcode,
                                     restarts=budget.count(ind),
                                     delay=delay)
                del workers[ind]
                pending[ind] = now + delay
            else:
                print(f"[fleet] actor-{ind} out of restart budget; "
                      f"abandoning slot")
                host_recorder.record("slot-abandoned", slot=ind,
                                     exit=w.exitcode)
                del workers[ind]
                abandoned.append(ind)
        # ---- hang watchdog: SIGKILL alive-but-stuck actors (no
        # progress mark within hang_deadline; compile grace respected)
        # and respawn them through the RestartBudget as EXIT_HUNG
        if hp.hang_deadline > 0:
            hung = set(board.hung(hp.hang_deadline, hp.hang_grace,
                                  only=[f"actor-{i}" for i in workers]))
            for ind, w in list(workers.items()):
                if f"actor-{ind}" not in hung or not w.is_alive():
                    continue
                host_recorder.record(
                    "worker-hung", slot=ind,
                    age=round(board.age(f"actor-{ind}"), 1))
                flight_recorder.dump_all(
                    f"actor-{ind} hung (> {hp.hang_deadline:g}s without "
                    f"progress); watchdog SIGKILL")
                w.kill()
                w.join(10.0)
                delay = budget.request_restart(ind)
                if delay is not None:
                    print(f"[fleet] actor-{ind} "
                          f"({describe_exit(EXIT_HUNG)}); restart "
                          f"{budget.count(ind)}/{max_restarts} "
                          f"in {delay:.0f}s")
                    host_recorder.record("worker-restarted", slot=ind,
                                         exit=EXIT_HUNG,
                                         restarts=budget.count(ind),
                                         delay=delay)
                    del workers[ind]
                    pending[ind] = now + delay
                else:
                    print(f"[fleet] actor-{ind} out of restart budget "
                          f"(hung); abandoning slot")
                    host_recorder.record("slot-abandoned", slot=ind,
                                         exit=EXIT_HUNG)
                    del workers[ind]
                    abandoned.append(ind)
        if abandoned:
            # fail fast like the single-host monitor (runtime._monitor
            # trips the stop event on the same condition): a host running
            # a reduced fleet for the rest of a long run is the silent
            # degradation this supervision exists to prevent.  Terminate
            # the survivors and surface the failure NOW — the outer
            # orchestrator restarts the whole host with a fresh budget.
            flight_recorder.dump_all(
                f"actor host failing fast: slots {abandoned} out of "
                f"restart budget")
            for ind, w in list(workers.items()):
                print(f"[fleet] terminating healthy actor-{ind} "
                      "(host failing fast)")
                w.terminate()
                w.join(10.0)
            workers.clear()
            pending.clear()
            break
    if host_stop.is_set():
        print(f"[fleet] SIGTERM: preemption notice — terminating "
              f"{len(workers)} actors on this host")
        host_recorder.record("sigterm-preemption", live=len(workers))
        flight_recorder.dump_all("SIGTERM preemption notice (actor host)")
        for ind, w in list(workers.items()):
            w.terminate()
            w.join(10.0)
        workers.clear()
        pending.clear()
    if prev_term is not None:
        signal.signal(signal.SIGTERM, prev_term)
    if pusher is not None:
        pusher.stop()  # final tail drain rides the stop
    return abandoned


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def main(argv: Optional[List[str]] = None) -> None:
    ap = argparse.ArgumentParser(
        prog="pytorch_distributed_tpu.fleet",
        description="multi-host Ape-X fleet launcher")
    ap.add_argument("--role",
                    choices=("learner", "actors", "learner-replica",
                             "gateway-standby", "replay-shard"),
                    required=True)
    ap.add_argument("--replica-id", type=int, default=1,
                    help="[learner-replica] this host's replica id "
                         "(replica 0 is the lead learner host; ids "
                         "must be unique across the fleet)")
    ap.add_argument("--shard-id", type=int, default=0,
                    help="[replay-shard] this host's replay shard id "
                         "(ids must be unique across the fleet; "
                         "ISSUE 20, memory/shard_plane.py)")
    ap.add_argument("--config", type=int, default=1)
    ap.add_argument("--num-actors", type=int, default=None,
                    help="TOTAL fleet actor count (defaults to config)")
    ap.add_argument("--port", type=int, default=5555)
    ap.add_argument("--local-actors", type=int, default=0,
                    help="[learner] actors co-located on the learner host")
    ap.add_argument("--coordinator", type=str, default=None,
                    help="[actors|gateway-standby] learner host as "
                         "host:port; actor hosts may give a comma list "
                         "'h1:p1,h2:p2' (primary first, standby after) "
                         "and fail over between them (ISSUE 16)")
    ap.add_argument("--actor-base", type=int, default=0,
                    help="[actors] first global actor slot on this host")
    ap.add_argument("--actor-count", type=int, default=8,
                    help="[actors] actors to run on this host")
    ap.add_argument("--seed", type=int, default=None)
    ap.add_argument("--actor-backend", type=str, default=None,
                    choices=("inline", "pipelined", "batched", "device",
                             "anakin"),
                    help="actor hot-loop schedule (config.py EnvParams."
                         "actor_backend): pipelined = overlapped "
                         "two-stage loop (default), inline = serial "
                         "fallback, batched = SEED-style shared "
                         "inference on the learner host — applies to "
                         "that host's LOCAL actors; remote actor hosts "
                         "have no co-located server and auto-downgrade "
                         "to pipelined; device = Sebulba on-device env "
                         "fleet (pure-JAX envs fused with the policy "
                         "into one scan, envs/device_env.py — dqn + "
                         "device-implemented envs only, others "
                         "downgrade); anakin = the CLOSED loop (ISSUE "
                         "12): env fleet + learner in ONE process, no "
                         "actor workers on the learner host at all "
                         "(agents/anakin.py — remote actor hosts in a "
                         "hybrid fleet run the device schedule) "
                         "(factory.resolve_actor_backend)")
    ap.add_argument("--resume", type=str, default=None, metavar="REFS",
                    help="[learner] resume run REFS from its newest "
                         "complete checkpoint epoch (models/REFS_ckpt — "
                         "written on the checkpoint_freq cadence and on "
                         "SIGTERM preemption); fails fast if none exists. "
                         "Remote actor hosts need no flag: their slots "
                         "re-attach through the DCN session layer's "
                         "incarnation fencing as on any learner restart.")
    ap.add_argument("--set", action="append", default=[], metavar="K=V",
                    help="Options override, e.g. --set steps=2000 "
                         "--set batch_size=32 (repeatable; int/float/str "
                         "auto-typed). Must match on every host.")
    ap.add_argument("--faults-client", type=str, default=None,
                    metavar="SPEC",
                    help="fault-injection spec for DCN clients on this "
                         "host (utils/faults.py grammar, e.g. "
                         "'sever@40,corrupt@90' or 'random:7'); exported "
                         "as DCN_FAULTS_CLIENT so spawn children inherit")
    ap.add_argument("--faults-gateway", type=str, default=None,
                    metavar="SPEC",
                    help="[learner] fault-injection spec for the gateway "
                         "(DCN_FAULTS_GATEWAY)")
    ap.add_argument("--reconnect-timeout", type=float, default=None,
                    help="seconds a disconnected actor redials before "
                         "declaring its session lost (DCN_RECONNECT_TIMEOUT)")
    ap.add_argument("--heartbeat", type=float, default=None,
                    help="idle seconds between client heartbeat pings "
                         "(DCN_HEARTBEAT_INTERVAL; <=0 disables)")
    args = ap.parse_args(argv)

    for env, val in (("DCN_FAULTS_CLIENT", args.faults_client),
                     ("DCN_FAULTS_GATEWAY", args.faults_gateway),
                     ("DCN_RECONNECT_TIMEOUT", args.reconnect_timeout),
                     ("DCN_HEARTBEAT_INTERVAL", args.heartbeat)):
        if val is not None:
            os.environ[env] = str(val)

    from pytorch_distributed_tpu.config import parse_set_overrides

    overrides = parse_set_overrides(args.set)
    if args.num_actors is not None:
        overrides["num_actors"] = args.num_actors
    if args.seed is not None:
        overrides["seed"] = args.seed
    if args.actor_backend is not None:
        overrides["actor_backend"] = args.actor_backend
    if args.resume is not None:
        if args.role != "learner":
            ap.error("--resume applies to the learner host (actor hosts "
                     "re-attach through DCN incarnation fencing)")
        overrides["refs"] = args.resume
        overrides["resume"] = "must"
    opt = build_options(args.config, **overrides)

    if args.role == "learner":
        run_fleet_learner(opt, local_actors=args.local_actors,
                          port=args.port)
    elif args.role == "learner-replica":
        assert args.coordinator, "--coordinator host:port required"
        run_replica_host(opt, args.coordinator, args.replica_id)
    elif args.role == "gateway-standby":
        assert args.coordinator, "--coordinator host:port required"
        run_gateway_standby(opt, args.coordinator, args.port)
    elif args.role == "replay-shard":
        assert args.coordinator, "--coordinator host:port required"
        run_replay_shard_host(opt, args.coordinator, args.shard_id,
                              args.port)
    else:
        assert args.coordinator, "--coordinator host:port required"
        abandoned = run_fleet_actors(opt, args.coordinator, args.actor_base,
                                     args.actor_count)
        if abandoned:
            print(f"[fleet] actor host FAILED: slots {abandoned} out of "
                  "restart budget")
            sys.exit(1)


if __name__ == "__main__":
    main()
