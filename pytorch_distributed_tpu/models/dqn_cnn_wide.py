"""MXU-filling IMPALA-deep convolutional Q-network (ISSUE 13).

The Nature CNN (models/dqn_cnn.py) structurally underfills a 128-lane
MXU: its 4/32/64-wide conv channels leave most lanes idle regardless of
batch size or dtype (tools/mfu_probe.py lever sweep, BENCH_r03
``mfu_bound``).  This family is the third front of the MFU campaign: an
IMPALA-style residual stack (Espeholt et al. 2018) whose channel widths
are MULTIPLES OF 128 — sections (width, 2*width, 2*width) with
``width`` defaulting to 128 (ModelParams.cnn_wide_width) — so every
conv GEMM's contraction and output lanes land on the MXU grid exactly.
~50x the Nature torso's FLOPs per forward, spent at high utilization
instead of idling lanes: on a dispatch-rich TPU the chip, not the
program structure, becomes the bottleneck (the Podracer recipe).

Same external contract as DqnCnnModel — (B, C, H, W) uint8 frame
stacks, /norm_val normalisation, compute-dtype forward with fp32
params, fp32 Q-values, ``example_input`` — so the factory, replay
geometry, eval plane and checkpoints plug in unchanged (CONFIGS row
19).  Sample-efficiency parity vs the Nature torso is an eval-plane
drive (TESTING.md), not an assumption: the family trains through the
SAME loss/target machinery, only the torso widens.
"""

from __future__ import annotations

from typing import Tuple

import flax.linen as nn
import jax.numpy as jnp
from flax.linen.initializers import orthogonal, zeros_init


class _ResBlock(nn.Module):
    channels: int
    compute_dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        kw = dict(kernel_init=orthogonal(jnp.sqrt(2.0)),
                  bias_init=zeros_init())
        y = nn.relu(x)
        y = nn.Conv(self.channels, (3, 3), padding="SAME",
                    dtype=self.compute_dtype, **kw)(y)
        y = nn.relu(y)
        y = nn.Conv(self.channels, (3, 3), padding="SAME",
                    dtype=self.compute_dtype, **kw)(y)
        return x + y


class DqnCnnWideModel(nn.Module):
    action_space: int
    norm_val: float = 255.0
    # base width; sections run (width, 2*width, 2*width).  Keep it a
    # multiple of 128 — that alignment IS this family's reason to exist.
    width: int = 128
    compute_dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        # x: (B, C, H, W) uint8/float -> NHWC compute in bf16 (the
        # DqnCnnModel input contract)
        x = x.astype(self.compute_dtype) / jnp.asarray(
            self.norm_val, dtype=self.compute_dtype)
        x = jnp.transpose(x, (0, 2, 3, 1))
        kw = dict(kernel_init=orthogonal(jnp.sqrt(2.0)),
                  bias_init=zeros_init())
        for channels in (self.width, 2 * self.width, 2 * self.width):
            x = nn.Conv(channels, (3, 3), padding="SAME",
                        dtype=self.compute_dtype, **kw)(x)
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
            x = _ResBlock(channels, self.compute_dtype)(x)
            x = _ResBlock(channels, self.compute_dtype)(x)
        x = nn.relu(x)
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(512, dtype=self.compute_dtype, **kw)(x)
        x = nn.relu(x)
        q = nn.Dense(self.action_space, dtype=self.compute_dtype,
                     kernel_init=orthogonal(1.0),
                     bias_init=zeros_init())(x)
        return q.astype(jnp.float32)

    @staticmethod
    def example_input(batch: int = 1,
                      state_shape: Tuple[int, ...] = (4, 84, 84)
                      ) -> jnp.ndarray:
        return jnp.zeros((batch, *state_shape), dtype=jnp.uint8)
