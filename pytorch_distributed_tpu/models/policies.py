"""Action-selection as pure jitted functions.

The reference folds action selection into the torch modules
(``get_action``: reference core/models/dqn_cnn_model.py:58-78,
ddpg_mlp_model.py:74-78).  TPU-first, these are standalone functions of
``(params, obs, key, ...)`` with explicit randomness, jit-compiled once and
reused by actors / evaluators / testers; they are batch-shaped so one call
can serve a whole vector of envs (the batched-inference answer to the
reference's latency-bound batch-1 actor forward, SURVEY.md §7 "hard
parts").
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np


def apex_epsilon(process_ind: int, num_actors: int,
                 eps: float = 0.4, eps_alpha: float = 7.0) -> float:
    """Ape-X per-actor exploration schedule
    ``eps ** (1 + i/(N-1) * alpha)`` (reference dqn_actor.py:33-36, with the
    reference's 1-based indexing of actors and its single-actor debug value).
    """
    if num_actors <= 1:
        return 0.1  # reference dqn_actor.py:33-34 debug branch
    frac = process_ind / (num_actors - 1)
    return float(eps ** (1.0 + frac * eps_alpha))


def apex_epsilons(process_ind: int, num_actors: int, num_envs: int,
                  eps: float = 0.4, eps_alpha: float = 7.0):
    """Per-env epsilon vector for a vectorized actor: env j of actor i
    takes fleet slot i*num_envs + j of num_actors*num_envs, so exploration
    diversity spans the whole fleet exactly as the reference's per-actor
    schedule spans its actors (reference dqn_actor.py:33-36)."""
    import numpy as np

    total = num_actors * num_envs
    return np.asarray(
        [apex_epsilon(process_ind * num_envs + j, total, eps, eps_alpha)
         for j in range(num_envs)], dtype=np.float32)


def build_epsilon_greedy_act(apply_fn: Callable) -> Callable:
    """eps-greedy over a Q-network.

    Returns a jitted ``act(params, obs[B,...], key, eps) ->
    (action[B], q_sel[B], q_max[B])``; ``eps`` may be a scalar or a (B,)
    per-sample vector (the vectorized-actor fleet schedule).  q_sel/q_max
    feed PER initial priorities, mirroring the tuple the reference returns
    when PER is on (reference dqn_cnn_model.py:65-78) — here they are
    always returned (cost-free under jit).
    """

    def act(params, obs, key, eps):
        q = apply_fn(params, obs)                        # (B, A)
        batch, num_actions = q.shape
        greedy = jnp.argmax(q, axis=-1)
        key_explore, key_choice = jax.random.split(key)
        random_a = jax.random.randint(key_choice, (batch,), 0, num_actions)
        explore = jax.random.uniform(key_explore, (batch,)) < eps
        action = jnp.where(explore, random_a, greedy)
        q_sel = jnp.take_along_axis(q, action[:, None], axis=-1)[:, 0]
        return action, q_sel, jnp.max(q, axis=-1)

    return jax.jit(act)


def tick_keys(base_key, tick, num_envs: int):
    """Per-(tick, env-row) PRNG keys derived ON DEVICE: fold the tick
    counter into the actor's base key, then fold each row index.  This is
    the pipelined actor's replacement for the serial loop's host-side
    ``jax.random.split`` chain (ISSUE 4 tentpole): the base key is
    committed once and never leaves the device, per-tick randomness is a
    pure function of ``(base_key, tick, row)``, and — because rows are
    keyed independently — the SAME stream falls out whether rows are
    evaluated by the local inline loop, the local pipelined loop, or a
    shared inference server batching rows from many actors."""
    k = jax.random.fold_in(base_key, tick)
    return jax.vmap(lambda j: jax.random.fold_in(k, j))(
        jnp.arange(num_envs))


def _rowwise_eps_greedy(q, row_keys, eps):
    """Row-keyed eps-greedy: each row draws from its own key so action
    randomness is independent of how rows were batched together."""
    num_actions = q.shape[-1]

    def row(qr, key, e):
        key_explore, key_choice = jax.random.split(key)
        random_a = jax.random.randint(key_choice, (), 0, num_actions)
        explore = jax.random.uniform(key_explore) < e
        return jnp.where(explore, random_a, jnp.argmax(qr))

    return jax.vmap(row)(q, row_keys, eps)


def _pack_dqn(q, action):
    """One (3, B) float32 array — (action, q_sel, q_max) rows — so a tick
    costs ONE device->host copy instead of three (action indices are
    small integers, exactly representable in f32)."""
    q_sel = jnp.take_along_axis(q, action[:, None], axis=-1)[:, 0]
    return jnp.stack([action.astype(jnp.float32),
                      q_sel.astype(jnp.float32),
                      jnp.max(q, axis=-1).astype(jnp.float32)])


def build_packed_act(apply_fn: Callable) -> Callable:
    """The pipelined actor's fused per-tick program (ISSUE 4 tentpole).

    Returns a jitted ``act(params, obs[B,...], base_key, tick, eps[B]) ->
    packed[3, B]`` where ``packed`` stacks (action, q_sel, q_max) as one
    float32 array.  Everything the serial loop did on the host per tick —
    key split, action selection, the three separate device reads — is
    fused on-device: the PRNG key stays resident (``tick_keys`` folds the
    tick counter instead of a host-side split chain), and the single
    packed output means one dispatch + one D2H copy per tick.  ``tick``
    is a traced scalar, so consecutive ticks NEVER retrace.

    The obs is deliberately NOT donated: none of the shipped feedforward
    nets produce an output that could alias it (XLA would just warn the
    donation off).  The buffer donations that pay in this codebase are
    the recurrent carry (``build_recurrent_packed_act``) and the
    server-side roll stack (``build_packed_roll_act``).
    """

    def act(params, obs, base_key, tick, eps):
        q = apply_fn(params, obs)                        # (B, A)
        action = _rowwise_eps_greedy(q, tick_keys(base_key, tick,
                                                  q.shape[0]), eps)
        return _pack_dqn(q, action)

    return jax.jit(act)


def build_packed_roll_act(apply_fn: Callable) -> Callable:
    """Frame-packed variant of ``build_packed_act`` for the shared
    inference server (agents/inference.py): the client ships only the
    NEWEST frame per env and the device rolls its resident history stack
    before acting, fused into the same dispatch —
    ``act(params, stack[B,C,H,W], new[B,H,W], base_key, tick, eps) ->
    (stack', packed[3,B])``.

    Over a tunnelled chip this cuts the per-tick upload by the stack
    factor C (451 KB -> 113 KB for the production 16-env Nature-CNN
    shape) — the difference between the obs plane fitting next to the
    replay-ingest stream or fighting it for the link.  The stack is
    DONATED (stack' has its exact shape/dtype, so XLA rolls in place).
    The client only elects this path when the roll property held on the
    host (``obs[:, :-1] == prev[:, 1:]`` — any env reset falls back to a
    full upload that also reseeds the device stack), so the device
    reconstruction is bit-exact with what the env emitted."""

    def act(params, stack, new, base_key, tick, eps):
        stack = jnp.concatenate([stack[:, 1:], new[:, None]], axis=1)
        q = apply_fn(params, stack)
        action = _rowwise_eps_greedy(q, tick_keys(base_key, tick,
                                                  q.shape[0]), eps)
        return stack, _pack_dqn(q, action)

    return jax.jit(act, donate_argnums=(1,))


def build_packed_act_rowkeys(apply_fn: Callable) -> Callable:
    """Server-side variant of ``build_packed_act`` taking precomputed
    per-row keys: the inference batcher concatenates rows from several
    actors into one wide forward, so each row's key comes from ITS
    actor's (base_key, tick, row) fold — identical streams to the local
    paths regardless of batch composition."""

    def act(params, obs, row_keys, eps):
        q = apply_fn(params, obs)
        return _pack_dqn(q, _rowwise_eps_greedy(q, row_keys, eps))

    return jax.jit(act)


# ---------------------------------------------------------------------------
# The fused device rollout (ISSUE 7 tentpole): env + policy + n-step
# assembly in ONE donated on-device scan.
# ---------------------------------------------------------------------------

from typing import NamedTuple  # noqa: E402


class RolloutCarry(NamedTuple):
    """Everything the fused rollout keeps device-resident between
    dispatches: the env fleet's state and the open n-step windows.

    Window bookkeeping implements EXACTLY the ``ops/nstep.py``
    assembler semantics, restructured for fixed shapes: every env tick
    t opens exactly one window (s_t, a_t); a window closes when it
    accumulates ``nstep`` rewards or the episode ends (true terminals
    mark ``terminal1``; truncation closes but still bootstraps); and
    every window is EMITTED a fixed ``nstep`` ticks after it opened —
    by which point it is guaranteed closed and its bootstrap q_max
    (the NEXT forward after its close, the same forward the host
    actor's pending-queue used) has been stamped.  Fixed delay means
    exactly one emission slot per env per tick — no data-dependent
    output shapes — at the cost of rings of the last ``nstep + 1``
    ticks of per-window state and true post-step observations."""

    env_state: Any
    win_s0: Any          # (N, R, *obs) uint8 — s0 of window per slot
    win_action: Any      # (N, R) int32
    win_qsel: Any        # (N, R) f32 — q(s0, a) at open
    win_racc: Any        # (N, R) f32 — discounted reward accumulator
    win_age: Any         # (N, R) int32 — rewards accumulated
    win_open: Any        # (N, R) bool
    win_term: Any        # (N, R) f32 — terminal1 stamped at close
    win_prio_ok: Any     # (N, R) bool — False for truncated closes
    win_close_slot: Any  # (N, R) int32 — obs_true slot of the close
    win_qboot: Any       # (N, R) f32 — bootstrap q_max, stamped late
    win_need_boot: Any   # (N, R) bool — closed, awaiting next forward
    obs_true: Any        # (N, R, *obs) uint8 — true post-step obs ring


class RolloutChunk(NamedTuple):
    """Per-dispatch emission: ``(K, N)``-leading transition columns
    (the six replay fields) plus the PER scalars and per-tick env
    stats.  ``valid`` is False only for the run's first ``nstep``
    warmup ticks.  ``prio_ok`` False marks truncated-close windows —
    the host path feeds those with priority None (new-sample max)."""

    state0: Any
    action: Any
    reward: Any
    gamma_n: Any
    state1: Any
    terminal1: Any
    valid: Any
    q_sel: Any
    q_boot: Any
    prio_ok: Any
    step_reward: Any     # (K, N) f32 raw per-tick env rewards
    step_terminal: Any   # (K, N) bool
    step_truncated: Any  # (K, N) bool


class RolloutStats(NamedTuple):
    """The replay-emit variant's host-visible output (everything else
    stays in HBM): per-tick env stats only."""

    step_reward: Any
    step_terminal: Any
    step_truncated: Any
    fed: Any             # () int32 — rows written into the ring


def init_rollout_carry(env, nstep: int) -> RolloutCarry:
    """Fresh carry for ``build_fused_rollout``: env at reset, no open
    windows.  Ring depth R = nstep + 1: the emission slot (t - nstep)
    and the open slot (t) must never collide."""
    import jax.numpy as jnp

    n = env.num_envs
    R = nstep + 1
    obs_shape = tuple(env.state_shape)
    env_state = env.init()
    z = lambda dt: jnp.zeros((n, R), dt)
    return RolloutCarry(
        env_state=env_state,
        win_s0=jnp.zeros((n, R, *obs_shape), jnp.uint8),
        win_action=z(jnp.int32), win_qsel=z(jnp.float32),
        win_racc=z(jnp.float32), win_age=z(jnp.int32),
        win_open=z(bool), win_term=z(jnp.float32),
        win_prio_ok=z(bool), win_close_slot=z(jnp.int32),
        win_qboot=z(jnp.float32), win_need_boot=z(bool),
        obs_true=jnp.zeros((n, R, *obs_shape), jnp.uint8),
    )


def build_fused_rollout(apply_fn: Callable, env, *, nstep: int,
                        gamma: float, rollout_ticks: int,
                        emit: str = "chunk",
                        ring_write_fn: Callable = None) -> Callable:
    """ONE donated on-device scan advancing N envs x K ticks: per tick,
    the policy forward, row-keyed eps-greedy action selection, the
    vectorized env step, and n-step transition assembly all run inside
    the same XLA program — obs stacks, PRNG, env state and the open
    n-step windows never leave the device, and finished transitions
    are emitted device-side (no per-tick H2D/D2H).

    Randomness rides the exact ISSUE-4 stream contract: row keys are
    ``tick_keys(base_key, tick, row)`` folds, so the action stream for
    any (actor, tick, env-row) is bit-identical to what the
    inline/pipelined/batched backends produce over the same env.

    ``emit``:

    - ``"chunk"`` — the scan returns a ``RolloutChunk`` of (K, N)
      transition columns; the cross-process actor driver ships it to
      the replay feeder with ONE device->host copy per dispatch
      (amortized over K*N frames).  Returns a jitted
      ``rollout(params, carry, base_key, tick0, eps) ->
      (carry', RolloutChunk)`` with ``carry`` DONATED.
    - ``"replay"`` — the scan scatters valid rows straight into a
      device replay ``ReplayState`` carried through the program
      (memory/device_replay.ring_write_masked): experience lands in
      the learner-side HBM ring with ZERO host round-trip — the
      co-located Sebulba topology, and the bench's fused section.
      Returns ``rollout(params, carry, ring_state, base_key, tick0,
      eps) -> (carry', ring_state', RolloutStats)`` with ``carry`` and
      ``ring_state`` donated.

    ``tick0`` is a traced scalar (the global tick of the dispatch's
    first tick), so consecutive dispatches NEVER retrace; the caller
    advances it by ``rollout_ticks`` per call.  Priorities: the chunk
    carries ``q_sel``/``q_boot``/``prio_ok`` columns so the host can
    form the actor-side PER priority |R + gamma_n*maxQ(s_end) - q_sel|
    with two flops per row — same estimate, no device sync.

    ``ring_write_fn`` (emit="replay" only) overrides the masked ring
    scatter — the hook the co-located Anakin loop (agents/anakin.py)
    uses to write into the HBM PER ring with new-row priority stamping
    (memory/device_per.per_write_masked); None keeps the uniform-ring
    ``ring_write_masked``.  The interleave contract for that loop: the
    rollout program reads ``params`` but never writes them, and the
    fused learner program reads the ring but only ever writes the
    priority column — so alternating (or double-buffer-interleaving)
    the two dispatches against the same device-resident state is
    race-free by construction, and the acting params ARE the train
    state's params (the published version is the acting version).
    """
    import jax
    import jax.numpy as jnp

    assert emit in ("chunk", "replay")
    n = env.num_envs
    R = nstep + 1
    K = int(rollout_ticks)
    # f64-computed discount powers (cast once): the host assembler
    # accumulates in python f64 and casts at emit, so a f32 pow chain
    # here would drift a final ulp on scoring windows
    gamma_pow = jnp.asarray(
        np.power(np.float64(gamma), np.arange(R)).astype(np.float32))

    if emit == "replay":
        from pytorch_distributed_tpu.memory.device_replay import (
            ring_write_masked,
        )
        from pytorch_distributed_tpu.utils.experience import Transition

        if ring_write_fn is None:
            ring_write_fn = ring_write_masked

    def one_tick(params, eps, base_key, c: RolloutCarry, t):
        obs = env.observe(c.env_state)
        q = apply_fn(params, obs)
        qmax = jnp.max(q, axis=-1).astype(jnp.float32)
        # late bootstrap stamp: windows closed at t-1 take THIS
        # forward's q_max — the same forward the host actor's pending
        # queue resolved against (agents/actor._resolve_pending); the
        # stamp satisfies every waiting window, so need_boot resets
        qboot = jnp.where(c.win_need_boot, qmax[:, None], c.win_qboot)
        need_boot = jnp.zeros_like(c.win_need_boot)
        action = _rowwise_eps_greedy(q, tick_keys(base_key, t, n), eps)
        q_sel = jnp.take_along_axis(
            q, action[:, None], axis=-1)[:, 0].astype(jnp.float32)
        env_state, out = env.step(c.env_state, action.astype(jnp.int32))
        slot = (t % R).astype(jnp.int32)
        cols = jnp.arange(R, dtype=jnp.int32)
        at_slot = cols[None, :] == slot             # (1, R) -> broadcast
        term = out.terminal
        trunc = out.truncated
        true_term = (term & ~trunc).astype(jnp.float32)

        # slot writes via dynamic_update_index_in_dim, NOT a where over
        # the whole ring: the obs rings are the carry's bulk (N x R
        # stacks), and a where-based write would stream the full ring
        # through memory every tick — measured ~4x on the whole engine
        def set_slot(ring, val):
            return jax.lax.dynamic_update_index_in_dim(ring, val, slot,
                                                       axis=1)

        # open this tick's window at ``slot``
        win_s0 = set_slot(c.win_s0, obs)
        win_action = set_slot(c.win_action, action.astype(jnp.int32))
        win_qsel = set_slot(c.win_qsel, q_sel)
        win_racc = set_slot(c.win_racc, jnp.zeros((n,), jnp.float32))
        win_age = set_slot(c.win_age, jnp.zeros((n,), jnp.int32))
        win_open = set_slot(c.win_open, jnp.ones((n,), bool))
        # accumulate this tick's reward into every open window
        win_racc = win_racc + jnp.where(
            win_open, gamma_pow[win_age] * out.reward[:, None], 0.0)
        win_age = win_age + win_open
        # true post-step obs ring (final_obs preserves the terminal
        # frame; non-terminal rows it equals the next obs)
        obs_true = set_slot(c.obs_true, out.final_obs)
        # closes: window full, or episode over (truncation included)
        closing = win_open & ((win_age >= nstep) | term[:, None])
        win_open = win_open & ~closing
        win_term = jnp.where(closing, true_term[:, None], c.win_term)
        win_prio_ok = jnp.where(closing, (~trunc)[:, None], c.win_prio_ok)
        win_close_slot = jnp.where(closing, slot, c.win_close_slot)
        need_boot = jnp.where(closing, (true_term == 0.0)[:, None],
                              need_boot)
        # emission: the window opened nstep ticks ago — closed by
        # t-1 at the latest, boot-stamped by this tick's forward
        slot_e = ((t - nstep) % R).astype(jnp.int32)
        rows = jnp.arange(n)
        valid = jnp.broadcast_to(t >= nstep, (n,))

        def get_slot(ring):
            return jax.lax.dynamic_index_in_dim(ring, slot_e, axis=1,
                                                keepdims=False)

        term1_e = get_slot(win_term)
        close_e = get_slot(win_close_slot)
        s1 = jnp.take_along_axis(
            obs_true, close_e.reshape((n, 1) + (1,) * (
                obs_true.ndim - 2)), axis=1)[:, 0]
        emitted = dict(
            state0=get_slot(win_s0),
            action=get_slot(win_action),
            reward=get_slot(win_racc),
            gamma_n=gamma_pow[get_slot(win_age)],
            state1=s1,
            terminal1=term1_e,
            valid=valid,
            q_sel=get_slot(win_qsel),
            # true terminals never bootstrap; zeroing the column keeps
            # the chunk self-describing (stale slot values otherwise)
            q_boot=jnp.where(term1_e > 0, 0.0, get_slot(qboot)),
            prio_ok=get_slot(win_prio_ok),
        )
        carry = RolloutCarry(
            env_state=env_state, win_s0=win_s0, win_action=win_action,
            win_qsel=win_qsel, win_racc=win_racc, win_age=win_age,
            win_open=win_open, win_term=win_term,
            win_prio_ok=win_prio_ok, win_close_slot=win_close_slot,
            win_qboot=qboot, win_need_boot=need_boot,
            obs_true=obs_true)
        stats = (out.reward, term, trunc)
        return carry, emitted, stats

    if emit == "chunk":
        def rollout(params, carry, base_key, tick0, eps):
            ticks = tick0 + jnp.arange(K)

            def body(c, t):
                c, emitted, (r, te, tr) = one_tick(params, eps,
                                                   base_key, c, t)
                return c, RolloutChunk(step_reward=r, step_terminal=te,
                                       step_truncated=tr, **emitted)

            carry, chunk = jax.lax.scan(body, carry, ticks)
            return carry, chunk

        return jax.jit(rollout, donate_argnums=(1,))

    def rollout(params, carry, ring_state, base_key, tick0, eps,
                prov=None):
        # ``prov`` (optional): a (3,) int32 of (actor_id, param_version,
        # birth_step) for THIS dispatch — scattered into the ring's
        # provenance columns alongside each emitted row, with env_slot =
        # the env's row index (ISSUE 8).  Stamps quantize to the
        # dispatch exactly like the chunk-mode host stamps; None leaves
        # the columns at the -1 sentinel (legacy callers).
        ticks = tick0 + jnp.arange(K)
        capacity = ring_state.reward.shape[0]
        rows_prov = None
        if prov is not None:
            rows_prov = jnp.stack([
                jnp.full((n,), prov[0], jnp.int32),
                jnp.arange(n, dtype=jnp.int32),
                jnp.full((n,), prov[1], jnp.int32),
                jnp.full((n,), prov[2], jnp.int32)], axis=1)

        def body(cs, t):
            c, ring, fed = cs
            c, e, (r, te, tr) = one_tick(params, eps, base_key, c, t)
            ring, wrote = ring_write_fn(
                ring, Transition(
                    state0=e["state0"], action=e["action"],
                    reward=e["reward"], gamma_n=e["gamma_n"],
                    state1=e["state1"], terminal1=e["terminal1"],
                    prov=rows_prov),
                e["valid"], capacity)
            return (c, ring, fed + wrote), (r, te, tr)

        (carry, ring_state, fed), (r, te, tr) = jax.lax.scan(
            body, (carry, ring_state, jnp.int32(0)), ticks)
        return carry, ring_state, RolloutStats(
            step_reward=r, step_terminal=te, step_truncated=tr, fed=fed)

    return jax.jit(rollout, donate_argnums=(1, 2))


def rollout_priorities(chunk_np: dict, enabled: bool):
    """Actor-side PER initial priorities off a fetched chunk's columns:
    |R + gamma_n * maxQ(s_end) - q_sel| with the bootstrap term zeroed
    on true terminals (the q_boot column already is) — the exact
    estimate the host actor's pending-queue computes
    (agents/actor.py).  Rows with ``prio_ok`` False (truncated closes)
    get None: the host path feeds those at new-sample max priority.
    Returns an object-dtype convenience: (N,) array of float-or-None.
    """
    if not enabled:
        return None
    f8 = lambda k: np.asarray(chunk_np[k], np.float64)
    # f64 like the host actor's python-float arithmetic, so the two
    # paths assign identical priorities to identical transitions
    pr = np.abs(f8("reward") + f8("gamma_n") * (1.0 - f8("terminal1"))
                * f8("q_boot") - f8("q_sel"))
    out = np.empty(pr.shape, dtype=object)
    ok = np.asarray(chunk_np["prio_ok"], bool)
    out[ok] = pr[ok].astype(np.float64)
    out[~ok] = None
    return out


def build_greedy_act(apply_fn: Callable) -> Callable:
    """Pure-greedy variant for evaluator/tester (reference evaluators.py:56-86
    runs eps=0 episodes)."""

    def act(params, obs):
        q = apply_fn(params, obs)
        return jnp.argmax(q, axis=-1), jnp.max(q, axis=-1)

    return jax.jit(act)


def build_recurrent_epsilon_greedy_act(apply_fn: Callable) -> Callable:
    """eps-greedy over a recurrent Q-network (models/drqn.py contract
    ``apply(params, obs, carry) -> (q, carry')``).  Returns a jitted
    ``act(params, obs[B,...], carry, key, eps) -> (action[B], carry')`` —
    the caller owns the carry and resets env rows at episode ends."""

    def act(params, obs, carry, key, eps):
        q, carry = apply_fn(params, obs, carry)
        batch, num_actions = q.shape
        greedy = jnp.argmax(q, axis=-1)
        key_explore, key_choice = jax.random.split(key)
        random_a = jax.random.randint(key_choice, (batch,), 0, num_actions)
        explore = jax.random.uniform(key_explore, (batch,)) < eps
        return jnp.where(explore, random_a, greedy), carry

    return jax.jit(act)


def build_recurrent_packed_act(apply_fn: Callable, zero_carry) -> Callable:
    """Fused recurrent act for the pipelined loop: the carry stays
    DEVICE-RESIDENT across ticks (no per-tick host round-trip of the LSTM
    state into the forward), and episode resets arrive as a per-row
    boolean mask folded in on-device — row j's carry is replaced with the
    model's zero carry before acting when ``reset_mask[j]`` is set, which
    is exactly the host-side row reset the serial loop performed between
    ticks.

    ``zero_carry`` is the model's ``zero_carry(1)`` pytree (leading dim 1
    broadcasts over rows).  Returns a jitted ``act(params, obs, carry,
    reset_mask[B], base_key, tick, eps[B]) -> (action[B] int32, carry')``.
    The caller owns the device carry and keeps a host copy for segment
    storage (agents/recurrent_actor.py).

    The carry argument is DONATED: carry' has exactly carry's shapes, so
    XLA updates it in place — for the transformer family, whose carry IS
    the rolling (B, window, *obs) context buffer, this is the ISSUE 4
    "donate the obs buffer" optimisation (no per-tick reallocation of
    the window).  Callers must treat the passed-in carry as consumed,
    which the engine's swap-on-submit discipline guarantees."""
    zero = jax.tree_util.tree_map(jnp.asarray, zero_carry)

    def act(params, obs, carry, reset_mask, base_key, tick, eps):
        def reset_rows(c, z):
            mask = reset_mask.reshape(reset_mask.shape[0],
                                      *([1] * (c.ndim - 1)))
            return jnp.where(mask, z.astype(c.dtype), c)

        carry = jax.tree_util.tree_map(reset_rows, carry, zero)
        q, carry = apply_fn(params, obs, carry)
        action = _rowwise_eps_greedy(
            q, tick_keys(base_key, tick, q.shape[0]), eps)
        return action.astype(jnp.int32), carry

    return jax.jit(act, donate_argnums=(2,))


def build_recurrent_greedy_act(apply_fn: Callable) -> Callable:
    """Greedy recurrent variant for evaluator/tester."""

    def act(params, obs, carry):
        q, carry = apply_fn(params, obs, carry)
        return jnp.argmax(q, axis=-1), carry

    return jax.jit(act)


def build_ddpg_act(actor_apply_fn: Callable) -> Callable:
    """Deterministic policy forward ``act(params, obs[B,...]) -> action[B,d]``
    in [-1,1]; exploration noise (OU) is added host-side by the actor
    process, as in the reference (reference ddpg_mlp_model.py:74-78 returns
    action + noise; here noise stays outside the jitted function so the OU
    state lives with the process)."""

    def act(params, obs):
        return actor_apply_fn(params, obs)

    return jax.jit(act)
