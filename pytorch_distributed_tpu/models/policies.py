"""Action-selection as pure jitted functions.

The reference folds action selection into the torch modules
(``get_action``: reference core/models/dqn_cnn_model.py:58-78,
ddpg_mlp_model.py:74-78).  TPU-first, these are standalone functions of
``(params, obs, key, ...)`` with explicit randomness, jit-compiled once and
reused by actors / evaluators / testers; they are batch-shaped so one call
can serve a whole vector of envs (the batched-inference answer to the
reference's latency-bound batch-1 actor forward, SURVEY.md §7 "hard
parts").
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp


def apex_epsilon(process_ind: int, num_actors: int,
                 eps: float = 0.4, eps_alpha: float = 7.0) -> float:
    """Ape-X per-actor exploration schedule
    ``eps ** (1 + i/(N-1) * alpha)`` (reference dqn_actor.py:33-36, with the
    reference's 1-based indexing of actors and its single-actor debug value).
    """
    if num_actors <= 1:
        return 0.1  # reference dqn_actor.py:33-34 debug branch
    frac = process_ind / (num_actors - 1)
    return float(eps ** (1.0 + frac * eps_alpha))


def apex_epsilons(process_ind: int, num_actors: int, num_envs: int,
                  eps: float = 0.4, eps_alpha: float = 7.0):
    """Per-env epsilon vector for a vectorized actor: env j of actor i
    takes fleet slot i*num_envs + j of num_actors*num_envs, so exploration
    diversity spans the whole fleet exactly as the reference's per-actor
    schedule spans its actors (reference dqn_actor.py:33-36)."""
    import numpy as np

    total = num_actors * num_envs
    return np.asarray(
        [apex_epsilon(process_ind * num_envs + j, total, eps, eps_alpha)
         for j in range(num_envs)], dtype=np.float32)


def build_epsilon_greedy_act(apply_fn: Callable) -> Callable:
    """eps-greedy over a Q-network.

    Returns a jitted ``act(params, obs[B,...], key, eps) ->
    (action[B], q_sel[B], q_max[B])``; ``eps`` may be a scalar or a (B,)
    per-sample vector (the vectorized-actor fleet schedule).  q_sel/q_max
    feed PER initial priorities, mirroring the tuple the reference returns
    when PER is on (reference dqn_cnn_model.py:65-78) — here they are
    always returned (cost-free under jit).
    """

    def act(params, obs, key, eps):
        q = apply_fn(params, obs)                        # (B, A)
        batch, num_actions = q.shape
        greedy = jnp.argmax(q, axis=-1)
        key_explore, key_choice = jax.random.split(key)
        random_a = jax.random.randint(key_choice, (batch,), 0, num_actions)
        explore = jax.random.uniform(key_explore, (batch,)) < eps
        action = jnp.where(explore, random_a, greedy)
        q_sel = jnp.take_along_axis(q, action[:, None], axis=-1)[:, 0]
        return action, q_sel, jnp.max(q, axis=-1)

    return jax.jit(act)


def tick_keys(base_key, tick, num_envs: int):
    """Per-(tick, env-row) PRNG keys derived ON DEVICE: fold the tick
    counter into the actor's base key, then fold each row index.  This is
    the pipelined actor's replacement for the serial loop's host-side
    ``jax.random.split`` chain (ISSUE 4 tentpole): the base key is
    committed once and never leaves the device, per-tick randomness is a
    pure function of ``(base_key, tick, row)``, and — because rows are
    keyed independently — the SAME stream falls out whether rows are
    evaluated by the local inline loop, the local pipelined loop, or a
    shared inference server batching rows from many actors."""
    k = jax.random.fold_in(base_key, tick)
    return jax.vmap(lambda j: jax.random.fold_in(k, j))(
        jnp.arange(num_envs))


def _rowwise_eps_greedy(q, row_keys, eps):
    """Row-keyed eps-greedy: each row draws from its own key so action
    randomness is independent of how rows were batched together."""
    num_actions = q.shape[-1]

    def row(qr, key, e):
        key_explore, key_choice = jax.random.split(key)
        random_a = jax.random.randint(key_choice, (), 0, num_actions)
        explore = jax.random.uniform(key_explore) < e
        return jnp.where(explore, random_a, jnp.argmax(qr))

    return jax.vmap(row)(q, row_keys, eps)


def _pack_dqn(q, action):
    """One (3, B) float32 array — (action, q_sel, q_max) rows — so a tick
    costs ONE device->host copy instead of three (action indices are
    small integers, exactly representable in f32)."""
    q_sel = jnp.take_along_axis(q, action[:, None], axis=-1)[:, 0]
    return jnp.stack([action.astype(jnp.float32),
                      q_sel.astype(jnp.float32),
                      jnp.max(q, axis=-1).astype(jnp.float32)])


def build_packed_act(apply_fn: Callable) -> Callable:
    """The pipelined actor's fused per-tick program (ISSUE 4 tentpole).

    Returns a jitted ``act(params, obs[B,...], base_key, tick, eps[B]) ->
    packed[3, B]`` where ``packed`` stacks (action, q_sel, q_max) as one
    float32 array.  Everything the serial loop did on the host per tick —
    key split, action selection, the three separate device reads — is
    fused on-device: the PRNG key stays resident (``tick_keys`` folds the
    tick counter instead of a host-side split chain), and the single
    packed output means one dispatch + one D2H copy per tick.  ``tick``
    is a traced scalar, so consecutive ticks NEVER retrace.

    The obs is deliberately NOT donated: none of the shipped feedforward
    nets produce an output that could alias it (XLA would just warn the
    donation off).  The buffer donations that pay in this codebase are
    the recurrent carry (``build_recurrent_packed_act``) and the
    server-side roll stack (``build_packed_roll_act``).
    """

    def act(params, obs, base_key, tick, eps):
        q = apply_fn(params, obs)                        # (B, A)
        action = _rowwise_eps_greedy(q, tick_keys(base_key, tick,
                                                  q.shape[0]), eps)
        return _pack_dqn(q, action)

    return jax.jit(act)


def build_packed_roll_act(apply_fn: Callable) -> Callable:
    """Frame-packed variant of ``build_packed_act`` for the shared
    inference server (agents/inference.py): the client ships only the
    NEWEST frame per env and the device rolls its resident history stack
    before acting, fused into the same dispatch —
    ``act(params, stack[B,C,H,W], new[B,H,W], base_key, tick, eps) ->
    (stack', packed[3,B])``.

    Over a tunnelled chip this cuts the per-tick upload by the stack
    factor C (451 KB -> 113 KB for the production 16-env Nature-CNN
    shape) — the difference between the obs plane fitting next to the
    replay-ingest stream or fighting it for the link.  The stack is
    DONATED (stack' has its exact shape/dtype, so XLA rolls in place).
    The client only elects this path when the roll property held on the
    host (``obs[:, :-1] == prev[:, 1:]`` — any env reset falls back to a
    full upload that also reseeds the device stack), so the device
    reconstruction is bit-exact with what the env emitted."""

    def act(params, stack, new, base_key, tick, eps):
        stack = jnp.concatenate([stack[:, 1:], new[:, None]], axis=1)
        q = apply_fn(params, stack)
        action = _rowwise_eps_greedy(q, tick_keys(base_key, tick,
                                                  q.shape[0]), eps)
        return stack, _pack_dqn(q, action)

    return jax.jit(act, donate_argnums=(1,))


def build_packed_act_rowkeys(apply_fn: Callable) -> Callable:
    """Server-side variant of ``build_packed_act`` taking precomputed
    per-row keys: the inference batcher concatenates rows from several
    actors into one wide forward, so each row's key comes from ITS
    actor's (base_key, tick, row) fold — identical streams to the local
    paths regardless of batch composition."""

    def act(params, obs, row_keys, eps):
        q = apply_fn(params, obs)
        return _pack_dqn(q, _rowwise_eps_greedy(q, row_keys, eps))

    return jax.jit(act)


def build_greedy_act(apply_fn: Callable) -> Callable:
    """Pure-greedy variant for evaluator/tester (reference evaluators.py:56-86
    runs eps=0 episodes)."""

    def act(params, obs):
        q = apply_fn(params, obs)
        return jnp.argmax(q, axis=-1), jnp.max(q, axis=-1)

    return jax.jit(act)


def build_recurrent_epsilon_greedy_act(apply_fn: Callable) -> Callable:
    """eps-greedy over a recurrent Q-network (models/drqn.py contract
    ``apply(params, obs, carry) -> (q, carry')``).  Returns a jitted
    ``act(params, obs[B,...], carry, key, eps) -> (action[B], carry')`` —
    the caller owns the carry and resets env rows at episode ends."""

    def act(params, obs, carry, key, eps):
        q, carry = apply_fn(params, obs, carry)
        batch, num_actions = q.shape
        greedy = jnp.argmax(q, axis=-1)
        key_explore, key_choice = jax.random.split(key)
        random_a = jax.random.randint(key_choice, (batch,), 0, num_actions)
        explore = jax.random.uniform(key_explore, (batch,)) < eps
        return jnp.where(explore, random_a, greedy), carry

    return jax.jit(act)


def build_recurrent_packed_act(apply_fn: Callable, zero_carry) -> Callable:
    """Fused recurrent act for the pipelined loop: the carry stays
    DEVICE-RESIDENT across ticks (no per-tick host round-trip of the LSTM
    state into the forward), and episode resets arrive as a per-row
    boolean mask folded in on-device — row j's carry is replaced with the
    model's zero carry before acting when ``reset_mask[j]`` is set, which
    is exactly the host-side row reset the serial loop performed between
    ticks.

    ``zero_carry`` is the model's ``zero_carry(1)`` pytree (leading dim 1
    broadcasts over rows).  Returns a jitted ``act(params, obs, carry,
    reset_mask[B], base_key, tick, eps[B]) -> (action[B] int32, carry')``.
    The caller owns the device carry and keeps a host copy for segment
    storage (agents/recurrent_actor.py).

    The carry argument is DONATED: carry' has exactly carry's shapes, so
    XLA updates it in place — for the transformer family, whose carry IS
    the rolling (B, window, *obs) context buffer, this is the ISSUE 4
    "donate the obs buffer" optimisation (no per-tick reallocation of
    the window).  Callers must treat the passed-in carry as consumed,
    which the engine's swap-on-submit discipline guarantees."""
    zero = jax.tree_util.tree_map(jnp.asarray, zero_carry)

    def act(params, obs, carry, reset_mask, base_key, tick, eps):
        def reset_rows(c, z):
            mask = reset_mask.reshape(reset_mask.shape[0],
                                      *([1] * (c.ndim - 1)))
            return jnp.where(mask, z.astype(c.dtype), c)

        carry = jax.tree_util.tree_map(reset_rows, carry, zero)
        q, carry = apply_fn(params, obs, carry)
        action = _rowwise_eps_greedy(
            q, tick_keys(base_key, tick, q.shape[0]), eps)
        return action.astype(jnp.int32), carry

    return jax.jit(act, donate_argnums=(2,))


def build_recurrent_greedy_act(apply_fn: Callable) -> Callable:
    """Greedy recurrent variant for evaluator/tester."""

    def act(params, obs, carry):
        q, carry = apply_fn(params, obs, carry)
        return jnp.argmax(q, axis=-1), carry

    return jax.jit(act)


def build_ddpg_act(actor_apply_fn: Callable) -> Callable:
    """Deterministic policy forward ``act(params, obs[B,...]) -> action[B,d]``
    in [-1,1]; exploration noise (OU) is added host-side by the actor
    process, as in the reference (reference ddpg_mlp_model.py:74-78 returns
    action + noise; here noise stays outside the jitted function so the OU
    state lives with the process)."""

    def act(params, obs):
        return actor_apply_fn(params, obs)

    return jax.jit(act)
