"""Action-selection as pure jitted functions.

The reference folds action selection into the torch modules
(``get_action``: reference core/models/dqn_cnn_model.py:58-78,
ddpg_mlp_model.py:74-78).  TPU-first, these are standalone functions of
``(params, obs, key, ...)`` with explicit randomness, jit-compiled once and
reused by actors / evaluators / testers; they are batch-shaped so one call
can serve a whole vector of envs (the batched-inference answer to the
reference's latency-bound batch-1 actor forward, SURVEY.md §7 "hard
parts").
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp


def apex_epsilon(process_ind: int, num_actors: int,
                 eps: float = 0.4, eps_alpha: float = 7.0) -> float:
    """Ape-X per-actor exploration schedule
    ``eps ** (1 + i/(N-1) * alpha)`` (reference dqn_actor.py:33-36, with the
    reference's 1-based indexing of actors and its single-actor debug value).
    """
    if num_actors <= 1:
        return 0.1  # reference dqn_actor.py:33-34 debug branch
    frac = process_ind / (num_actors - 1)
    return float(eps ** (1.0 + frac * eps_alpha))


def apex_epsilons(process_ind: int, num_actors: int, num_envs: int,
                  eps: float = 0.4, eps_alpha: float = 7.0):
    """Per-env epsilon vector for a vectorized actor: env j of actor i
    takes fleet slot i*num_envs + j of num_actors*num_envs, so exploration
    diversity spans the whole fleet exactly as the reference's per-actor
    schedule spans its actors (reference dqn_actor.py:33-36)."""
    import numpy as np

    total = num_actors * num_envs
    return np.asarray(
        [apex_epsilon(process_ind * num_envs + j, total, eps, eps_alpha)
         for j in range(num_envs)], dtype=np.float32)


def build_epsilon_greedy_act(apply_fn: Callable) -> Callable:
    """eps-greedy over a Q-network.

    Returns a jitted ``act(params, obs[B,...], key, eps) ->
    (action[B], q_sel[B], q_max[B])``; ``eps`` may be a scalar or a (B,)
    per-sample vector (the vectorized-actor fleet schedule).  q_sel/q_max
    feed PER initial priorities, mirroring the tuple the reference returns
    when PER is on (reference dqn_cnn_model.py:65-78) — here they are
    always returned (cost-free under jit).
    """

    def act(params, obs, key, eps):
        q = apply_fn(params, obs)                        # (B, A)
        batch, num_actions = q.shape
        greedy = jnp.argmax(q, axis=-1)
        key_explore, key_choice = jax.random.split(key)
        random_a = jax.random.randint(key_choice, (batch,), 0, num_actions)
        explore = jax.random.uniform(key_explore, (batch,)) < eps
        action = jnp.where(explore, random_a, greedy)
        q_sel = jnp.take_along_axis(q, action[:, None], axis=-1)[:, 0]
        return action, q_sel, jnp.max(q, axis=-1)

    return jax.jit(act)


def build_greedy_act(apply_fn: Callable) -> Callable:
    """Pure-greedy variant for evaluator/tester (reference evaluators.py:56-86
    runs eps=0 episodes)."""

    def act(params, obs):
        q = apply_fn(params, obs)
        return jnp.argmax(q, axis=-1), jnp.max(q, axis=-1)

    return jax.jit(act)


def build_recurrent_epsilon_greedy_act(apply_fn: Callable) -> Callable:
    """eps-greedy over a recurrent Q-network (models/drqn.py contract
    ``apply(params, obs, carry) -> (q, carry')``).  Returns a jitted
    ``act(params, obs[B,...], carry, key, eps) -> (action[B], carry')`` —
    the caller owns the carry and resets env rows at episode ends."""

    def act(params, obs, carry, key, eps):
        q, carry = apply_fn(params, obs, carry)
        batch, num_actions = q.shape
        greedy = jnp.argmax(q, axis=-1)
        key_explore, key_choice = jax.random.split(key)
        random_a = jax.random.randint(key_choice, (batch,), 0, num_actions)
        explore = jax.random.uniform(key_explore, (batch,)) < eps
        return jnp.where(explore, random_a, greedy), carry

    return jax.jit(act)


def build_recurrent_greedy_act(apply_fn: Callable) -> Callable:
    """Greedy recurrent variant for evaluator/tester."""

    def act(params, obs, carry):
        q, carry = apply_fn(params, obs, carry)
        return jnp.argmax(q, axis=-1), carry

    return jax.jit(act)


def build_ddpg_act(actor_apply_fn: Callable) -> Callable:
    """Deterministic policy forward ``act(params, obs[B,...]) -> action[B,d]``
    in [-1,1]; exploration noise (OU) is added host-side by the actor
    process, as in the reference (reference ddpg_mlp_model.py:74-78 returns
    action + noise; here noise stays outside the jitted function so the OU
    state lives with the process)."""

    def act(params, obs):
        return actor_apply_fn(params, obs)

    return jax.jit(act)
