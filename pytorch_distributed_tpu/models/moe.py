"""Mixture-of-Experts DTQN: expert-parallel FFN over the mesh ``ep`` axis.

No reference equivalent (the reference is a single-GPU dense-model repo;
SURVEY.md §2 lists expert parallelism as NOT present there) — this is the
TPU-native capability that makes the mesh's ``ep`` axis real: the DTQN
transformer's FFN becomes a top-k-routed mixture of experts whose expert
kernels shard over ``ep`` (parallel/expert_parallel.py).

Design, the GShard/Switch dataflow expressed the XLA-SPMD way — einsum
dispatch/combine with static capacity, sharding annotations only, no
manual collectives:

- router: one Dense(E) per MoE block; softmax over experts; top-k choices
  per token, gates renormalised over the chosen k;
- capacity: each expert accepts at most C = ceil(capacity_factor * k *
  T / E) tokens **per batch row** (grouping by row keeps the slot cumsum
  local to the dp shard — no cross-device prefix sums on the hot path);
  overflow tokens are dropped for that expert (their residual branch
  simply contributes nothing, the standard Switch behaviour);
- dispatch/combine: one-hot (B, T, E, C) tensors turn routing into two
  einsums around the expert-batched FFN matmuls (E-leading kernels).
  Under jit with the batch dp-sharded and the expert kernels ep-sharded,
  XLA runs each device's expert slice locally and closes the combine
  contraction over E with one psum over ep — expert parallelism as a
  compiler-inserted collective, the same way tensor_parallel.py gets its
  Megatron psum;
- aux loss: the Switch load-balancing term E * sum_e f_e * P_e (f_e =
  fraction of tokens whose top-1 choice is e, P_e = mean router prob),
  sown into the ``moe_losses`` collection; the DTQN train step adds it
  with weight ``moe_aux_weight`` (ops/sequence_losses.py aux_weight).

The model class mirrors models/dtqn.py `DtqnMlpModel` exactly on the
acting/learner contract (window carry, leading-aligned positions,
window_q) so the whole r2d2 pipeline is reused unchanged.
"""

from __future__ import annotations

from typing import Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from pytorch_distributed_tpu.models.dtqn import (
    DtqnMlpModel, attention_half, embed_tokens, q_head,
)

AUX_COLLECTION = "moe_losses"


def _top_k_dispatch(probs: jnp.ndarray, top_k: int, capacity: int
                    ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Routing tensors from per-token expert probabilities.

    probs: (B, T, E) softmax router output.  Returns

    - dispatch (B, T, E, C) in {0,1}: token t of row b occupies slot c of
      expert e;
    - combine  (B, T, E, C) float: dispatch scaled by the token's
      renormalised gate for that expert;
    - f_top1   (B, T, E) in {0,1}: rank-0 assignment mask (for the aux
      loss), before any capacity drop.

    Slots are assigned in (rank, time) priority order: all rank-0 choices
    claim capacity before any rank-1 choice, earlier tokens before later
    ones — the deterministic Switch/GShard policy.
    """
    B, T, E = probs.shape
    top_p, top_i = jax.lax.top_k(probs, top_k)            # (B, T, k)
    # renormalise gates over the chosen k
    top_p = top_p / jnp.maximum(jnp.sum(top_p, -1, keepdims=True), 1e-9)

    dispatch = jnp.zeros((B, T, E, capacity), probs.dtype)
    combine = jnp.zeros((B, T, E, capacity), probs.dtype)
    count = jnp.zeros((B, E), probs.dtype)  # slots already claimed
    for r in range(top_k):  # static unroll; k is 1 or 2
        mask_r = jax.nn.one_hot(top_i[..., r], E, dtype=probs.dtype)
        if r == 0:
            f_top1 = mask_r
        # slot index for each token at this rank: previously claimed slots
        # plus this rank's exclusive running count along time
        pos = count[:, None, :] + jnp.cumsum(mask_r, axis=1) - mask_r
        keep = mask_r * (pos < capacity)
        slot_hot = jax.nn.one_hot(pos.astype(jnp.int32), capacity,
                                  dtype=probs.dtype)      # (B, T, E, C)
        dispatch = dispatch + keep[..., None] * slot_hot
        combine = combine + (keep * top_p[..., r:r + 1])[..., None] \
            * slot_hot
        count = count + jnp.sum(mask_r, axis=1)
    return dispatch, combine, f_top1


class MoeFfn(nn.Module):
    """Top-k routed expert FFN (dim -> hidden -> dim), expert-batched
    kernels with a leading E dim so ``ep`` sharding is one PartitionSpec.
    Returns (y, aux) — aux is the Switch load-balancing loss, also sown
    into ``moe_losses``."""

    dim: int
    num_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    hidden_mult: int = 4
    # Static token count the capacity is derived from.  When set (the DTQN
    # models pass their static ``window``) routing is length-invariant:
    # the same params route a 4-token prefix and the padded acting window
    # identically.  Deriving it from the runtime x.shape[1] made capacity
    # — and hence overflow-drop behaviour — depend on input length
    # (round-2 advisor finding).
    capacity_tokens: Optional[int] = None

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
        B, T, D = x.shape
        E, k = self.num_experts, min(self.top_k, self.num_experts)
        H = self.hidden_mult * self.dim
        cap_T = self.capacity_tokens if self.capacity_tokens else T
        capacity = max(int(-(-self.capacity_factor * k * cap_T // E)), 1)

        logits = nn.Dense(E, name="router")(x)            # (B, T, E)
        probs = jax.nn.softmax(logits, axis=-1)
        dispatch, combine, f_top1 = _top_k_dispatch(probs, k, capacity)

        w1 = self.param("w1", nn.initializers.lecun_normal(),
                        (E, D, H))
        b1 = self.param("b1", nn.initializers.zeros, (E, H))
        w2 = self.param("w2", nn.initializers.lecun_normal(),
                        (E, H, D))
        b2 = self.param("b2", nn.initializers.zeros, (E, D))

        # (B, E, C, D): each expert's token slab for this batch shard
        xin = jnp.einsum("btec,btd->becd", dispatch, x)
        h = nn.gelu(jnp.einsum("becd,edh->bech", xin, w1)
                    + b1[None, :, None, :])
        out = jnp.einsum("bech,ehd->becd", h, w2) + b2[None, :, None, :]
        # combine contracts over (e, c): with experts ep-sharded XLA
        # closes this with the psum over ep
        y = jnp.einsum("becd,btec->btd", out, combine)

        # Switch aux: E * sum_e (token fraction routed to e) * (mean prob)
        f = jnp.mean(f_top1, axis=(0, 1))                 # (E,)
        p = jnp.mean(probs, axis=(0, 1))                  # (E,)
        aux = jnp.asarray(E, x.dtype) * jnp.sum(f * p)
        self.sow(AUX_COLLECTION, "aux", aux)
        return y, aux


class _MoeBlock(nn.Module):
    """Pre-LN transformer block: causal attention + MoE FFN.  The
    attention half IS models/dtqn.py's (shared ``attention_half`` — same
    padding semantics, same injected-attn hook for sequence
    parallelism)."""

    dim: int
    heads: int
    num_experts: int
    top_k: int
    capacity_factor: float
    attn: Optional[object] = None
    capacity_tokens: Optional[int] = None

    @nn.compact
    def __call__(self, x: jnp.ndarray,
                 pad_mask: Optional[jnp.ndarray] = None) -> jnp.ndarray:
        x = attention_half(self, x, pad_mask)
        y = nn.LayerNorm()(x)
        ffn_out, _ = MoeFfn(self.dim, self.num_experts, self.top_k,
                            self.capacity_factor,
                            capacity_tokens=self.capacity_tokens,
                            name="moe")(y)
        return x + ffn_out


class DtqnMoeModel(DtqnMlpModel):
    """DTQN with every block's FFN replaced by a routed expert mixture.

    Same acting/learner contract as DtqnMlpModel (it inherits the window
    carry, window_q and act paths); only ``_encode`` changes.  The aux
    load-balancing losses are sown — the learner applies with
    ``mutable=[AUX_COLLECTION]`` and feeds their MEAN over blocks to the
    train step's ``aux_weight`` term (factory.py wires this;
    ``window_q_with_aux`` below).
    """

    num_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25

    @nn.compact
    def _encode(self, win: jnp.ndarray,
                pad_mask: Optional[jnp.ndarray]) -> jnp.ndarray:
        x = embed_tokens(self, win)
        for _ in range(self.depth):
            x = _MoeBlock(self.dim, self.heads, self.num_experts,
                          self.top_k, self.capacity_factor,
                          self.attn,
                          capacity_tokens=self.window)(x, pad_mask)
        return q_head(self, x)


def window_q_with_aux(model: DtqnMoeModel):
    """(params, obs_seq) -> (q, aux_mean): the learner-side apply that
    surfaces the sown load-balancing losses, averaged over the MoE blocks
    (depth-invariant, so ``moe_aux_weight`` needs no retuning when
    ``tf_depth`` changes).  Matches the tuple-returning window_apply
    contract of ops/sequence_losses.build_dtqn_train_step.

    Only the ``params`` collection is passed through: a variables dict
    that (incorrectly) still carries init-time sown ``moe_losses`` leaves
    must not seed the sow reduce — stored aux values would become free
    parameters with a constant positive gradient under aux_weight, and
    Adam would drive them unboundedly negative (factory.init_params
    strips them at the source; this guards direct callers).
    """

    def apply(params, obs_seq):
        variables = {"params": params["params"]} if "params" in params \
            else params
        q, aux_vars = model.apply(variables, obs_seq,
                                  method=model.window_q,
                                  mutable=[AUX_COLLECTION])
        sown = jax.tree_util.tree_leaves(aux_vars)
        aux = sum(sown) / max(len(sown), 1)
        return q, aux

    return apply
