"""Transformer Q-network (DTQN) over observation windows.

The attention-based sibling of models/drqn.py (Esslinger et al. 2022,
"Deep Transformer Q-Networks for Partially Observable RL"): instead of an
LSTM carry, the Q-function attends causally over a window of recent
observations.  This is the model family that exercises the long-context
machinery — for windows longer than one device can hold, the attention
call swaps to sequence-parallel ring attention over the mesh's sp axis
(``attn`` constructor knob; ops/ring_attention.py).

Two call paths, mirroring the DRQN contract so the whole r2d2 pipeline
(recurrent actor, policies, evaluator, sequence learner) is shared:

- ``window_q(obs_seq)``: one causal pass over a (B, T, *S) window ->
  (B, T, A) — the learner's path, one transformer call per segment;
- ``__call__(obs, carry)``: acting path; the carry is a rolling
  (window, filled) pair, the newest observation is pushed in, and the
  last position's Q comes out.  Unfilled slots are masked out of
  attention.  ``state_for_segment`` returns a 1-dim zero placeholder —
  a transformer needs no stored recurrent state; the segment window
  itself is the context (burn-in positions act as attention prefix).
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from pytorch_distributed_tpu.ops.ring_attention import full_attention

Carry = Tuple[jnp.ndarray, jnp.ndarray]  # (window (B,W,*S) f32, filled (B,))


def embed_tokens(m: nn.Module, win: jnp.ndarray) -> jnp.ndarray:
    """Shared DTQN torso (norm -> flatten -> Dense embed -> learned
    positions), used by the dense and MoE families' compact ``_encode``
    so the exact acting/training position contract cannot drift.
    (The pipeline family re-expresses the same two lines setup-style on
    named submodules — models/dtqn_pipeline.py ``embed``.)  Must be
    called first inside the caller's compact method: submodules register
    under the caller, keeping historical auto-names."""
    B, T = win.shape[0], win.shape[1]
    x = win.astype(jnp.float32) / m.norm_val
    x = x.reshape(B, T, -1)
    x = nn.Dense(m.dim)(x)
    return x + m.param("pos_embed", nn.initializers.normal(0.02),
                       (m.window, m.dim))[:T]


def q_head(m: nn.Module, x: jnp.ndarray) -> jnp.ndarray:
    """Shared DTQN head: final LayerNorm + ZERO-INIT Q projection — Q
    starts exactly at 0, so the max-bias of early bootstrapping has
    nothing optimistic to amplify; without this the online loop can
    drift onto a flat inflated plateau on sparse-reward envs (tiny TD
    loss, useless greedy policy)."""
    x = nn.LayerNorm()(x)
    return nn.Dense(m.action_space, kernel_init=nn.initializers.zeros)(x)


def attention_half(block: nn.Module, x: jnp.ndarray,
                   pad_mask: Optional[jnp.ndarray]) -> jnp.ndarray:
    """The attention residual of a pre-LN block — shared by the dense
    `_Block` here and the MoE block (models/moe.py) so the two families
    cannot drift.  ``block`` provides dim/heads/attn and the module scope
    (submodules register under the caller, keeping the historical
    Dense_0/Dense_1 auto-names that parallel/tensor_parallel.py's
    path rules rely on).  Must be called first inside the block's compact
    ``__call__``."""
    B, T, _ = x.shape
    hdim = block.dim // block.heads
    y = nn.LayerNorm()(x)
    qkv = nn.Dense(3 * block.dim)(y).reshape(B, T, 3, block.heads, hdim)
    q, k, v = (qkv[:, :, i].transpose(0, 2, 1, 3) for i in range(3))
    if pad_mask is not None:
        # acting path: unfilled window slots masked out; the injected
        # attn hook (ring) has no padding concept, but acting windows
        # always fit one device, so dense attention is the right call
        o = full_attention(q, k, v, causal=True, key_pad_mask=pad_mask)
    else:
        o = (block.attn or full_attention)(q, k, v, causal=True)
    o = o.transpose(0, 2, 1, 3).reshape(B, T, block.dim)
    return x + nn.Dense(block.dim)(o)


class _Block(nn.Module):
    """Pre-LN transformer block with causal (+padding-masked) attention."""

    dim: int
    heads: int
    attn: Optional[Callable] = None  # (q,k,v,causal)->o; None = full

    @nn.compact
    def __call__(self, x: jnp.ndarray,
                 pad_mask: Optional[jnp.ndarray] = None) -> jnp.ndarray:
        x = attention_half(self, x, pad_mask)
        y = nn.LayerNorm()(x)
        y = nn.Dense(4 * self.dim)(y)
        x = x + nn.Dense(self.dim)(nn.gelu(y))
        return x


class DtqnMlpModel(nn.Module):
    """Dense-embed torso -> causal transformer -> Q head (low-dim obs)."""

    action_space: int
    state_shape: Tuple[int, ...] = ()   # set by the factory from the probe
    window: int = 32          # acting-path context length
    dim: int = 128
    heads: int = 4
    depth: int = 2
    norm_val: float = 1.0
    attn: Optional[Callable] = None  # learner may inject ring attention

    @property
    def act_window(self) -> int:
        """Acting context length: one less than the positional table.
        Training segments span T+1 positions but position T is
        bootstrap-only (never TD-trained), so acting must keep the newest
        observation within the trained positions [0, T)."""
        return self.window - 1

    def zero_carry(self, batch: int) -> Carry:
        return (jnp.zeros((batch, self.act_window, *self.state_shape),
                          jnp.float32),
                jnp.zeros((batch,), jnp.float32))

    def state_for_segment(self, carry: Carry, j: int):
        """Stored-state placeholder for SegmentBuilder: transformers carry
        no recurrent state worth replaying from — the segment window
        itself is the context."""
        return (np.zeros(1, np.float32), np.zeros(1, np.float32))

    @nn.compact
    def _encode(self, win: jnp.ndarray,
                pad_mask: Optional[jnp.ndarray]) -> jnp.ndarray:
        x = embed_tokens(self, win)
        for _ in range(self.depth):
            x = _Block(self.dim, self.heads, self.attn)(x, pad_mask)
        return q_head(self, x)  # (B, T, A)

    def __call__(self, obs: jnp.ndarray, carry: Optional[Carry] = None
                 ) -> Tuple[jnp.ndarray, Carry]:
        if carry is None:
            carry = (jnp.zeros(
                (obs.shape[0], self.act_window, *obs.shape[1:]),
                jnp.float32),
                jnp.zeros((obs.shape[0],), jnp.float32))
        window, filled = carry
        # LEADING-aligned window: data occupies positions [0, filled) so
        # acting sees exactly the positional embeddings training windows
        # are trained on (training segments start at position 0); once
        # full, the oldest obs rolls off and positions stay [0, W).
        obs_f = obs.astype(jnp.float32)
        shifted = jnp.concatenate([window[:, 1:], obs_f[:, None]], axis=1)
        placed = jax.vmap(
            lambda w, f, o: jax.lax.dynamic_update_slice_in_dim(
                w, o[None], f, 0)
        )(window, filled.astype(jnp.int32), obs_f)
        full = filled >= float(self.act_window)
        window = jnp.where(
            full.reshape(-1, *([1] * (window.ndim - 1))), shifted, placed)
        filled = jnp.minimum(filled + 1.0, float(self.act_window))
        slot = jnp.arange(self.act_window)[None, :]
        pad_mask = slot < filled[:, None]
        q_seq = self._encode(window, pad_mask)
        # the newest observation sits at position filled-1
        last = (filled - 1.0).astype(jnp.int32)
        q = jnp.take_along_axis(
            q_seq, last[:, None, None].repeat(q_seq.shape[-1], axis=-1),
            axis=1)[:, 0]
        return q, (window, filled)

    def window_q(self, obs_seq: jnp.ndarray) -> jnp.ndarray:
        """Learner path: causal Q over a fully-valid (B, T, *S) window."""
        return self._encode(obs_seq, None)


def _with_sp_attention(model: DtqnMlpModel, mesh, attn_fn) -> DtqnMlpModel:
    """Clone the model with its attention swapped for a sequence-parallel
    strategy over ``mesh``'s sp axis — same params, same math (up to fp
    order); the learner uses this when windows outgrow one device
    (parallel_params.sp_size > 1)."""
    import dataclasses
    import functools

    return dataclasses.replace(
        model, attn=functools.partial(attn_fn, mesh=mesh,
                                      axis="sp", batch_axis="dp"))


def with_ring_attention(model: DtqnMlpModel, mesh) -> DtqnMlpModel:
    """Ring K/V rotation (ops/ring_attention.py) — works for any head
    count."""
    from pytorch_distributed_tpu.ops.ring_attention import ring_attention

    return _with_sp_attention(model, mesh, ring_attention)


def with_ulysses_attention(model: DtqnMlpModel, mesh) -> DtqnMlpModel:
    """Ulysses head/time all-to-all (ops/ulysses_attention.py) — needs
    heads divisible by the sp axis size (parallel_params.sp_attention)."""
    from pytorch_distributed_tpu.ops.ulysses_attention import (
        ulysses_attention,
    )

    return _with_sp_attention(model, mesh, ulysses_attention)
