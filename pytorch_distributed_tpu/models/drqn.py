"""Recurrent Q-networks (R2D2 family).

No reference equivalent — the reference's only sequence notion is the
n-step window and 4-frame stack (SURVEY.md §5 "long-context: store
contiguous episode segments, not only single transitions"); this is the
model side of that extension: an LSTM core over the torso so the Q-function
conditions on history far beyond the frame stack (Kapturowski et al. 2019,
"Recurrent Experience Replay in Distributed RL").

Interface contract shared by both variants:

- ``apply(params, obs, carry)`` -> ``(q, carry')`` — one recurrent step on
  a batch of observations; ``carry`` is the flax LSTM ``(c, h)`` pair.
- ``apply(params, obs)`` (carry omitted) starts from the zero state, so
  the factory's ``init_params``/``example_obs`` probe works unchanged.
- ``zero_carry(batch)`` builds the start-of-episode state; the same zeros
  are what segment builders record at episode starts.

The time dimension deliberately lives OUTSIDE the module:
``ops/sequence_losses.unroll`` scans the single-step apply over a
time-major sequence — keeping the module shape-agnostic and the scan in
one place XLA can optimise.
"""

from __future__ import annotations

from typing import Optional, Tuple

import flax.linen as nn
import jax.numpy as jnp

Carry = Tuple[jnp.ndarray, jnp.ndarray]  # flax LSTM (c, h)


class DrqnMlpModel(nn.Module):
    """MLP torso -> LSTM -> Q head, the low-dim recurrent counterpart of
    DqnMlpModel (reference core/models/dqn_mlp_model.py's 3x256 ReLU MLP,
    with the middle layer replaced by the recurrent core)."""

    action_space: int
    hidden_dim: int = 256
    lstm_dim: int = 256
    norm_val: float = 1.0

    def zero_carry(self, batch: int) -> Carry:
        z = jnp.zeros((batch, self.lstm_dim), dtype=jnp.float32)
        return (z, z)

    @nn.compact
    def __call__(self, obs: jnp.ndarray, carry: Optional[Carry] = None
                 ) -> Tuple[jnp.ndarray, Carry]:
        x = obs.astype(jnp.float32) / self.norm_val
        x = x.reshape(x.shape[0], -1)
        x = nn.relu(nn.Dense(self.hidden_dim)(x))
        if carry is None:
            carry = self.zero_carry(x.shape[0])
        carry, x = nn.OptimizedLSTMCell(self.lstm_dim)(carry, x)
        q = nn.Dense(self.action_space)(x)
        return q, carry


class DrqnCnnModel(nn.Module):
    """Nature-CNN torso -> LSTM -> Q head: the R2D2 pixel architecture
    (Nature-DQN convs as in reference core/models/dqn_cnn_model.py:16-30,
    with the first FC layer's output feeding the LSTM)."""

    action_space: int
    lstm_dim: int = 512
    norm_val: float = 255.0
    compute_dtype: jnp.dtype = jnp.bfloat16

    def zero_carry(self, batch: int) -> Carry:
        z = jnp.zeros((batch, self.lstm_dim), dtype=jnp.float32)
        return (z, z)

    @nn.compact
    def __call__(self, obs: jnp.ndarray, carry: Optional[Carry] = None
                 ) -> Tuple[jnp.ndarray, Carry]:
        # NCHW uint8 frames -> NHWC for XLA's TPU conv layouts
        x = obs.astype(self.compute_dtype) / jnp.asarray(
            self.norm_val, self.compute_dtype)
        x = jnp.transpose(x, (0, 2, 3, 1))
        conv = lambda f, k, s: nn.Conv(
            f, (k, k), strides=(s, s), padding="VALID",
            dtype=self.compute_dtype)
        x = nn.relu(conv(32, 8, 4)(x))
        x = nn.relu(conv(64, 4, 2)(x))
        x = nn.relu(conv(64, 3, 1)(x))
        x = x.reshape(x.shape[0], -1)
        x = nn.relu(nn.Dense(self.lstm_dim, dtype=self.compute_dtype)(x))
        x = x.astype(jnp.float32)  # LSTM state/gates stay fp32
        if carry is None:
            carry = self.zero_carry(x.shape[0])
        carry, x = nn.OptimizedLSTMCell(self.lstm_dim)(carry, x)
        q = nn.Dense(self.action_space)(x)
        return q, carry
