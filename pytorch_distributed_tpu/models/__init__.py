from pytorch_distributed_tpu.models.dqn_cnn import DqnCnnModel
from pytorch_distributed_tpu.models.dqn_cnn_wide import DqnCnnWideModel
from pytorch_distributed_tpu.models.dqn_mlp import DqnMlpModel
from pytorch_distributed_tpu.models.ddpg_mlp import DdpgMlpModel
from pytorch_distributed_tpu.models.policies import (
    build_epsilon_greedy_act, build_ddpg_act, apex_epsilon,
    build_packed_act, build_recurrent_packed_act,
)

__all__ = [
    "DqnCnnModel", "DqnCnnWideModel", "DqnMlpModel", "DdpgMlpModel",
    "build_epsilon_greedy_act", "build_ddpg_act", "apex_epsilon",
    "build_packed_act", "build_recurrent_packed_act",
]
