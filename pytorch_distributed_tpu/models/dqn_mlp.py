"""Low-dim MLP Q-network.

Re-design of reference core/models/dqn_mlp_model.py:18-26 (3 hidden ReLU
layers of ``hidden_dim``).  Unlike the reference — where this model exists
but is left unregistered in the factory (reference utils/factory.py:42-43) —
it is registered here and carries the smoke-test configs.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp
from flax.linen.initializers import orthogonal, zeros_init


class DqnMlpModel(nn.Module):
    action_space: int
    hidden_dim: int = 256
    norm_val: float = 1.0
    compute_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        x = x.astype(self.compute_dtype) / jnp.asarray(
            self.norm_val, dtype=self.compute_dtype)
        x = x.reshape((x.shape[0], -1))
        for _ in range(3):
            x = nn.Dense(self.hidden_dim, dtype=self.compute_dtype,
                         kernel_init=orthogonal(jnp.sqrt(2.0)),
                         bias_init=zeros_init())(x)
            x = nn.relu(x)
        q = nn.Dense(self.action_space, dtype=self.compute_dtype,
                     kernel_init=orthogonal(1.0), bias_init=zeros_init())(x)
        return q.astype(jnp.float32)
