"""Pipeline-ready DTQN: the transformer stack as STACKED raw block params.

No reference equivalent (the reference is single-GPU; SURVEY.md §2 lists
pipeline parallelism as NOT present there).  This is the model family
behind the mesh ``pp`` axis (parallel/pipeline.py): every transformer
block's parameters live in one pytree of arrays with a leading layer
axis ``(depth, ...)``, so

- single-device execution is a ``lax.scan`` over the layer axis (the
  "scan over layers" pattern XLA compiles to one block program), and
- pipeline execution shards that SAME leading axis over ``pp`` — each
  stage holds ``depth / pp`` contiguous blocks — with microbatches
  flowing stage-to-stage via ``ppermute`` (GPipe schedule, expressed as
  a shard_map; parallel/pipeline.py).

The block math (pre-LN causal attention + GELU FFN) is written ONCE as
the pure function ``block_forward`` on raw params and is used by both
paths, so the pipeline equivalence tests pin the scheduling machinery,
not a re-implementation of the math.  Embedding, final LN and the
zero-init Q head stay ordinary Flax submodules outside the pipelined
region (they are a few percent of the FLOPs; replicating their compute
is cheaper than two extra pipeline stages).

The acting/learner contract (window carry, leading-aligned positions,
``window_q``) is inherited from models/dtqn.py ``DtqnMlpModel``
unchanged — only ``_encode`` is overridden.
"""

from __future__ import annotations

from typing import Dict, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from pytorch_distributed_tpu.models.dtqn import DtqnMlpModel
from pytorch_distributed_tpu.ops.ring_attention import full_attention

BlockParams = Dict[str, jnp.ndarray]


def block_forward(p: BlockParams, x: jnp.ndarray, *, heads: int,
                  key_pad_mask: Optional[jnp.ndarray] = None
                  ) -> jnp.ndarray:
    """One pre-LN transformer block on raw params — the single source of
    the block math for both the sequential scan and the pipeline stages.

    ``p`` holds one layer's slice: ln1_{s,b}, qkv_{k,b}, proj_{k,b},
    ln2_{s,b}, ffn1_{k,b}, ffn2_{k,b}.
    """
    B, T, D = x.shape
    hdim = D // heads

    def ln(h, scale, bias):
        mu = jnp.mean(h, axis=-1, keepdims=True)
        var = jnp.var(h, axis=-1, keepdims=True)
        return (h - mu) * jax.lax.rsqrt(var + 1e-6) * scale + bias

    y = ln(x, p["ln1_s"], p["ln1_b"])
    qkv = (y @ p["qkv_k"] + p["qkv_b"]).reshape(B, T, 3, heads, hdim)
    q, k, v = (qkv[:, :, i].transpose(0, 2, 1, 3) for i in range(3))
    o = full_attention(q, k, v, causal=True, key_pad_mask=key_pad_mask)
    o = o.transpose(0, 2, 1, 3).reshape(B, T, D)
    x = x + o @ p["proj_k"] + p["proj_b"]
    y = ln(x, p["ln2_s"], p["ln2_b"])
    y = nn.gelu(y @ p["ffn1_k"] + p["ffn1_b"])
    return x + y @ p["ffn2_k"] + p["ffn2_b"]


def scan_blocks(stacked: BlockParams, x: jnp.ndarray, *, heads: int,
                key_pad_mask: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Sequential execution: lax.scan over the leading layer axis."""

    def body(h, layer):
        return block_forward(layer, h, heads=heads,
                             key_pad_mask=key_pad_mask), None

    out, _ = jax.lax.scan(body, x, stacked)
    return out


class _StackedBlockParams(nn.Module):
    """Parameter-only submodule holding the stacked block pytree — its
    leaves live under ``params/blocks/...`` so the pipeline sharding rule
    (parallel/pipeline.py) can key on the path."""

    dim: int
    depth: int

    @nn.compact
    def __call__(self) -> BlockParams:
        D, H, depth = self.dim, 4 * self.dim, self.depth
        lecun = nn.initializers.lecun_normal()

        # a vmapped lecun init keeps per-layer fan-in statistics despite
        # the leading layer axis
        def stacked_kernel(key, shape):
            return jax.vmap(lambda k: lecun(k, shape[1:]))(
                jax.random.split(key, shape[0]))

        mk = self.param
        return {
            "ln1_s": mk("ln1_s", nn.initializers.ones, (depth, D)),
            "ln1_b": mk("ln1_b", nn.initializers.zeros, (depth, D)),
            "qkv_k": mk("qkv_k", stacked_kernel, (depth, D, 3 * D)),
            "qkv_b": mk("qkv_b", nn.initializers.zeros, (depth, 3 * D)),
            "proj_k": mk("proj_k", stacked_kernel, (depth, D, D)),
            "proj_b": mk("proj_b", nn.initializers.zeros, (depth, D)),
            "ln2_s": mk("ln2_s", nn.initializers.ones, (depth, D)),
            "ln2_b": mk("ln2_b", nn.initializers.zeros, (depth, D)),
            "ffn1_k": mk("ffn1_k", stacked_kernel, (depth, D, H)),
            "ffn1_b": mk("ffn1_b", nn.initializers.zeros, (depth, H)),
            "ffn2_k": mk("ffn2_k", stacked_kernel, (depth, H, D)),
            "ffn2_b": mk("ffn2_b", nn.initializers.zeros, (depth, D)),
        }


class DtqnPipelineModel(DtqnMlpModel):
    """DTQN whose block stack is one stacked-param pytree (leading
    ``depth`` axis) under the param subtree ``blocks`` — shardable over
    the mesh ``pp`` axis by parallel/pipeline.py.  Same acting/learner
    contract as DtqnMlpModel.  The learner swaps ``window_q`` for the
    pipelined apply when ``pp_size > 1`` (factory.py);
    sequence-parallel attention injection (``attn``) is not supported on
    this family — pp and sp address the same too-big-for-one-chip
    problem along different dims.

    Setup-based (no compact method) so ``embed`` and ``head`` are
    independently callable via ``model.apply(..., method=...)`` — the
    pipeline op composes embed -> pipelined blocks -> head from outside
    the module (parallel/pipeline.py::pipelined_window_apply).
    """

    def setup(self) -> None:
        assert self.attn is None, (
            "DtqnPipelineModel does not take injected sp attention; use "
            "DtqnMlpModel for sequence parallelism")
        # setup-style: attribute names become the param-tree keys
        # (embed_in, blocks, head_ln, head_q)
        self.embed_in = nn.Dense(self.dim)
        self.pos_embed = self.param(
            "pos_embed", nn.initializers.normal(0.02),
            (self.window, self.dim))
        self.blocks = _StackedBlockParams(self.dim, self.depth)
        self.head_ln = nn.LayerNorm()
        # zero-init head: same bootstrapping rationale as models/dtqn.py
        self.head_q = nn.Dense(self.action_space,
                               kernel_init=nn.initializers.zeros)

    def _encode(self, win: jnp.ndarray,
                pad_mask: Optional[jnp.ndarray]) -> jnp.ndarray:
        x = self.embed(win)
        x = scan_blocks(self.blocks(), x, heads=self.heads,
                        key_pad_mask=pad_mask)
        return self.head(x)

    # ---- pieces the pipeline op re-composes ---------------------------

    def embed(self, win: jnp.ndarray) -> jnp.ndarray:
        B, T = win.shape[0], win.shape[1]
        x = win.astype(jnp.float32) / self.norm_val
        x = x.reshape(B, T, -1)
        return self.embed_in(x) + self.pos_embed[:T]

    def head(self, x: jnp.ndarray) -> jnp.ndarray:
        return self.head_q(self.head_ln(x))
