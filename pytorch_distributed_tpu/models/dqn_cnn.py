"""Nature-DQN convolutional Q-network as a Flax module.

Functional re-design of reference core/models/dqn_cnn_model.py:16-56 —
same architecture (conv 32x8x8/4, 64x4x4/2, 64x3x3/1, FC 512, linear head to
``action_space``) and the same /norm_val input normalisation
(reference :54-56), with two deliberate TPU-first changes:

- layout: inputs arrive as (B, C, H, W) frame stacks (the replay layout) and
  are transposed once to NHWC, the layout XLA tiles best onto the MXU;
- init: orthogonal initialisation is *applied* — the reference defines it
  but never calls it (reference dqn_cnn_model.py:33 commented out;
  SURVEY.md "known quirks").  Set ``ModelParams.orthogonal_init=False`` for
  reference-faithful default init.

The forward runs in ``compute_dtype`` (bfloat16 by default) with fp32
params, returning fp32 Q-values.
"""

from __future__ import annotations

from typing import Tuple

import flax.linen as nn
import jax.numpy as jnp
from flax.linen.initializers import orthogonal, zeros_init


class DqnCnnModel(nn.Module):
    action_space: int
    norm_val: float = 255.0
    orthogonal_init: bool = True
    compute_dtype: jnp.dtype = jnp.bfloat16
    # True = inputs arrive already channels-last (B, H, W, C) and the
    # transpose is skipped.  The learner's fused HBM path stores replay
    # rows NHWC (memory/device_replay.py channels_last) because the
    # per-update NCHW->NHWC copies were ~25% of device time in the XLA
    # profile (tools/mfu_probe.py, 2026-07-31); the param tree is
    # identical either way, so actors/evaluators keep publishing and
    # consuming the same weights with NCHW inputs.
    nhwc_input: bool = False

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        # x: (B, C, H, W) uint8/float -> NHWC compute in bf16
        x = x.astype(self.compute_dtype) / jnp.asarray(
            self.norm_val, dtype=self.compute_dtype)
        if not self.nhwc_input:
            x = jnp.transpose(x, (0, 2, 3, 1))
        kw = {}
        if self.orthogonal_init:
            # sqrt(2) gain for ReLU trunk, 1.0 for the linear head — the
            # gains the reference's dead init intended (dqn_cnn_model.py:39-52).
            kw = dict(kernel_init=orthogonal(jnp.sqrt(2.0)),
                      bias_init=zeros_init())
        x = nn.Conv(32, (8, 8), strides=(4, 4), padding="VALID",
                    dtype=self.compute_dtype, **kw)(x)
        x = nn.relu(x)
        x = nn.Conv(64, (4, 4), strides=(2, 2), padding="VALID",
                    dtype=self.compute_dtype, **kw)(x)
        x = nn.relu(x)
        x = nn.Conv(64, (3, 3), strides=(1, 1), padding="VALID",
                    dtype=self.compute_dtype, **kw)(x)
        x = nn.relu(x)
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(512, dtype=self.compute_dtype, **kw)(x)
        x = nn.relu(x)
        head_kw = dict(kernel_init=orthogonal(1.0), bias_init=zeros_init()) \
            if self.orthogonal_init else {}
        q = nn.Dense(self.action_space, dtype=self.compute_dtype, **head_kw)(x)
        return q.astype(jnp.float32)

    @staticmethod
    def example_input(batch: int = 1,
                      state_shape: Tuple[int, ...] = (4, 84, 84)) -> jnp.ndarray:
        return jnp.zeros((batch, *state_shape), dtype=jnp.uint8)
