"""DDPG actor-critic as one Flax module.

Re-design of reference core/models/ddpg_mlp_model.py: a single module
holding both networks —

- actor: state -> 300 tanh -> 200 tanh -> tanh action in [-1,1]
  (reference :16-23);
- critic: state -> 400 tanh, concat(action) -> 300 tanh -> scalar Q
  (reference :26-35);
- init: fan-in uniform hidden layers with uniform(±3e-3) output layers,
  the init the reference actually applies (reference :38-56).

Exposed as ``forward_actor`` / ``forward_critic`` methods so the learner can
differentiate each path separately (the reference couples them through one
optimizer — see AgentParams.ddpg_coupled_update).  Actions are normalised to
[-1,1]; envs rescale via ContinuousSpace.denormalize.
"""

from __future__ import annotations

from typing import Tuple

import flax.linen as nn
import jax.numpy as jnp
from flax.linen.initializers import uniform as uniform_init
from jax.nn.initializers import variance_scaling

# fan-in uniform, the classic DDPG hidden init (1/sqrt(fan_in))
_fanin = variance_scaling(scale=1.0 / 3.0, mode="fan_in",
                          distribution="uniform")


def _out_init(scale: float = 3e-3):
    def init(key, shape, dtype=jnp.float32):
        import jax
        return jax.random.uniform(key, shape, dtype, -scale, scale)
    return init


class DdpgMlpModel(nn.Module):
    action_dim: int
    norm_val: float = 1.0
    actor_hidden: Tuple[int, int] = (300, 200)
    critic_hidden: Tuple[int, int] = (400, 300)

    def setup(self):
        a1, a2 = self.actor_hidden
        c1, c2 = self.critic_hidden
        self.actor_l1 = nn.Dense(a1, kernel_init=_fanin)
        self.actor_l2 = nn.Dense(a2, kernel_init=_fanin)
        self.actor_out = nn.Dense(self.action_dim, kernel_init=_out_init(),
                                  bias_init=uniform_init(3e-3))
        self.critic_l1 = nn.Dense(c1, kernel_init=_fanin)
        self.critic_l2 = nn.Dense(c2, kernel_init=_fanin)
        self.critic_out = nn.Dense(1, kernel_init=_out_init(),
                                   bias_init=uniform_init(3e-3))

    def _norm(self, x: jnp.ndarray) -> jnp.ndarray:
        x = x.astype(jnp.float32) / self.norm_val
        return x.reshape((x.shape[0], -1))

    def forward_actor(self, x: jnp.ndarray) -> jnp.ndarray:
        x = self._norm(x)
        x = nn.tanh(self.actor_l1(x))
        x = nn.tanh(self.actor_l2(x))
        return nn.tanh(self.actor_out(x))

    def forward_critic(self, x: jnp.ndarray, a: jnp.ndarray) -> jnp.ndarray:
        x = self._norm(x)
        h = nn.tanh(self.critic_l1(x))
        h = jnp.concatenate([h, a.reshape((a.shape[0], -1))], axis=-1)
        h = nn.tanh(self.critic_l2(h))
        return self.critic_out(h).squeeze(-1)

    def __call__(self, x: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
        # combined pass (reference ddpg_mlp_model.py:66-72): Q(s, pi(s))
        a = self.forward_actor(x)
        return a, self.forward_critic(x, a)
