"""pytorch_distributed_tpu — a TPU-native distributed RL framework.

A ground-up JAX/XLA re-design of the capabilities of the reference
``LJP580230/pytorch-distributed`` repo (Ape-X style asynchronous actor/learner
training with a global replay memory, Distributed DQN + Distributed DDPG,
Atari pipeline, evaluator/tester/logger processes, TensorBoard metrics and
checkpointing) — built TPU-first:

- the learner update is a single jit-compiled XLA program, optionally
  sharded over a ``jax.sharding.Mesh`` with gradient all-reduce over ICI
  (``parallel/``);
- the replay memory is either a host ring buffer shared across actor
  processes (``memory/shared_replay.py``, the equivalent of the reference's
  ``core/memories/shared_memory.py``) or a device-resident sharded buffer in
  HBM (``memory/device_replay.py``);
- models are Flax modules with explicitly-keyed functional ``act`` policies
  (``models/``), replacing the reference's ``core/models/*`` torch modules;
- actor/learner/evaluator/tester/logger are OS processes communicating by
  explicit message passing instead of shared CUDA storage
  (``agents/``, replacing the reference's ``core/single_processes/``).

See SURVEY.md at the repo root for the layer-by-layer mapping.
"""

__version__ = "0.1.0"
