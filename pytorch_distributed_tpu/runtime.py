"""Run topology: wiring and supervision.

Re-design of the reference orchestrator (reference main.py:12-118): allocate
the shared objects (replay plane, param store, clocks — the explicit
equivalents of the reference's shared memory at main.py:42, shared CUDA
model at :44-47, and mp.Value logs at :51-54), then run one logger,
``num_actors`` actors and one evaluator as workers, with **the learner in
the parent process** — the parent owns the TPU mesh; every child pins JAX to
CPU through the spawn trampoline, so exactly one process initialises the
accelerator (the reference instead gives every process a CUDA context).
Scaling learners means widening the mesh's dp axis, not adding racing
processes (agents/learner.py docstring).

Supervision — absent in the reference, where a dead worker silently stalls
or hangs the run (SURVEY.md §5 "failure detection: none"): a monitor thread
watches child liveness and trips the shared stop event if any child dies
abnormally; shutdown joins with a timeout and terminates stragglers.

Backends: ``process`` (spawn, production) and ``thread`` (in-process, the
deterministic test harness SURVEY.md §4 calls for).
"""

from __future__ import annotations

import multiprocessing as mp
import os
import signal
import threading
import time
from typing import Any, Dict, List, Optional

from pytorch_distributed_tpu.config import Options
from pytorch_distributed_tpu.factory import (
    EnvSpec, anakin_active, build_memory, get_worker,
    needs_inference_server, prebuild_native, probe_env,
)
from pytorch_distributed_tpu.agents.clocks import (
    ActorStats, EvaluatorStats, GlobalClock, LearnerStats,
)
from pytorch_distributed_tpu.agents.param_store import ParamStore

_CTX = mp.get_context("spawn")


def _count_params(opt: Options, spec: EnvSpec) -> int:
    from pytorch_distributed_tpu.factory import build_model, init_params
    from pytorch_distributed_tpu.utils.helpers import tree_size

    model = build_model(opt, spec)
    return tree_size(init_params(opt, spec, model, seed=opt.seed))


def _child_main(role: str, agent_type: str, args: tuple) -> None:
    """Spawn trampoline: pin this child to the CPU backend *before* any JAX
    computation, then dispatch to the worker function.  Backends initialise
    lazily, so flipping the config here is safe even though modules were
    imported during unpickling.

    Also the crash boundary for the flight recorder: an exception escaping
    the worker dumps this process's event rings to ``blackbox/`` BEFORE
    re-raising — the supervisor's restart must not erase the evidence."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    # CPU-backend processes never use the persistent compile cache: the
    # CPU AOT loader can nondeterministically SIGABRT re-loaded
    # multi-device programs (utils/helpers.enable_compile_cache), and a
    # TPU parent's cache env var would otherwise leak in here
    os.environ.pop("JAX_COMPILATION_CACHE_DIR", None)
    import jax

    jax.config.update("jax_platforms", "cpu")
    from pytorch_distributed_tpu.utils import flight_recorder

    opt = args[0]
    flight_recorder.configure(opt.log_dir, run_id=opt.refs)
    label = role
    if role in ("actor", "evaluator") and len(args) > 2:
        label = f"{role}-{args[2]}"
    jax.config.update("jax_compilation_cache_dir", None)
    if role == "evaluator":
        # The evaluator's batch-1 greedy episodes are bursty CPU work
        # that matters only for reporting cadence; on an oversubscribed
        # host its bursts starved the learner (observed: the config-14
        # learner fell 2.2 -> 0.1 updates/s once eval episodes
        # lengthened, 2026-07-31).  Deprioritise it so the training
        # plane keeps the core — tunable because the flip side is a
        # starved evaluator on a 1-core host (AgentParams.evaluator_nice).
        nice = args[0].agent_params.evaluator_nice
        if nice:
            try:
                os.nice(nice)
            except OSError:  # pragma: no cover - restricted environments
                pass
    try:
        get_worker(role, agent_type)(*args)
    except BaseException as e:
        flight_recorder.get_recorder(label).record("crash", error=repr(e))
        flight_recorder.dump_all(f"{label} crashed: {e!r}")
        raise


class Topology:
    """Builds the shared plane and runs the worker topology for one
    Options."""

    def __init__(self, opt: Options, spec: Optional[EnvSpec] = None):
        self.opt = opt
        self.spec = spec if spec is not None else probe_env(opt)
        self.clock = GlobalClock()
        self.actor_stats = ActorStats()
        self.learner_stats = LearnerStats()
        self.evaluator_stats = EvaluatorStats()
        self.param_store = ParamStore(_count_params(opt, self.spec))
        self.handles = build_memory(opt, self.spec)
        # actor_backend=batched: the shared inference batcher lives HERE
        # — this process owns the accelerator (the learner runs in it),
        # so the SEED-style wide actor forward shares the device with
        # the learner's dispatches instead of burning actor-host CPUs
        # (agents/inference.py)
        self.inference_server = None
        if needs_inference_server(opt):
            from pytorch_distributed_tpu.agents.inference import (
                InferenceServer,
            )

            self.inference_server = InferenceServer(
                opt, self.spec, self.param_store)
        self._workers: List[Any] = []
        # populated by the process-backend monitor; the health plane
        # (fleet.py STATUS provider) reads per-slot budget remaining
        self._restart_budget = None
        # set when a SIGTERM (preemption notice) ended the run rather
        # than the step budget — observable by callers/tests
        self.preempted = threading.Event()
        # ---- hang watchdog (health sentinel): every supervised role
        # publishes liveness-progress marks on a shared board riding the
        # clock's spawn pickle; the monitor SIGKILLs workers whose marks
        # go stale past hang_deadline (utils/supervision.ProgressBoard).
        from pytorch_distributed_tpu.utils import flow, health, perf
        from pytorch_distributed_tpu.utils.supervision import ProgressBoard

        self.health = health.resolve(opt.health_params)
        # flow-control plane (ISSUE 11): resolved once and exported to
        # the environment so spawn children (actor feeders building
        # their shed rings, the device-ingest pending bound) resolve
        # the same policy the topology was configured with
        self.flow = flow.resolve_flow(opt.flow_params)
        flow.export_env(self.flow)
        # perf plane knobs resolved once for the topology; exported to
        # the environment so spawn children (and tools THEY fork)
        # resolve the same plane even when it was enabled
        # programmatically rather than by TPU_APEX_PERF=1
        self.perf = perf.resolve(opt.perf_params)
        if self.perf.enabled:
            perf.export_env(self.perf)
        # replica plane (ISSUE 15): resolved once + exported on the same
        # spawn-inheritance contract.  The ReplicaRegistry itself rides
        # the fleet DCN gateway (fleet.FleetTopology builds it); a plain
        # Topology with replicas > 1 has no registry and the learner
        # downgrades loudly to solo (agents/learner.py delegation gate).
        from pytorch_distributed_tpu.parallel.dcn import (
            export_gateway_env, export_replica_env, resolve_gateway,
            resolve_replica,
        )

        self.replica = resolve_replica(opt.replica_params)
        if self.replica.replicas > 1:
            export_replica_env(self.replica)
        self.replica_registry = None
        # gateway HA plane (ISSUE 16): same resolve-once + export
        # contract — spawn children (remote actor mains, the standby
        # runner) must dial the same endpoint list and lease windows
        # the topology was configured with.  Off by default: a plain
        # fleet never journals, never syncs, stays byte-compatible.
        self.gateway_ha = resolve_gateway(opt.gateway_params)
        if self.gateway_ha.enabled:
            export_gateway_env(self.gateway_ha)
        # ---- mission control (ISSUE 10): fleet metrics aggregation +
        # SLO/alert engine + opt-in OpenMetrics endpoint.  Built here
        # (unstarted) so the fleet gateway's T_METRICS sink has a
        # target from construction; run() starts/stops the poll thread.
        from pytorch_distributed_tpu.utils import telemetry

        self.metrics_params = telemetry.resolve_metrics(opt.metrics_params)
        self.mission = None
        if self.metrics_params.enabled:
            self.mission = telemetry.MissionControl(
                opt.log_dir, self.metrics_params, opt.alert_params)
        # anakin topology (ISSUE 12): NO actor workers exist — the env
        # fleet lives in the learner process, so the watchdog board
        # carries no actor slots and _worker_specs spawns none
        self.anakin = anakin_active(opt)
        labels = ["learner", "evaluator-0"] + [
            f"actor-{i}"
            for i in range(0 if self.anakin else opt.num_actors)]
        self.progress_board = ProgressBoard(labels)
        self.clock.progress = self.progress_board
        self.hang_kills = 0  # watchdog SIGKILLs (health plane counter)

    # -- worker table (reference main.py:58-106 spawn loops) ----------------

    def _worker_specs(self):
        opt, spec = self.opt, self.spec
        specs = [("logger", 0, (opt, self.clock, self.actor_stats,
                                self.learner_stats, self.evaluator_stats))]
        for i in range(0 if self.anakin else opt.num_actors):
            # per-actor feeder clone: thread workers must not share one
            # chunk buffer (process children get their own pickled copy)
            side = self.handles.actor_side
            if hasattr(side, "clone"):
                side = side.clone()
            client = (self.inference_server.make_client(i)
                      if self.inference_server is not None else None)
            specs.append(("actor", i, (
                opt, spec, i, side, self.param_store,
                self.clock, self.actor_stats, client)))
        if opt.agent_params.evaluator_nepisodes > 0:
            specs.append(("evaluator", 0, (
                opt, spec, 0, None, self.param_store, self.clock,
                self.evaluator_stats)))
        else:
            # no evaluator (time-boxed benches): mark its handshake done so
            # the logger's end-of-run drain doesn't wait the 60 s grace
            self.evaluator_stats.done.value = 1
        return specs

    # -- run ---------------------------------------------------------------

    def run(self, backend: str = "process") -> None:
        """Mode-1 training (reference main.py:34-106): start workers, run
        the learner here, supervise, join.

        SIGTERM is treated as a PREEMPTION NOTICE (what a TPU/VM
        scheduler sends before reclaiming the host, Podracer-style): trip
        the stop event so every loop drains, let the learner write its
        final checkpoint epoch (agents/learner.py end-of-loop
        ``_save_epoch``), join, and exit cleanly — the next ``--resume``
        run continues from that epoch.  Installed only when this is the
        process's main thread (signal API constraint); thread-backend
        test harnesses driving run() from a worker thread keep their
        default handling."""
        assert backend in ("process", "thread")
        opt = self.opt
        prebuild_native(opt)  # once, before N workers race the same g++
        from pytorch_distributed_tpu.utils import flight_recorder

        # the run's blackbox home; exported so spawn children inherit it
        # without plumbing (same trick the fault schedules use)
        flight_recorder.configure(opt.log_dir, export_env=True,
                                  run_id=opt.refs)
        prev_term = None
        run_over = threading.Event()
        if threading.current_thread() is threading.main_thread():
            def _on_sigterm(signum, frame):
                # handler touches ONLY self.preempted (a threading.Event
                # whose lock no other thread's hot path takes — its
                # is_set is a lockless flag read).  Promoting to the
                # shared mp stop event happens on the watcher thread
                # below, never here: mp.Event's internal lock is not
                # reentrant and the interrupted main thread — the learner
                # — polls clock.stop constantly, so a set() from the
                # handler could deadlock against the very loop it is
                # trying to stop.
                self.preempted.set()

            installed = False
            try:
                prev_term = signal.signal(signal.SIGTERM, _on_sigterm)
                installed = True
            except (ValueError, OSError):  # pragma: no cover - exotic host
                prev_term = None
            if installed:
                def _promote_preemption():
                    while not run_over.is_set():
                        if self.preempted.wait(0.2):
                            print("[runtime] SIGTERM: preemption notice "
                                  "— draining for a final checkpoint "
                                  "epoch", flush=True)
                            flight_recorder.get_recorder("runtime").record(
                                "sigterm-preemption")
                            flight_recorder.dump_all(
                                "SIGTERM preemption notice")
                            self.clock.stop.set()
                            return

                threading.Thread(target=_promote_preemption,
                                 name="preempt-watch",
                                 daemon=True).start()
        if backend == "thread":
            self._use_thread_queue()
        if backend == "process":
            self._proc_meta = []
            for role, ind, args in self._worker_specs():
                self._spawn(role, ind, args)
            monitor = threading.Thread(target=self._monitor, daemon=True)
            monitor.start()
        else:
            for role, ind, args in self._worker_specs():
                t = threading.Thread(
                    target=get_worker(role, opt.agent_type), args=args,
                    name=f"{role}-{ind}", daemon=True)
                t.start()
                self._workers.append(t)

        if self.inference_server is not None:
            # after _worker_specs wired the clients, before anyone acts
            self.inference_server.start()
        if self.mission is not None:
            # after the blackbox home is configured (alert transitions
            # record into this process's rings), before the learner
            # starts producing the rows it will aggregate
            self.mission.start()
        try:
            self.progress_board.note_start("learner")
            if self.anakin:
                # the co-located Anakin loop: this process hosts the
                # env fleet AND the learner; pass the shared ActorStats
                # so the logger's rollout curves keep flowing without
                # any actor worker existing
                from pytorch_distributed_tpu.agents.anakin import (
                    run_anakin_learner,
                )

                run_anakin_learner(
                    opt, self.spec, 0, self.handles.learner_side,
                    self.param_store, self.clock, self.learner_stats,
                    actor_stats=self.actor_stats)
            else:
                run_learner = get_worker("learner", opt.agent_type)
                run_learner(opt, self.spec, 0, self.handles.learner_side,
                            self.param_store, self.clock,
                            self.learner_stats)
        finally:
            # learner done (or dead): release every spinning loop
            self.clock.stop.set()
            run_over.set()  # parks the preemption watcher
            if prev_term is not None:
                signal.signal(signal.SIGTERM, prev_term)
            self._join_all()
            if self.inference_server is not None:
                # after the join: an actor draining its last tick may
                # still be blocked in collect()
                self.inference_server.stop()
            if self.mission is not None:
                # final tail drain + alert pass, then the writer closes;
                # before _pre_close so a last T_METRICS push racing the
                # gateway teardown still finds a live sink
                self.mission.stop()
            # transports feeding learner_side must shut before its queue
            # closes (FleetTopology stops its DCN gateway here)
            self._pre_close()
            if hasattr(self.handles.learner_side, "close"):
                self.handles.learner_side.close()

    def _pre_close(self) -> None:
        """Hook: extra transports to tear down before learner_side closes."""

    def _use_thread_queue(self) -> None:
        """In-process workers don't need the spawn-context queue: mp.Queue
        pickles every chunk (a uint8 Atari transition is ~56 KB, so a
        16-chunk put copies ~1 MB through a pipe), while queue.Queue hands
        over references.  Swap the shared queue before any worker starts;
        feeder clones made in _worker_specs pick the new queue up."""
        import queue as _q

        ls, as_ = self.handles.learner_side, self.handles.actor_side
        if hasattr(ls, "_q") and hasattr(as_, "_q") and ls._q is as_._q:
            # keep the mp queue's chunk bound: backpressure must still
            # stall producers when the learner falls behind, or drains
            # balloon into multi-GB backlog copies
            tq = _q.Queue(getattr(ls, "max_queue_chunks", 4096))
            ls._q = tq
            as_._q = tq

    def _spawn(self, role: str, ind: int, args: tuple) -> None:
        p = _CTX.Process(
            target=_child_main, args=(role, self.opt.agent_type, args),
            name=f"{role}-{ind}", daemon=True)
        p.start()
        # restart the slot's watchdog grace window with the incarnation
        self.progress_board.note_start(f"{role}-{ind}")
        self._workers.append(p)
        self._proc_meta.append((p, role, ind, args))

    def _monitor(self, poll: float = 0.5, max_restarts: int = 3) -> None:
        """Failure detection + elastic recovery — both absent in the
        reference, where a dead actor silently reduces throughput and a
        dead learner hangs every loop (SURVEY.md §5).  A crashed ACTOR is
        restarted in place (Ape-X tolerates actor churn; its replay
        contribution just pauses), up to ``max_restarts`` per slot; any
        other abnormal child death — or an actor out of restart budget —
        trips the stop event so the run fails fast instead of degrading
        silently.  Restart/GRACE policy shared with the fleet actor-host
        supervisor via utils/supervision.RestartBudget."""
        from pytorch_distributed_tpu.utils import flight_recorder
        from pytorch_distributed_tpu.utils.supervision import (
            EXIT_HUNG, RestartBudget, describe_exit,
        )

        recorder = flight_recorder.get_recorder("runtime")
        budget = RestartBudget(max_restarts=max_restarts)
        # exposed for the health plane: the fleet gateway's STATUS verb
        # reports per-slot restart budget remaining from here
        self._restart_budget = budget
        for _p, role, ind, _args in self._proc_meta:
            # record first incarnations: the grace-period budget reset
            # only applies to slots with a KNOWN long-lived incarnation
            # (RestartBudget.request_restart no longer treats unborn
            # slots as ancient ones)
            if role == "actor":
                budget.note_birth(ind)
        while not self.clock.stop.is_set():
            srv = self.inference_server
            if srv is not None and not srv.healthy():
                # a dead inference server starves every batched actor;
                # fail the run NOW instead of letting supervised actor
                # restarts each block a full collect timeout against a
                # thread that will never answer
                print("[runtime] inference server died; stopping run")
                recorder.record("inference-server-dead")
                flight_recorder.dump_all(
                    "inference server died; run stopped")
                self.clock.stop.set()
                return
            for i, (p, role, ind, args) in enumerate(list(self._proc_meta)):
                if p.exitcode in (None, 0):
                    continue
                if role == "actor" \
                        and budget.request_restart(ind) is not None:
                    budget.note_birth(ind)
                    print(f"[runtime] actor-{ind} died "
                          f"({describe_exit(p.exitcode)}); restart "
                          f"{budget.count(ind)}/{max_restarts}")
                    recorder.record("worker-restarted", role=role,
                                    slot=ind, exit=p.exitcode,
                                    restarts=budget.count(ind))
                    self._workers.remove(p)
                    self._proc_meta.remove((p, role, ind, args))
                    self._spawn(role, ind, args)
                else:
                    print(f"[runtime] {role}-{ind} died "
                          f"({describe_exit(p.exitcode)}); stopping run")
                    recorder.record("worker-fatal", role=role, slot=ind,
                                    exit=p.exitcode)
                    flight_recorder.dump_all(
                        f"{role}-{ind} died "
                        f"({describe_exit(p.exitcode)}); run stopped")
                    self.clock.stop.set()
                    return
            # ---- hang watchdog: an alive-but-stuck worker never
            # produces an exit code, so liveness is read off the
            # progress board instead.  Hung children are SIGKILLed
            # (flight recorder dumped first — the kill erases nothing)
            # and actors respawn through the SAME RestartBudget as a
            # crash, classified EXIT_HUNG.  Opt-in: hang_deadline=0 (the
            # default) disables the pass entirely.
            hd = self.health.hang_deadline
            if hd and hd > 0:
                hung = set(self.progress_board.hung(
                    hd, self.health.hang_grace))
                for p, role, ind, args in list(self._proc_meta):
                    label = f"{role}-{ind}"
                    if label not in hung or p.exitcode is not None:
                        continue
                    self.hang_kills += 1
                    recorder.record("worker-hung", role=role, slot=ind,
                                    age=round(self.progress_board.age(
                                        label), 1))
                    flight_recorder.dump_all(
                        f"{label} hung (> {hd:g}s without progress); "
                        f"watchdog SIGKILL")
                    p.kill()
                    p.join(5.0)
                    self._workers.remove(p)
                    self._proc_meta.remove((p, role, ind, args))
                    if role == "actor" \
                            and budget.request_restart(ind) is not None:
                        budget.note_birth(ind)
                        print(f"[runtime] {label} "
                              f"({describe_exit(EXIT_HUNG)}); restart "
                              f"{budget.count(ind)}/{max_restarts}")
                        recorder.record("worker-restarted", role=role,
                                        slot=ind, exit=EXIT_HUNG,
                                        restarts=budget.count(ind))
                        self._spawn(role, ind, args)
                    else:
                        print(f"[runtime] {label} "
                              f"({describe_exit(EXIT_HUNG)}); "
                              f"stopping run")
                        recorder.record("worker-fatal", role=role,
                                        slot=ind, exit=EXIT_HUNG)
                        self.clock.stop.set()
                        return
                if "learner" in hung:
                    # the learner runs on THIS process's main thread: a
                    # SIGKILL from here kills the whole host, which is
                    # exactly right — a stuck learner stalls every loop
                    # and only an outer orchestrator (--resume) can
                    # bring the run back.  Dump first; exit EXIT_HUNG.
                    recorder.record("learner-hung")
                    flight_recorder.dump_all(
                        f"learner hung (> {hd:g}s without progress); "
                        f"failing host fast")
                    print(f"[runtime] learner "
                          f"({describe_exit(EXIT_HUNG)}); exiting for "
                          f"the outer orchestrator", flush=True)
                    self.clock.stop.set()
                    os._exit(EXIT_HUNG)
            time.sleep(poll)

    def _join_all(self, timeout: float = 240.0) -> None:
        # generous: the evaluator's final eval (jit + greedy episodes) can
        # take minutes on a saturated host, and a thread-backend worker
        # abandoned at interpreter exit aborts the process from C++
        # teardown — waiting is the safe side
        t0 = time.monotonic()
        deadline = t0 + timeout
        hd = self.health.hang_deadline
        if hd and hd > 0:
            # watchdog-enabled shutdown: a worker whose progress mark is
            # already stale cannot drain anything — a hang that landed
            # AFTER the monitor exited (stop set) would otherwise pin
            # this join for the full timeout.  Poll-join and terminate
            # hung stragglers as their marks go stale.
            while time.monotonic() < deadline:
                alive = [w for w in self._workers if w.is_alive()]
                if not alive:
                    break
                hung = set(self.progress_board.hung(
                    hd, self.health.hang_grace))
                for w in alive:
                    if isinstance(w, _CTX.Process) and w.name in hung:
                        print(f"[runtime] {w.name} hung at shutdown; "
                              f"terminating")
                        w.terminate()
                time.sleep(0.25)
        for w in self._workers:
            w.join(max(0.1, deadline - time.monotonic()))
        if time.monotonic() - t0 > 30.0:
            slow = [w.name for w in self._workers
                    if (w.is_alive() if hasattr(w, "is_alive") else False)]
            print(f"[runtime] join took {time.monotonic() - t0:.0f}s; "
                  f"still alive: {slow or 'none'}")
        for w in self._workers:
            if isinstance(w, _CTX.Process) and w.is_alive():
                w.terminate()
                w.join(5.0)


def train(opt: Options, backend: str = "process") -> Topology:
    topo = Topology(opt)
    topo.run(backend=backend)
    return topo


def test(opt: Options) -> Dict[str, float]:
    """Mode-2 (reference main.py:107-115): run the tester inline."""
    from pytorch_distributed_tpu.agents.tester import run_tester

    return run_tester(opt, probe_env(opt))
