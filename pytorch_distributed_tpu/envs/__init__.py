from pytorch_distributed_tpu.envs.base import DiscreteSpace, ContinuousSpace, Env
from pytorch_distributed_tpu.envs.fake_env import FakeChainEnv
from pytorch_distributed_tpu.envs.classic import CartPoleEnv, PendulumEnv, make_classic_env
from pytorch_distributed_tpu.envs.pong_sim import PongSimEnv
from pytorch_distributed_tpu.envs.device_env import (
    DeviceEnv, DevicePongVectorEnv, make_device_pong,
)

__all__ = [
    "Env", "DiscreteSpace", "ContinuousSpace", "FakeChainEnv",
    "CartPoleEnv", "PendulumEnv", "make_classic_env", "PongSimEnv",
    "DeviceEnv", "DevicePongVectorEnv", "make_device_pong",
]
