"""Deterministic chain MDP for tests and CPU smoke runs.

The reference has no test env at all (SURVEY.md §4 recommends adding one);
this fills that hole.  A length-L chain: state i (one-hot), action 1 moves
right (+0 reward until the terminal right end pays +1), action 0 moves left
(reward 0, floor at state 0).  Optimal policy: always right; the optimal
n-step/TD values are known in closed form, which the learner-math tests use.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import numpy as np

from pytorch_distributed_tpu.envs.base import DiscreteSpace, Env


class FakeChainEnv(Env):
    LENGTH = 8

    def __init__(self, env_params, process_ind: int = 0, length: int | None = None):
        super().__init__(env_params, process_ind)
        self.length = length or self.LENGTH
        self.pos = 0
        self.norm_val = 1.0

    @property
    def state_shape(self) -> Tuple[int, ...]:
        return (self.length,)

    @property
    def action_space(self) -> DiscreteSpace:
        return DiscreteSpace(2)

    def _obs(self) -> np.ndarray:
        o = np.zeros((self.length,), dtype=np.float32)
        o[self.pos] = 1.0
        return o

    def _reset(self) -> np.ndarray:
        self.pos = 0
        return self._obs()

    def _step(self, action) -> Tuple[np.ndarray, float, bool, Dict[str, Any]]:
        action = int(action)
        if action == 1:
            self.pos += 1
        else:
            self.pos = max(0, self.pos - 1)
        terminal = self.pos >= self.length - 1
        reward = 1.0 if terminal else 0.0
        return self._obs(), reward, terminal, {}

    def optimal_q(self, gamma: float) -> np.ndarray:
        """Closed-form optimal Q table, shape (length-1, 2) over non-terminal
        states; used by learner convergence tests."""
        L = self.length
        q = np.zeros((L - 1, 2), dtype=np.float64)
        # value of being in state i under optimal (always-right) policy:
        # gamma**(L-1-i-1) discounted terminal reward of 1.
        v = lambda i: gamma ** (L - 2 - i) if i <= L - 2 else 0.0
        for i in range(L - 1):
            right = 1.0 if i + 1 == L - 1 else gamma * v(i + 1)
            left = gamma * v(max(0, i - 1))
            q[i] = [left, right]
        return q
