"""Self-contained classic-control envs (no gym dependency).

The reference's continuous-control (DDPG) path assumes gym MuJoCo-style envs
(Pendulum/HalfCheetah per BASELINE.json tracked configs); neither gym nor
MuJoCo is in this image, so the standard CartPole and Pendulum dynamics are
implemented directly from their textbook equations (Barto-Sutton-Anderson
1983 cart-pole; classic torque-limited pendulum swing-up).  Observations are
float32 low-dim vectors (the reference's "mlp" state family, reference
utils/options.py:57-60).
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import numpy as np

from pytorch_distributed_tpu.envs.base import ContinuousSpace, DiscreteSpace, Env


class CartPoleEnv(Env):
    """Cart-pole balance, discrete push-left/push-right.

    Dynamics constants follow the standard formulation (gravity 9.8, cart
    mass 1.0, pole mass 0.1, half-length 0.5, force 10, Euler dt 0.02);
    episode ends on |x|>2.4, |theta|>12deg, or 500 steps.
    """

    def __init__(self, env_params, process_ind: int = 0):
        super().__init__(env_params, process_ind)
        self.gravity = 9.8
        self.masscart = 1.0
        self.masspole = 0.1
        self.total_mass = self.masscart + self.masspole
        self.length = 0.5
        self.polemass_length = self.masspole * self.length
        self.force_mag = 10.0
        self.tau = 0.02
        self.theta_threshold = 12 * 2 * np.pi / 360
        self.x_threshold = 2.4
        self.max_steps = 500
        self.state = np.zeros(4, dtype=np.float64)
        self._steps = 0

    @property
    def state_shape(self) -> Tuple[int, ...]:
        return (4,)

    @property
    def action_space(self) -> DiscreteSpace:
        return DiscreteSpace(2)

    def _reset(self) -> np.ndarray:
        self.state = self.rng.uniform(-0.05, 0.05, size=(4,))
        self._steps = 0
        return self.state.astype(np.float32)

    def _step(self, action) -> Tuple[np.ndarray, float, bool, Dict[str, Any]]:
        x, x_dot, theta, theta_dot = self.state
        force = self.force_mag if int(action) == 1 else -self.force_mag
        costheta, sintheta = np.cos(theta), np.sin(theta)
        temp = (force + self.polemass_length * theta_dot ** 2 * sintheta) \
            / self.total_mass
        thetaacc = (self.gravity * sintheta - costheta * temp) / (
            self.length * (4.0 / 3.0
                           - self.masspole * costheta ** 2 / self.total_mass))
        xacc = temp - self.polemass_length * thetaacc * costheta / self.total_mass
        x = x + self.tau * x_dot
        x_dot = x_dot + self.tau * xacc
        theta = theta + self.tau * theta_dot
        theta_dot = theta_dot + self.tau * thetaacc
        self.state = np.array([x, x_dot, theta, theta_dot])
        self._steps += 1
        terminal = bool(
            abs(x) > self.x_threshold
            or abs(theta) > self.theta_threshold
            or self._steps >= self.max_steps
        )
        return self.state.astype(np.float32), 1.0, terminal, {}


class PendulumEnv(Env):
    """Torque-limited pendulum swing-up, continuous 1-d action.

    Standard formulation: theta'' = 3g/(2l) sin(theta) + 3/(m l^2) u with
    g=10, m=1, l=1, dt=0.05, |u|<=2, cost = theta^2 + 0.1 theta'^2 +
    0.001 u^2; observation (cos, sin, theta'); 200-step episodes.
    Policies emit actions in [-1,1]; the env rescales to [-2,2]
    (ContinuousSpace.denormalize).
    """

    def __init__(self, env_params, process_ind: int = 0):
        super().__init__(env_params, process_ind)
        self.max_speed = 8.0
        self.max_torque = 2.0
        self.dt = 0.05
        self.g = 10.0
        self.m = 1.0
        self.l = 1.0
        self.max_steps = 200
        self.state = np.zeros(2, dtype=np.float64)
        self._steps = 0

    @property
    def state_shape(self) -> Tuple[int, ...]:
        return (3,)

    @property
    def action_space(self) -> ContinuousSpace:
        return ContinuousSpace(dim=1, low=-self.max_torque, high=self.max_torque)

    def _obs(self) -> np.ndarray:
        th, thdot = self.state
        return np.array([np.cos(th), np.sin(th), thdot], dtype=np.float32)

    def _reset(self) -> np.ndarray:
        self.state = self.rng.uniform([-np.pi, -1.0], [np.pi, 1.0])
        self._steps = 0
        return self._obs()

    def _step(self, action) -> Tuple[np.ndarray, float, bool, Dict[str, Any]]:
        th, thdot = self.state
        u = float(np.squeeze(self.action_space.denormalize(action)))
        angle = ((th + np.pi) % (2 * np.pi)) - np.pi
        cost = angle ** 2 + 0.1 * thdot ** 2 + 0.001 * u ** 2
        thdot = thdot + (3.0 * self.g / (2.0 * self.l) * np.sin(th)
                         + 3.0 / (self.m * self.l ** 2) * u) * self.dt
        thdot = np.clip(thdot, -self.max_speed, self.max_speed)
        th = th + thdot * self.dt
        self.state = np.array([th, thdot])
        self._steps += 1
        terminal = self._steps >= self.max_steps
        return self._obs(), float(-cost), terminal, {}


def make_classic_env(env_params, process_ind: int = 0) -> Env:
    game = env_params.game
    if game == "cartpole":
        return CartPoleEnv(env_params, process_ind)
    if game == "pendulum":
        return PendulumEnv(env_params, process_ind)
    raise ValueError(f"unknown classic game: {game}")
