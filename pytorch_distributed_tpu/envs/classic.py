"""Self-contained classic-control envs (no gym dependency).

The reference's continuous-control (DDPG) path assumes gym MuJoCo-style envs
(Pendulum/HalfCheetah per BASELINE.json tracked configs); neither gym nor
MuJoCo is in this image, so the standard CartPole and Pendulum dynamics are
implemented directly from their textbook equations (Barto-Sutton-Anderson
1983 cart-pole; classic torque-limited pendulum swing-up).  Observations are
float32 low-dim vectors (the reference's "mlp" state family, reference
utils/options.py:57-60).
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import numpy as np

from pytorch_distributed_tpu.envs.base import ContinuousSpace, DiscreteSpace, Env


class CartPoleEnv(Env):
    """Cart-pole balance, discrete push-left/push-right.

    Dynamics constants follow the standard formulation (gravity 9.8, cart
    mass 1.0, pole mass 0.1, half-length 0.5, force 10, Euler dt 0.02);
    episode ends on |x|>2.4, |theta|>12deg, or 500 steps.
    """

    def __init__(self, env_params, process_ind: int = 0):
        super().__init__(env_params, process_ind)
        self.gravity = 9.8
        self.masscart = 1.0
        self.masspole = 0.1
        self.total_mass = self.masscart + self.masspole
        self.length = 0.5
        self.polemass_length = self.masspole * self.length
        self.force_mag = 10.0
        self.tau = 0.02
        self.theta_threshold = 12 * 2 * np.pi / 360
        self.x_threshold = 2.4
        self.max_steps = 500
        self.state = np.zeros(4, dtype=np.float64)
        self._steps = 0

    @property
    def state_shape(self) -> Tuple[int, ...]:
        return (4,)

    @property
    def action_space(self) -> DiscreteSpace:
        return DiscreteSpace(2)

    def _reset(self) -> np.ndarray:
        self.state = self.rng.uniform(-0.05, 0.05, size=(4,))
        self._steps = 0
        return self.state.astype(np.float32)

    def _step(self, action) -> Tuple[np.ndarray, float, bool, Dict[str, Any]]:
        x, x_dot, theta, theta_dot = self.state
        force = self.force_mag if int(action) == 1 else -self.force_mag
        costheta, sintheta = np.cos(theta), np.sin(theta)
        temp = (force + self.polemass_length * theta_dot ** 2 * sintheta) \
            / self.total_mass
        thetaacc = (self.gravity * sintheta - costheta * temp) / (
            self.length * (4.0 / 3.0
                           - self.masspole * costheta ** 2 / self.total_mass))
        xacc = temp - self.polemass_length * thetaacc * costheta / self.total_mass
        x = x + self.tau * x_dot
        x_dot = x_dot + self.tau * xacc
        theta = theta + self.tau * theta_dot
        theta_dot = theta_dot + self.tau * thetaacc
        self.state = np.array([x, x_dot, theta, theta_dot])
        self._steps += 1
        died = bool(abs(x) > self.x_threshold
                    or abs(theta) > self.theta_threshold)
        timed_out = self._steps >= self.max_steps
        info: Dict[str, Any] = {}
        if timed_out and not died:
            # surviving to the step cap is a truncation (bootstrap), not
            # a failure terminal
            info["truncated"] = True
        return (self.state.astype(np.float32), 1.0, died or timed_out,
                info)


class PendulumEnv(Env):
    """Torque-limited pendulum swing-up, continuous 1-d action.

    Standard formulation: theta'' = 3g/(2l) sin(theta) + 3/(m l^2) u with
    g=10, m=1, l=1, dt=0.05, |u|<=2, cost = theta^2 + 0.1 theta'^2 +
    0.001 u^2; observation (cos, sin, theta'); 200-step episodes.
    Policies emit actions in [-1,1]; the env rescales to [-2,2]
    (ContinuousSpace.denormalize).
    """

    def __init__(self, env_params, process_ind: int = 0):
        super().__init__(env_params, process_ind)
        self.max_speed = 8.0
        self.max_torque = 2.0
        self.dt = 0.05
        self.g = 10.0
        self.m = 1.0
        self.l = 1.0
        self.max_steps = 200
        self.state = np.zeros(2, dtype=np.float64)
        self._steps = 0

    @property
    def state_shape(self) -> Tuple[int, ...]:
        return (3,)

    @property
    def action_space(self) -> ContinuousSpace:
        return ContinuousSpace(dim=1, low=-self.max_torque, high=self.max_torque)

    def _obs(self) -> np.ndarray:
        th, thdot = self.state
        return np.array([np.cos(th), np.sin(th), thdot], dtype=np.float32)

    def _reset(self) -> np.ndarray:
        self.state = self.rng.uniform([-np.pi, -1.0], [np.pi, 1.0])
        self._steps = 0
        return self._obs()

    def _step(self, action) -> Tuple[np.ndarray, float, bool, Dict[str, Any]]:
        th, thdot = self.state
        u = float(np.squeeze(self.action_space.denormalize(action)))
        angle = ((th + np.pi) % (2 * np.pi)) - np.pi
        cost = angle ** 2 + 0.1 * thdot ** 2 + 0.001 * u ** 2
        thdot = thdot + (3.0 * self.g / (2.0 * self.l) * np.sin(th)
                         + 3.0 / (self.m * self.l ** 2) * u) * self.dt
        thdot = np.clip(thdot, -self.max_speed, self.max_speed)
        th = th + thdot * self.dt
        self.state = np.array([th, thdot])
        self._steps += 1
        terminal = self._steps >= self.max_steps
        # fixed-length episode: the end is a time limit, not a death state
        info: Dict[str, Any] = {"truncated": True} if terminal else {}
        return self._obs(), float(-cost), terminal, info


class ReacherEnv(Env):
    """Two-joint planar arm reaching a random target — the multi-dim
    continuous-action env the DDPG family needs (the reference's DDPG
    restricts itself to scalar action spaces via ``.item()``, reference
    core/models/ddpg_mlp_model.py:74-78; BASELINE.json tracks MuJoCo
    HalfCheetah/Humanoid configs that this image cannot run).

    Dynamics: two damped torque-driven joints (decoupled inertia — a
    deliberate simplification of the full manipulator equations; the RL
    problem of coordinating a 2-dim action to steer a nonlinear fingertip
    stays).  Link lengths 0.1/0.11 and the control/distance cost mirror the
    gym Reacher convention.  Observation (10-dim float32):
    cos/sin of both joints, both velocities, target xy, fingertip-target
    delta.  Action: 2 torques in [-1,1]; 150-step episodes;
    ``info["solved"]`` when the final fingertip lands within 5 cm.
    """

    L1, L2 = 0.1, 0.11
    MAX_TORQUE = 1.0
    DT = 0.05
    DAMPING = 0.5
    INERTIA = 0.1   # DT/INERTIA=0.5: qdot' = 0.75*qdot + 0.5*u — velocity
    MAX_SPEED = 4.0  # carries memory (steady state 2*u; clip is headroom)

    def __init__(self, env_params, process_ind: int = 0):
        super().__init__(env_params, process_ind)
        self.max_steps = 150
        self.q = np.zeros(2)       # joint angles
        self.qdot = np.zeros(2)    # joint velocities
        self.target = np.zeros(2)
        self._steps = 0

    @property
    def state_shape(self) -> Tuple[int, ...]:
        return (10,)

    @property
    def action_space(self) -> ContinuousSpace:
        return ContinuousSpace(dim=2, low=-self.MAX_TORQUE,
                               high=self.MAX_TORQUE)

    def _fingertip(self) -> np.ndarray:
        x = self.L1 * np.cos(self.q[0]) \
            + self.L2 * np.cos(self.q[0] + self.q[1])
        y = self.L1 * np.sin(self.q[0]) \
            + self.L2 * np.sin(self.q[0] + self.q[1])
        return np.array([x, y])

    def _obs(self) -> np.ndarray:
        delta = self._fingertip() - self.target
        return np.concatenate([
            np.cos(self.q), np.sin(self.q), self.qdot * 0.1,
            self.target, delta,
        ]).astype(np.float32)

    def _reset(self) -> np.ndarray:
        self.q = self.rng.uniform(-np.pi, np.pi, size=2)
        self.qdot = self.rng.uniform(-0.5, 0.5, size=2)
        # target uniformly inside the reachable annulus (radius <= L1+L2)
        r = np.sqrt(self.rng.uniform(0.0, 1.0)) * (self.L1 + self.L2)
        phi = self.rng.uniform(-np.pi, np.pi)
        self.target = np.array([r * np.cos(phi), r * np.sin(phi)])
        self._steps = 0
        return self._obs()

    def _step(self, action) -> Tuple[np.ndarray, float, bool, Dict[str, Any]]:
        u = self.action_space.denormalize(np.asarray(action).reshape(2))
        self.qdot = self.qdot + self.DT * (
            u - self.DAMPING * self.qdot) / self.INERTIA
        self.qdot = np.clip(self.qdot, -self.MAX_SPEED, self.MAX_SPEED)
        self.q = self.q + self.DT * self.qdot
        self._steps += 1
        dist = float(np.linalg.norm(self._fingertip() - self.target))
        reward = -(dist + 0.01 * float(np.square(u).sum()))
        terminal = self._steps >= self.max_steps
        info: Dict[str, Any] = {}
        if terminal:
            # a pure time limit, not a death state: flag truncation so the
            # n-step assembler bootstraps the tail instead of zeroing it
            # (ops/nstep.py truncation-vs-terminal handling)
            info["truncated"] = True
            info["solved"] = dist < 0.05
        return self._obs(), reward, terminal, info


def make_classic_env(env_params, process_ind: int = 0) -> Env:
    game = env_params.game
    if game == "cartpole":
        return CartPoleEnv(env_params, process_ind)
    if game == "pendulum":
        return PendulumEnv(env_params, process_ind)
    if game == "reacher":
        return ReacherEnv(env_params, process_ind)
    raise ValueError(f"unknown classic game: {game}")
