"""ALE-free Pong simulator emitting the full Atari observation pipeline.

Why this exists: the BASELINE north star is DQN on Pong, but ALE
(atari_py/ale_py) is not installed in this image.  This env reimplements the
*game* of Pong (ball, two paddles, scoring to 21) as a small numpy
simulation and runs it through exactly the preprocessing contract of the
reference Atari path so models/replay/bench exercise identical shapes and
dtypes: 84x84 grayscale uint8 frames, action-repeat 4 with a max-pool over
the last two raw frames, 4-frame history stack, norm_val 255
(reference core/envs/atari_env.py:53-61, 89-104).

Action set mirrors ALE Pong's minimal set of 6 (NOOP/FIRE/UP/DOWN/
UPFIRE/DOWNFIRE — FIRE variants act like their move) so a policy trained
here has the same action head as on real ALE Pong.

The opponent is a rate-limited ball tracker; its max paddle speed is below
the ball's vertical speed range, so it is beatable but not trivially
(random play scores about -21, a perfect tracker scores +21).
"""

from __future__ import annotations

from collections import deque
from typing import Any, Dict, Tuple

import numpy as np

from pytorch_distributed_tpu.envs.base import DiscreteSpace, Env

# Playfield geometry in "game units" (rendered straight into 84x84).
H, W = 84.0, 84.0
PADDLE_H = 10.0
PADDLE_W = 2.0
BALL = 2.0
PLAYER_X = W - 6.0          # right paddle (the agent, as in ALE Pong)
ENEMY_X = 4.0
PLAYER_SPEED = 2.0          # units per raw frame
ENEMY_SPEED = 0.9
BALL_SPEED_X = 1.4
WIN_SCORE = 21

ACTIONS = ("NOOP", "FIRE", "UP", "DOWN", "UPFIRE", "DOWNFIRE")
_MOVE = {0: 0.0, 1: 0.0, 2: -PLAYER_SPEED, 3: +PLAYER_SPEED,
         4: -PLAYER_SPEED, 5: +PLAYER_SPEED}


class PongSimEnv(Env):
    def __init__(self, env_params, process_ind: int = 0):
        super().__init__(env_params, process_ind)
        self.norm_val = 255.0
        self.hist_len = env_params.state_cha
        self.frame_stack: deque = deque(maxlen=self.hist_len)
        self._score = [0, 0]  # [enemy, player]
        self._reset_ball(direction=1)
        self.player_y = H / 2
        self.enemy_y = H / 2

    # -- spaces -------------------------------------------------------------

    @property
    def state_shape(self) -> Tuple[int, ...]:
        return (self.hist_len, 84, 84)

    @property
    def action_space(self) -> DiscreteSpace:
        return DiscreteSpace(len(ACTIONS))

    # -- game dynamics (per raw frame) --------------------------------------

    def _reset_ball(self, direction: int) -> None:
        self.ball_x = W / 2
        self.ball_y = float(self.rng.uniform(20.0, H - 20.0))
        self.ball_vx = BALL_SPEED_X * direction
        self.ball_vy = float(self.rng.uniform(-1.2, 1.2))

    def _tick(self, move: float) -> float:
        """Advance one raw frame; returns scoring reward for the player."""
        self.player_y = float(np.clip(self.player_y + move,
                                      PADDLE_H / 2, H - PADDLE_H / 2))
        # enemy: rate-limited tracking with small deadzone
        err = self.ball_y - self.enemy_y
        self.enemy_y = float(np.clip(
            self.enemy_y + np.clip(err, -ENEMY_SPEED, ENEMY_SPEED),
            PADDLE_H / 2, H - PADDLE_H / 2))

        self.ball_x += self.ball_vx
        self.ball_y += self.ball_vy
        # wall bounce
        if self.ball_y < BALL / 2:
            self.ball_y = BALL - self.ball_y
            self.ball_vy = -self.ball_vy
        elif self.ball_y > H - BALL / 2:
            self.ball_y = 2 * (H - BALL / 2) - self.ball_y
            self.ball_vy = -self.ball_vy

        # paddle collisions
        if (self.ball_vx > 0
                and self.ball_x >= PLAYER_X - PADDLE_W
                and abs(self.ball_y - self.player_y) <= PADDLE_H / 2 + BALL / 2):
            self.ball_x = PLAYER_X - PADDLE_W
            self.ball_vx = -self.ball_vx
            # english: hitting off-center adds vertical speed
            self.ball_vy += 0.5 * (self.ball_y - self.player_y) / (PADDLE_H / 2)
            self.ball_vy = float(np.clip(self.ball_vy, -2.0, 2.0))
        elif (self.ball_vx < 0
                and self.ball_x <= ENEMY_X + PADDLE_W
                and abs(self.ball_y - self.enemy_y) <= PADDLE_H / 2 + BALL / 2):
            self.ball_x = ENEMY_X + PADDLE_W
            self.ball_vx = -self.ball_vx
            self.ball_vy += 0.5 * (self.ball_y - self.enemy_y) / (PADDLE_H / 2)
            self.ball_vy = float(np.clip(self.ball_vy, -2.0, 2.0))

        # scoring
        if self.ball_x < 0:
            self._score[1] += 1
            self._reset_ball(direction=-1)
            return 1.0
        if self.ball_x > W:
            self._score[0] += 1
            self._reset_ball(direction=1)
            return -1.0
        return 0.0

    # -- rendering ----------------------------------------------------------

    def _draw(self) -> np.ndarray:
        f = np.zeros((84, 84), dtype=np.uint8)
        f[:] = 35  # background, roughly ALE Pong's gray level
        def vspan(y):
            lo = int(max(0, round(y - PADDLE_H / 2)))
            hi = int(min(84, round(y + PADDLE_H / 2)))
            return lo, hi
        lo, hi = vspan(self.enemy_y)
        f[lo:hi, int(ENEMY_X - PADDLE_W):int(ENEMY_X)] = 130
        lo, hi = vspan(self.player_y)
        f[lo:hi, int(PLAYER_X):int(PLAYER_X + PADDLE_W)] = 150
        by, bx = int(round(self.ball_y)), int(round(self.ball_x))
        f[max(0, by - 1):by + 1, max(0, bx - 1):bx + 1] = 236
        return f

    # -- env surface --------------------------------------------------------

    def _reset(self) -> np.ndarray:
        self._score = [0, 0]
        self.player_y = H / 2
        self.enemy_y = H / 2
        self._reset_ball(direction=1 if self.rng.random() < 0.5 else -1)
        self.frame_stack.clear()
        first = self._draw()
        for _ in range(self.hist_len):
            self.frame_stack.append(first)
        return np.stack(self.frame_stack)

    def _step(self, action) -> Tuple[np.ndarray, float, bool, Dict[str, Any]]:
        move = _MOVE[int(action)]
        reward = 0.0
        prev = None
        # action-repeat 4 + maxpool of the last two raw frames, matching the
        # reference's manual frameskip (reference core/envs/atari_env.py:89-104)
        for k in range(self.params.action_repetition):
            reward += self._tick(move)
            if k == self.params.action_repetition - 2:
                prev = self._draw()
        frame = self._draw()
        if prev is not None:
            frame = np.maximum(frame, prev)
        self.frame_stack.append(frame)
        terminal = max(self._score) >= WIN_SCORE
        return np.stack(self.frame_stack), reward, terminal, {
            "score": tuple(self._score)}
