"""The canonical Atari-57 benchmark suite list + sweep helpers.

The reference hardcodes single games in its CONFIGS rows (reference
utils/options.py:10-14 lists pong/boxing/breakout/enduro); the Ape-X paper
(and the BASELINE north star's "Atari-57, 256 actors" tracked config)
evaluates across the 57-game suite.  Game ids here are the ALE rom names
the Atari env loads (envs/atari.py resolves them through ale_py/atari_py).
"""

from __future__ import annotations

from typing import List

# single source of truth: the suite tuple next to the env that loads the
# roms (envs/atari.py normalizes "-" to "_" at load, so both id styles
# resolve to the same games)
from pytorch_distributed_tpu.envs.atari import ATARI57

ATARI_57: List[str] = list(ATARI57)

assert len(ATARI_57) == 57


def resolve_games(spec: str) -> List[str]:
    """``"all"`` -> the 57-game suite; ``"a,b,c"`` -> that list; a single
    name -> [name]."""
    if spec == "all":
        return list(ATARI_57)
    return [g.strip() for g in spec.split(",") if g.strip()]
