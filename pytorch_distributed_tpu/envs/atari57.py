"""The canonical Atari-57 benchmark suite list + sweep helpers.

The reference hardcodes single games in its CONFIGS rows (reference
utils/options.py:10-14 lists pong/boxing/breakout/enduro); the Ape-X paper
(and the BASELINE north star's "Atari-57, 256 actors" tracked config)
evaluates across the 57-game suite.  Game ids here are the ALE rom names
the Atari env loads (envs/atari.py resolves them through ale_py/atari_py).
"""

from __future__ import annotations

from typing import List

ATARI_57: List[str] = [
    "alien", "amidar", "assault", "asterix", "asteroids", "atlantis",
    "bank-heist", "battle-zone", "beam-rider", "berzerk", "bowling",
    "boxing", "breakout", "centipede", "chopper-command", "crazy-climber",
    "defender", "demon-attack", "double-dunk", "enduro", "fishing-derby",
    "freeway", "frostbite", "gopher", "gravitar", "hero", "ice-hockey",
    "jamesbond", "kangaroo", "krull", "kung-fu-master",
    "montezuma-revenge", "ms-pacman", "name-this-game", "phoenix",
    "pitfall", "pong", "private-eye", "qbert", "riverraid", "road-runner",
    "robotank", "seaquest", "skiing", "solaris", "space-invaders",
    "star-gunner", "surround", "tennis", "time-pilot", "tutankham",
    "up-n-down", "venture", "video-pinball", "wizard-of-wor",
    "yars-revenge", "zaxxon",
]

assert len(ATARI_57) == 57


def resolve_games(spec: str) -> List[str]:
    """``"all"`` -> the 57-game suite; ``"a,b,c"`` -> that list; a single
    name -> [name]."""
    if spec == "all":
        return list(ATARI_57)
    return [g.strip() for g in spec.split(",") if g.strip()]
