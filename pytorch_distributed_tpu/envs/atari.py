"""ALE Atari env with the exact preprocessing contract of the reference.

Re-design of reference core/envs/atari_env.py (cited per-behaviour below).
Gated on an ALE backend being installed: prefers ``ale_py`` (current
maintained package), falls back to legacy ``atari_py``; raises a clear error
otherwise.  This image ships neither, so ``PongSimEnv`` (pong_sim.py) covers
the visual-Pong pipeline in CI; this wrapper is exercised when a ROM-capable
install is present.

Behaviour parity checklist (each matching the reference):
- per-process seeding ``seed + process_ind * num_envs_per_actor``
  (reference atari_env.py:16)
- episode frame cap ``early_stop`` via max_num_frames, sticky actions off,
  manual frameskip (reference atari_env.py:20-24)
- minimal action set (reference atari_env.py:27-28)
- grayscale capture + bilinear resize to 84x84 (reference atari_env.py:53-58)
- action repeat 4 with max-pool over the last two raw frames
  (reference atari_env.py:89-104)
- training mode: life loss => terminal, with ``just_died`` resume-by-noop
  on the next reset instead of a full game reset
  (reference atari_env.py:106-112, 115-121)
- full reset performs up to 30 random no-ops (reference atari_env.py:122-129)
- 4-frame history stack, uint8 end-to-end, norm_val 255
  (reference atari_env.py:34, 43, 60-68)
"""

from __future__ import annotations

from collections import deque
from typing import Any, Dict, Tuple

import numpy as np

from pytorch_distributed_tpu.envs.base import DiscreteSpace, Env
from pytorch_distributed_tpu.utils.image import resize_bilinear


def _load_ale(game: str, seed: int, max_num_frames: int):
    """Return (ale, minimal_actions) from whichever ALE package exists."""
    try:
        import ale_py  # type: ignore

        ale = ale_py.ALEInterface()
        ale.setInt("random_seed", seed)
        ale.setFloat("repeat_action_probability", 0.0)  # sticky actions off
        ale.setInt("max_num_frames_per_episode", max_num_frames)
        rom = ale_py.roms.get_rom_path(game.replace("-", "_"))
        ale.loadROM(rom)
        return ale, list(ale.getMinimalActionSet())
    except ImportError:
        pass
    try:
        import atari_py  # type: ignore

        ale = atari_py.ALEInterface()
        ale.setInt(b"random_seed", seed)
        ale.setFloat(b"repeat_action_probability", 0.0)
        ale.setInt(b"max_num_frames_per_episode", max_num_frames)
        ale.loadROM(atari_py.get_game_path(game.replace("-", "_")))
        return ale, list(ale.getMinimalActionSet())
    except ImportError:
        raise ImportError(
            "AtariEnv needs `ale_py` (or legacy `atari_py`) plus game ROMs; "
            "neither is installed. Use env_type='pong-sim' for the ALE-free "
            "Pong pipeline."
        ) from None


class AtariEnv(Env):
    def __init__(self, env_params, process_ind: int = 0):
        super().__init__(env_params, process_ind)
        self.norm_val = 255.0
        self.hist_len = env_params.state_cha
        self.ale, self.actions = _load_ale(
            env_params.game, self.seed, env_params.early_stop)
        self.frame_stack: deque = deque(maxlen=self.hist_len)
        self.lives = 0
        self.just_died = False

    @property
    def state_shape(self) -> Tuple[int, ...]:
        return (self.hist_len, self.params.state_hei, self.params.state_wid)

    @property
    def action_space(self) -> DiscreteSpace:
        return DiscreteSpace(len(self.actions))

    # -- frame pipeline -----------------------------------------------------

    def _screen(self) -> np.ndarray:
        gray = self.ale.getScreenGrayscale()
        gray = np.asarray(gray).reshape(self.ale.getScreenDims()[::-1] if
                                        gray.ndim == 1 else gray.shape)
        # first-party bilinear resize (utils/image.py; the reference used
        # cv2.INTER_LINEAR, reference atari_env.py:56) — no cv2 dependency
        return resize_bilinear(
            gray.squeeze().astype(np.uint8),
            (self.params.state_hei, self.params.state_wid))

    def _stacked(self) -> np.ndarray:
        return np.stack(self.frame_stack)

    # -- env surface --------------------------------------------------------

    def _reset(self) -> np.ndarray:
        if self.training and self.just_died and not self.ale.game_over():
            # life lost mid-game: resume with a single no-op, keep the stack
            # (reference atari_env.py:115-121)
            self.just_died = False
            self.ale.act(0)
            self.frame_stack.append(self._screen())
        else:
            self.ale.reset_game()
            for _ in range(int(self.rng.integers(0, 31))):
                self.ale.act(0)
                if self.ale.game_over():
                    self.ale.reset_game()
            self.frame_stack.clear()
            first = self._screen()
            for _ in range(self.hist_len):
                self.frame_stack.append(first)
            self.just_died = False
        self.lives = self.ale.lives()
        return self._stacked()

    def _step(self, action) -> Tuple[np.ndarray, float, bool, Dict[str, Any]]:
        ale_action = self.actions[int(action)]
        reward = 0.0
        prev = None
        n = self.params.action_repetition
        for k in range(n):
            reward += self.ale.act(ale_action)
            if k == n - 2:
                prev = self._screen()
            if self.ale.game_over():
                # stop the action-repeat at terminal — the reference never
                # acts past game over (reference atari_env.py:101-103)
                break
        frame = self._screen()
        if prev is not None:
            frame = np.maximum(frame, prev)
        self.frame_stack.append(frame)

        terminal = bool(self.ale.game_over())
        info: Dict[str, Any] = {"lives": self.ale.lives()}
        if self.training:
            new_lives = self.ale.lives()
            if 0 < new_lives < self.lives:
                # life-loss-as-terminal (reference atari_env.py:106-112)
                terminal = True
                self.just_died = True
            self.lives = new_lives
        return self._stacked(), float(reward), terminal, info


# The canonical 57-game Atari benchmark suite (ALE game ids), for sweep
# tooling over CONFIGS row 11 (BASELINE.md tracked config 3: "DQN Breakout
# + Atari-57, 256 actors") — pass any of these as ``game``.
ATARI57 = (
    "alien", "amidar", "assault", "asterix", "asteroids", "atlantis",
    "bank_heist", "battle_zone", "beam_rider", "berzerk", "bowling",
    "boxing", "breakout", "centipede", "chopper_command", "crazy_climber",
    "defender", "demon_attack", "double_dunk", "enduro", "fishing_derby",
    "freeway", "frostbite", "gopher", "gravitar", "hero", "ice_hockey",
    "jamesbond", "kangaroo", "krull", "kung_fu_master",
    "montezuma_revenge", "ms_pacman", "name_this_game", "phoenix",
    "pitfall", "pong", "private_eye", "qbert", "riverraid", "road_runner",
    "robotank", "seaquest", "skiing", "solaris", "space_invaders",
    "star_gunner", "surround", "tennis", "time_pilot", "tutankham",
    "up_n_down", "venture", "video_pinball", "wizard_of_wor",
    "yars_revenge", "zaxxon",
)
