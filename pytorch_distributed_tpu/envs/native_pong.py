"""ctypes wrapper for the C++ batched Pong stepper (native/pong_batch.cpp).

``NativePongVectorEnv`` is a drop-in for ``envs.vector.VectorEnv`` wrapping
N ``PongSimEnv`` instances: same observation pipeline (84x84 uint8,
action-repeat + 2-frame maxpool, hist-length stack), same auto-reset
semantics (reset obs returned, true terminal obs in ``info["final_obs"]``),
same per-slot seeding (env j of actor i gets slot ``i*N + j``,
factory.build_env_vector).  One C call steps all N games — the actor hot
loop (reference dqn_actor.py:84-85; SURVEY.md §3.2) spends its env time in
native code instead of N Python ``step()`` round-trips.

Falls back at the factory layer: ``build_env_vector`` uses this class only
when the toolchain builds the library (native/build.py), else the Python
``VectorEnv``.
"""

from __future__ import annotations

import ctypes
from typing import Any, Dict, List, Tuple

import numpy as np

from pytorch_distributed_tpu.envs.base import DiscreteSpace

_lib = None


def get_lib() -> ctypes.CDLL:
    """Build-on-import; raises NativeBuildError when the toolchain is
    unusable (callers fall back to the Python vector env)."""
    global _lib
    if _lib is None:
        from native.build import load_library

        lib = load_library("pong_batch")
        lib.pong_create.restype = ctypes.c_void_p
        lib.pong_create.argtypes = [
            ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64)]
        lib.pong_destroy.argtypes = [ctypes.c_void_p]
        lib.pong_reset.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
        lib.pong_step.argtypes = [ctypes.c_void_p] + [ctypes.c_void_p] * 7
        lib.pong_state_size.restype = ctypes.c_int
        lib.pong_get_state.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                       ctypes.POINTER(ctypes.c_double)]
        lib.pong_set_state.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                       ctypes.POINTER(ctypes.c_double)]
        lib.pong_render.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                    ctypes.c_void_p]
        _lib = lib
    return _lib


def _ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.c_void_p)


class NativePongVectorEnv:
    """N Pong games stepped as one batch in native code."""

    def __init__(self, env_params, process_ind: int, num_envs: int):
        self.params = env_params
        self.num_envs = num_envs
        self.hist = env_params.state_cha
        self.norm_val = 255.0
        self.training = True
        self._lib = get_lib()
        seeds = (ctypes.c_int64 * num_envs)(*[
            env_params.seed + process_ind * num_envs + j
            for j in range(num_envs)])
        self._h = self._lib.pong_create(
            num_envs, self.hist, env_params.action_repetition,
            env_params.early_stop or 0, seeds)
        if not self._h:
            raise RuntimeError("pong_create failed")
        n, h = num_envs, self.hist
        self._obs = np.empty((n, h, 84, 84), dtype=np.uint8)
        self._final = np.empty((n, h, 84, 84), dtype=np.uint8)
        self._rewards = np.empty(n, dtype=np.float32)
        self._terminals = np.empty(n, dtype=np.uint8)
        self._truncateds = np.empty(n, dtype=np.uint8)
        self._scores = np.empty((n, 2), dtype=np.int32)

    def __del__(self):
        h = getattr(self, "_h", None)
        if h:
            self._lib.pong_destroy(h)
            self._h = None

    # -- VectorEnv surface --------------------------------------------------

    def train(self) -> None:
        self.training = True

    def eval(self) -> None:
        self.training = False

    @property
    def state_shape(self) -> Tuple[int, ...]:
        return (self.hist, 84, 84)

    @property
    def action_space(self) -> DiscreteSpace:
        return DiscreteSpace(6)

    def reset(self) -> np.ndarray:
        self._lib.pong_reset(self._h, _ptr(self._obs))
        return self._obs.copy()

    def step(self, actions) -> Tuple[np.ndarray, np.ndarray, np.ndarray,
                                     List[Dict[str, Any]]]:
        acts = np.ascontiguousarray(np.asarray(actions, dtype=np.int32))
        assert acts.shape == (self.num_envs,)
        assert ((acts >= 0) & (acts < 6)).all(), \
            f"actions out of range [0, 6): {acts}"
        self._lib.pong_step(self._h, _ptr(acts), _ptr(self._obs),
                            _ptr(self._rewards), _ptr(self._terminals),
                            _ptr(self._truncateds), _ptr(self._final),
                            _ptr(self._scores))
        infos: List[Dict[str, Any]] = []
        for i in range(self.num_envs):
            info: Dict[str, Any] = {"score": tuple(self._scores[i])}
            if self._terminals[i]:
                info["final_obs"] = self._final[i].copy()
                if self._truncateds[i]:
                    info["truncated"] = True
            infos.append(info)
        return (self._obs.copy(), self._rewards.copy(),
                self._terminals.astype(bool), infos)

    # -- test / checkpoint hooks --------------------------------------------

    def get_state(self, i: int) -> np.ndarray:
        buf = (ctypes.c_double * self._lib.pong_state_size())()
        self._lib.pong_get_state(self._h, i, buf)
        return np.asarray(buf, dtype=np.float64).copy()

    def set_state(self, i: int, state: np.ndarray) -> None:
        # a shorter vector (e.g. the 8 dynamics entries) keeps the current
        # episode clock / RNG stream; the full 10-entry vector restores all
        cur = self.get_state(i)
        cur[:len(state)] = np.asarray(state, dtype=np.float64)
        buf = (ctypes.c_double * len(cur))(*cur)
        self._lib.pong_set_state(self._h, i, buf)

    def render_frame(self, i: int) -> np.ndarray:
        frame = np.empty((84, 84), dtype=np.uint8)
        self._lib.pong_render(self._h, i, _ptr(frame))
        return frame
