"""Device-resident vectorized envs: the pure-JAX environment protocol.

The actor plane's throughput ceiling so far has been the HOST env step:
``envs/vector.py`` advances N Python simulators one ``step()`` at a time
(and ``native_pong.py`` one C call at a time), so every tick pays N
Python frames of work and the policy's device dispatch round-trips the
obs through host memory.  BENCH_r02/r03 measured 449 env frames/s from
that plane against ~93k updates/s of learner enqueue capacity — the chip
idles waiting for experience.  Podracer (Hessel et al. 2021) names the
fix: put the environments ON the device as pure functions and advance
thousands of them per XLA dispatch, fused with the policy step (the
Sebulba/Anakin actor plane).

This module supplies:

- ``DeviceEnv`` — the protocol: an env family as three pure functions
  (``init``/``step``/``observe``) over a batched state pytree, plus the
  static metadata the models/replay need.  ``step`` applies auto-reset
  internally and ALWAYS returns the true post-step observation
  (``final_obs``) next to the reset one, so the n-step assembler sees
  real episode boundaries — the same contract ``envs/vector.py``
  documents with its ``info["final_obs"]`` stash.

- ``make_device_pong`` — a Pong implementation ported op-for-op from
  ``envs/pong_sim.py`` (same 84x84 uint8 pipeline: action-repeat with a
  2-frame maxpool, hist-length stack, rate-limited tracker opponent,
  scoring to 21, ``early_stop`` truncation).  The kernel is written
  once over an array-module parameter ``xp`` so the SAME code runs as
  jitted jnp on the device and as plain numpy on the host — the host
  execution is the parity oracle (tests/test_device_env.py): f32 numpy
  and f32 XLA must agree bit-for-bit over full episodes, and the f64
  numpy run must agree bit-for-bit with the real ``PongSimEnv`` class
  once its RNG draws are replayed (see ``CounterRng``).

- ``DevicePongVectorEnv`` — a drop-in for ``envs.vector.VectorEnv``
  driving the jitted device step from the host loop, so the existing
  inline/pipelined actor backends (and the parity tests) can run
  against the device env without the fused rollout engine.

Randomness: the host sim draws from numpy's PCG64, which no XLA program
can reproduce.  The device env instead derives every draw from a
counter-based uint32 hash of ``(slot_seed, draw_index)`` (splitmix32
avalanche) — a pure function both numpy and jnp evaluate identically,
and one the parity oracle can replay into the host ``PongSimEnv``
class.  Slot seeding follows the fleet contract: env j of actor i takes
slot ``seed + i*N + j`` (factory.build_env_vector), so backend choice
never changes the seed stream.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, List, NamedTuple, Tuple

import numpy as np

from pytorch_distributed_tpu.envs.base import DiscreteSpace
from pytorch_distributed_tpu.envs.pong_sim import (
    BALL_SPEED_X, ENEMY_SPEED, WIN_SCORE,
)

# ---------------------------------------------------------------------------
# counter-based RNG: a pure function of (slot_seed, draw_index)
# ---------------------------------------------------------------------------

_MIX1 = 0x7FEB352D
_MIX2 = 0x846CA68B
_SEED_GOLD = 0x9E3779B9  # Weyl constant decorrelating adjacent slot seeds


def counter_mix(seed, count, xp=np):
    """splitmix32-style avalanche of ``seed ^ (count * golden)`` on
    uint32 arrays — identical wraparound semantics in numpy and jnp."""
    u = np.uint32
    x = xp.asarray(seed, np.uint32) ^ (
        xp.asarray(count, np.uint32) * u(_SEED_GOLD))
    x = (x ^ (x >> u(16))) * u(_MIX1)
    x = (x ^ (x >> u(15))) * u(_MIX2)
    return x ^ (x >> u(16))


def counter_uniform(seed, count, lo, hi, xp=np, dtype=np.float32):
    """``lo + (hi - lo) * u`` with ``u`` in [0, 1) from the top 24 hash
    bits (exactly representable in f32, so the f32 and f64 runs see the
    same u)."""
    u = (counter_mix(seed, count, xp) >> np.uint32(8)).astype(dtype) \
        * dtype(1.0 / (1 << 24))
    return dtype(lo) + (dtype(hi) - dtype(lo)) * u


class CounterRng:
    """Host-side shim with the numpy-Generator surface ``PongSimEnv``
    draws from (``uniform``, ``random``), replaying the device env's
    counter stream — patched into a ``PongSimEnv`` instance by the
    parity oracle so the REAL host class walks the exact episode the
    device env walks."""

    def __init__(self, seed: int):
        self.seed = np.uint32(seed)
        self.count = 0

    def uniform(self, lo: float, hi: float) -> float:
        self.count += 1
        return float(counter_uniform(
            np.asarray([self.seed], np.uint32),
            np.asarray([self.count], np.uint32),
            lo, hi, xp=np, dtype=np.float64)[0])

    def random(self) -> float:
        return self.uniform(0.0, 1.0)


# ---------------------------------------------------------------------------
# the protocol
# ---------------------------------------------------------------------------

class PongState(NamedTuple):
    """Batched per-env state (leading dim N everywhere)."""

    player_y: Any
    enemy_y: Any
    ball_x: Any
    ball_y: Any
    ball_vx: Any
    ball_vy: Any
    score_enemy: Any     # (N,) int32
    score_player: Any    # (N,) int32
    episode_steps: Any   # (N,) int32
    rng_count: Any       # (N,) uint32 draw counter
    seed: Any            # (N,) uint32 slot seed (constant)
    stack: Any           # (N, hist, 84, 84) uint8 current obs


class StepOut(NamedTuple):
    """One batched env step.  ``obs`` is the post-step observation with
    auto-reset applied; ``final_obs`` is the TRUE post-step stack (the
    terminal frames where ``terminal``, identical to ``obs``
    elsewhere) — the ``info["final_obs"]`` of the host vector env as a
    dense array."""

    obs: Any           # (N, hist, 84, 84) uint8
    final_obs: Any     # (N, hist, 84, 84) uint8
    reward: Any        # (N,) f32
    terminal: Any      # (N,) bool
    truncated: Any     # (N,) bool
    score: Any         # (N, 2) int32 (enemy, player)


@dataclass(frozen=True)
class DeviceEnv:
    """An env family as pure functions over a batched state pytree.

    ``init()`` builds the reset state for all N envs; ``step(state,
    actions)`` advances every env one agent step (auto-reset inside);
    ``observe(state)`` reads the current observation without stepping.
    ``step`` must be jit/vmap/scan-safe: no host callbacks, fixed
    shapes, randomness from counters carried in the state.
    """

    num_envs: int
    state_shape: Tuple[int, ...]
    num_actions: int
    norm_val: float
    init: Callable[[], Any]
    step: Callable[[Any, Any], Tuple[Any, StepOut]]
    observe: Callable[[Any], Any]


# ---------------------------------------------------------------------------
# Pong, transcribed from envs/pong_sim.py
#
# Every float constant below is the evaluated form of the pong_sim
# expression it mirrors (PADDLE_H/2 = 5.0, H - PADDLE_H/2 = 79.0,
# BALL/2 = 1.0, 2*(H - BALL/2) = 166.0, PLAYER_X - PADDLE_W = 76.0,
# ENEMY_X + PADDLE_W = 6.0).  The transcription must stay op-for-op:
# the parity oracle compares the f64 numpy run against the real
# PongSimEnv bit-for-bit (tests/test_device_env.py).
# ---------------------------------------------------------------------------

def _tick(s: PongState, move, xp, f):
    """One raw emulator frame (pong_sim.PongSimEnv._tick); ``f`` is the
    physics scalar type (np.float32 / np.float64)."""
    py = xp.clip(s.player_y + move, f(5.0), f(79.0))
    err = s.ball_y - s.enemy_y
    ey = xp.clip(s.enemy_y + xp.clip(err, f(-ENEMY_SPEED), f(ENEMY_SPEED)),
                 f(5.0), f(79.0))
    bx = s.ball_x + s.ball_vx
    by = s.ball_y + s.ball_vy
    bvy = s.ball_vy
    lo = by < f(1.0)
    hi = by > f(83.0)
    by = xp.where(lo, f(2.0) - by, xp.where(hi, f(166.0) - by, by))
    bvy = xp.where(lo | hi, -bvy, bvy)
    bvx = s.ball_vx
    # paddle collisions: conditions from PRE-collision bvx/bx (the
    # host's if/elif — exclusive because they need opposite bvx signs)
    hitp = (bvx > 0) & (bx >= f(76.0)) & (xp.abs(by - py) <= f(6.0))
    hite = (~hitp) & (bvx < 0) & (bx <= f(6.0)) \
        & (xp.abs(by - ey) <= f(6.0))
    english_p = xp.clip(bvy + (f(0.5) * (by - py)) / f(5.0),
                        f(-2.0), f(2.0))
    english_e = xp.clip(bvy + (f(0.5) * (by - ey)) / f(5.0),
                        f(-2.0), f(2.0))
    bvy = xp.where(hitp, english_p, xp.where(hite, english_e, bvy))
    bx = xp.where(hitp, f(76.0), xp.where(hite, f(6.0), bx))
    bvx = xp.where(hitp | hite, -bvx, bvx)
    # scoring (the host's two early-return ifs; exclusive by bx's sign)
    p_scores = bx < f(0.0)           # player point, serve direction -1
    e_scores = bx > f(84.0)          # enemy point, serve direction +1
    scored = p_scores | e_scores
    reward = xp.where(p_scores, f(1.0),
                      xp.where(e_scores, f(-1.0), f(0.0)))
    direction = xp.where(p_scores, f(-1.0), f(1.0))
    u = np.uint32
    new_by = counter_uniform(s.seed, s.rng_count + u(1), 20.0, 64.0,
                             xp, f)
    new_bvy = counter_uniform(s.seed, s.rng_count + u(2), -1.2, 1.2,
                              xp, f)
    bx = xp.where(scored, f(42.0), bx)
    by = xp.where(scored, new_by, by)
    bvx = xp.where(scored, f(BALL_SPEED_X) * direction, bvx)
    bvy = xp.where(scored, new_bvy, bvy)
    count = (s.rng_count + xp.where(scored, u(2), u(0))).astype(np.uint32)
    one = np.int32(1)
    zero = np.int32(0)
    score_p = s.score_player + xp.where(p_scores, one, zero)
    score_e = s.score_enemy + xp.where(e_scores, one, zero)
    return s._replace(player_y=py, enemy_y=ey, ball_x=bx, ball_y=by,
                      ball_vx=bvx, ball_vy=bvy, score_enemy=score_e,
                      score_player=score_p, rng_count=count), reward


def _row_band(center, half, value, ys, xp, f):
    """(N, 84) uint8 row band [round(c-half), round(c+half)) at
    ``value`` — the vspan slice of pong_sim._draw as a mask."""
    lo = xp.round(center - f(half))[:, None]
    hi = xp.round(center + f(half))[:, None]
    return ((ys >= lo) & (ys < hi)).astype(np.uint8) * np.uint8(value)


def _ball_overlay(ball_x, ball_y, ys, xp):
    br = ((ys >= xp.round(ball_y)[:, None] - 1)
          & (ys < xp.round(ball_y)[:, None] + 1)).astype(np.uint8)
    bc = ((ys >= xp.round(ball_x)[:, None] - 1)
          & (ys < xp.round(ball_x)[:, None] + 1)).astype(np.uint8)
    return br[:, :, None] * (bc * np.uint8(236))[:, None, :]


def _static_cols(xp):
    cols = xp.arange(84)
    ecol = ((cols >= 2) & (cols < 4)).astype(np.uint8)[None, :]
    pcol = ((cols >= 78) & (cols < 80)).astype(np.uint8)[None, :]
    return ecol, pcol


def _render(s: PongState, xp, f):
    """(N, 84, 84) uint8 frame == pong_sim._draw.  The host draws
    background (35), enemy (130), player (150), ball (236) in overwrite
    order; the values are increasing, so overwrite == pixelwise max and
    the frame is the max of four mask contributions."""
    ys = xp.arange(84).astype(f)[None, :]
    er = _row_band(s.enemy_y, 5.0, 130, ys, xp, f)
    pr = _row_band(s.player_y, 5.0, 150, ys, xp, f)
    ecol, pcol = _static_cols(xp)
    frame = xp.maximum(er[:, :, None] * ecol[:, None, :],
                       pr[:, :, None] * pcol[:, None, :])
    return xp.maximum(
        xp.maximum(frame, _ball_overlay(s.ball_x, s.ball_y, ys, xp)),
        np.uint8(35))


def _render_union(s2: PongState, s3: PongState, xp, f):
    """max(render(s2), render(s3)) in ONE pass — the action-repeat
    maxpool (pong_sim._step's np.maximum over the last two raw frames)
    computed as a render over unioned masks.  Exact because each frame
    is a pixelwise max of its contributions (see _render), so the max
    of two frames is the max over both frames' contributions."""
    ys = xp.arange(84).astype(f)[None, :]
    er = xp.maximum(_row_band(s2.enemy_y, 5.0, 130, ys, xp, f),
                    _row_band(s3.enemy_y, 5.0, 130, ys, xp, f))
    pr = xp.maximum(_row_band(s2.player_y, 5.0, 150, ys, xp, f),
                    _row_band(s3.player_y, 5.0, 150, ys, xp, f))
    ecol, pcol = _static_cols(xp)
    frame = xp.maximum(er[:, :, None] * ecol[:, None, :],
                       pr[:, :, None] * pcol[:, None, :])
    ball = xp.maximum(_ball_overlay(s2.ball_x, s2.ball_y, ys, xp),
                      _ball_overlay(s3.ball_x, s3.ball_y, ys, xp))
    return xp.maximum(xp.maximum(frame, ball), np.uint8(35))


def _reset_state(seed, count, n: int, hist: int, xp, f) -> PongState:
    """Fresh-episode state for all N envs (pong_sim._reset): centered
    paddles, serve direction from one draw, ball y/vy from two more.
    ``count`` is the per-env draw counter BEFORE the reset draws."""
    u = np.uint32
    direction = xp.where(
        counter_uniform(seed, count + u(1), 0.0, 1.0, xp, f) < f(0.5),
        f(1.0), f(-1.0))
    by = counter_uniform(seed, count + u(2), 20.0, 64.0, xp, f)
    bvy = counter_uniform(seed, count + u(3), -1.2, 1.2, xp, f)
    # distinct arrays per field: a shared zeros object would alias
    # donated buffers once this state rides a donated rollout carry
    zi = lambda: xp.zeros((n,), np.int32)
    s = PongState(
        player_y=xp.full((n,), f(42.0)), enemy_y=xp.full((n,), f(42.0)),
        ball_x=xp.full((n,), f(42.0)), ball_y=by,
        ball_vx=f(BALL_SPEED_X) * direction, ball_vy=bvy,
        score_enemy=zi(), score_player=zi(), episode_steps=zi(),
        rng_count=(count + u(3)).astype(np.uint32),
        seed=xp.asarray(seed, np.uint32),
        stack=None)
    # reset-frame fast path: both paddles sit at the centered 42.0 and
    # the ball at x=42.0, so the paddle contribution is one CONSTANT
    # (1, 84, 84) base shared by all envs and only the ball overlay is
    # per-env — the step pays one cheap pass here instead of a full
    # render (auto-reset computes this branch every tick for all envs).
    # Bit-equal to _render(s): same contributions, max is order-free.
    ys = xp.arange(84).astype(f)[None, :]
    center = xp.full((1,), f(42.0))
    er = _row_band(center, 5.0, 130, ys, xp, f)
    pr = _row_band(center, 5.0, 150, ys, xp, f)
    ecol, pcol = _static_cols(xp)
    base = xp.maximum(er[:, :, None] * ecol[:, None, :],
                      pr[:, :, None] * pcol[:, None, :])
    first = xp.maximum(
        xp.maximum(base, _ball_overlay(s.ball_x, s.ball_y, ys, xp)),
        np.uint8(35))
    # host _reset fills the whole stack with the first frame
    rep = xp.broadcast_to(first[:, None], (n, hist, 84, 84))
    return s._replace(stack=rep + np.uint8(0))


def make_device_pong(env_params, slot_seeds, xp=None,
                     dtype=np.float32) -> DeviceEnv:
    """Build the Pong ``DeviceEnv`` for the given env slot seeds.

    ``xp=jax.numpy`` (default) gives the device env; ``xp=numpy`` gives
    the bit-identical host oracle the parity drill runs against.
    ``dtype`` is the physics dtype: f32 in production (TPU-native), f64
    for the oracle leg that must match the f64 host ``PongSimEnv``.
    """
    if xp is None:
        import jax.numpy as jnp

        xp = jnp
    f = np.dtype(dtype).type
    n = len(slot_seeds)
    hist = int(env_params.state_cha)
    rep = int(env_params.action_repetition)
    early_stop = int(env_params.early_stop or 0)
    seeds = np.asarray(slot_seeds, np.uint32)

    def init():
        return _reset_state(xp.asarray(seeds),
                            xp.zeros((n,), np.uint32), n, hist, xp, f)

    def observe(state: PongState):
        return state.stack

    def step(state: PongState, actions):
        a = xp.asarray(actions)
        move = xp.where((a == 2) | (a == 4), f(-2.0),
                        xp.where((a == 3) | (a == 5), f(2.0), f(0.0)))
        reward = xp.zeros((n,), dtype)
        s = state
        states = []
        for _k in range(rep):
            s, r = _tick(s, move, xp, f)
            reward = reward + r
            states.append(s)
        if rep >= 2:
            frame = _render_union(states[rep - 2], states[rep - 1], xp, f)
        else:
            frame = _render(s, xp, f)
        true_stack = xp.concatenate([state.stack[:, 1:], frame[:, None]],
                                    axis=1)
        steps = s.episode_steps + np.int32(1)
        game_over = xp.maximum(s.score_enemy, s.score_player) >= WIN_SCORE
        if early_stop:
            truncated = steps >= early_stop
        else:
            truncated = xp.zeros((n,), bool)
        terminal = game_over | truncated
        score = xp.stack([s.score_enemy, s.score_player], axis=1)
        # auto-reset: the returned obs for terminal envs is the fresh
        # episode's first stack; the true terminal stack rides final_obs
        fresh = _reset_state(s.seed, s.rng_count, n, hist, xp, f)

        def sel(a_new, a_old):
            t = terminal
            extra = a_old.ndim - t.ndim
            if extra:
                t = t.reshape(t.shape + (1,) * extra)
            return xp.where(t, a_new, a_old)

        s = s._replace(episode_steps=steps, stack=true_stack)
        nxt = PongState(*(sel(f_new, f_old)
                          for f_new, f_old in zip(fresh, s)))
        nxt = nxt._replace(seed=state.seed)  # constant; keep dtype exact
        return nxt, StepOut(obs=nxt.stack, final_obs=true_stack,
                            reward=reward.astype(np.float32),
                            terminal=terminal, truncated=truncated,
                            score=score)

    return DeviceEnv(num_envs=n, state_shape=(hist, 84, 84),
                     num_actions=6, norm_val=255.0,
                     init=init, step=step, observe=observe)


# ---------------------------------------------------------------------------
# factory surface
# ---------------------------------------------------------------------------

# device env families (family name -> builder) and which env_type each
# family implements — the family is a device RE-IMPLEMENTATION of a
# host env_type, so the two must always agree (a Pong fleet behind a
# cartpole learner config would train on the wrong environment)
DEVICE_ENV_FAMILIES: Dict[str, Callable] = {
    "pong": make_device_pong,
}
_ENV_TYPE_FAMILY: Dict[str, str] = {
    "pong-sim": "pong",
}


def resolve_device_env_family(env_params) -> str | None:
    """The device family for this env config, or None when the
    env_type has no device implementation.  An explicit
    ``device_env_family`` must NAME the env_type's own family — it
    pins/documents the choice (and will disambiguate once an env_type
    has several implementations); it can never substitute a different
    game than the host config runs."""
    fam = _ENV_TYPE_FAMILY.get(env_params.env_type)
    explicit = getattr(env_params, "device_env_family", "auto") or "auto"
    if explicit == "auto":
        return fam
    if explicit != fam:
        raise ValueError(
            f"device_env_family={explicit!r} does not implement "
            f"env_type={env_params.env_type!r} (its device family is "
            f"{fam!r}; families: {sorted(DEVICE_ENV_FAMILIES)})")
    return fam


def device_env_supported(env_params) -> bool:
    """One gate shared by factory.resolve_actor_backend and the
    builders: does this env config have a device implementation?"""
    return resolve_device_env_family(env_params) is not None


def build_device_env(env_params, process_ind: int, num_envs: int,
                     xp=None, dtype=np.float32) -> DeviceEnv:
    """The device env for one actor slot, seeded on the fleet slot
    contract (env j of actor i takes slot ``seed + i*N + j`` — the same
    stream positions factory.build_env_vector hands the host
    backends)."""
    fam = resolve_device_env_family(env_params)
    if fam is None:
        raise ValueError(
            f"no device env implementation for env_type="
            f"{env_params.env_type!r} (families: "
            f"{sorted(DEVICE_ENV_FAMILIES)})")
    return DEVICE_ENV_FAMILIES[fam](
        env_params,
        [env_params.seed + process_ind * num_envs + j
         for j in range(num_envs)],
        xp=xp, dtype=dtype)


# ---------------------------------------------------------------------------
# host-facing wrapper (a VectorEnv drop-in)
# ---------------------------------------------------------------------------

class DevicePongVectorEnv:
    """Drive the jitted device Pong from a host loop — the
    ``VectorEnv`` surface (reset/step with ``final_obs``/``truncated``
    infos) over the device state, so inline/pipelined actors and the
    parity drill can run the device env without the fused engine."""

    def __init__(self, env_params, process_ind: int, num_envs: int):
        import jax

        self.params = env_params
        self.num_envs = num_envs
        self.norm_val = 255.0
        self.training = True
        self._env = build_device_env(env_params, process_ind, num_envs)
        self._step = jax.jit(self._env.step)
        self._state = None

    def train(self) -> None:
        self.training = True

    def eval(self) -> None:
        self.training = False

    @property
    def state_shape(self) -> Tuple[int, ...]:
        return self._env.state_shape

    @property
    def action_space(self) -> DiscreteSpace:
        return DiscreteSpace(self._env.num_actions)

    def reset(self) -> np.ndarray:
        self._state = self._env.init()
        return np.asarray(self._env.observe(self._state))

    def step(self, actions) -> Tuple[np.ndarray, np.ndarray, np.ndarray,
                                     List[Dict[str, Any]]]:
        acts = np.ascontiguousarray(np.asarray(actions, dtype=np.int32))
        assert acts.shape == (self.num_envs,)
        self._state, out = self._step(self._state, acts)
        obs = np.asarray(out.obs)
        reward = np.asarray(out.reward)
        terminal = np.asarray(out.terminal)
        truncated = np.asarray(out.truncated)
        score = np.asarray(out.score)
        final = None
        infos: List[Dict[str, Any]] = []
        for j in range(self.num_envs):
            info: Dict[str, Any] = {
                "score": tuple(int(v) for v in score[j])}
            if terminal[j]:
                if final is None:
                    final = np.asarray(out.final_obs)
                info["final_obs"] = final[j]
                if truncated[j]:
                    info["truncated"] = True
            infos.append(info)
        return obs, reward, terminal, infos
