"""Env abstraction.

Re-design of reference core/env.py:5-76.  The reference Env both steps the
emulator and assembles ``Experience`` records internally
(``_get_experience``, reference core/env.py:37-49); here the env exposes a
plain ``reset() -> obs`` / ``step(a) -> (obs, reward, terminal, info)``
surface and n-step experience assembly lives with the actor
(``ops/nstep.py``) where it can be unit-tested in isolation — the layer the
reference was missing (SURVEY.md §4).

Mode semantics match the reference: ``train()`` enables life-loss-as-
terminal + action repetition, ``eval()`` restores standard episode
boundaries (reference core/env.py:29-35).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Tuple

import numpy as np


@dataclass(frozen=True)
class DiscreteSpace:
    n: int

    def sample(self, rng: np.random.Generator) -> int:
        return int(rng.integers(self.n))


@dataclass(frozen=True)
class ContinuousSpace:
    """Box with symmetric policy convention: policies emit actions in
    [-1, 1]^dim and the env rescales to [low, high]."""

    dim: int
    low: float = -1.0
    high: float = 1.0

    def sample(self, rng: np.random.Generator) -> np.ndarray:
        return rng.uniform(-1.0, 1.0, size=(self.dim,)).astype(np.float32)

    def denormalize(self, action: np.ndarray) -> np.ndarray:
        a = np.clip(np.asarray(action, dtype=np.float32), -1.0, 1.0)
        return self.low + (a + 1.0) * 0.5 * (self.high - self.low)


class Env:
    """Base env.  Subclasses implement ``_reset``/``_step`` and set
    ``state_shape``, ``action_space``, ``norm_val``."""

    def __init__(self, env_params, process_ind: int = 0):
        self.params = env_params
        self.process_ind = process_ind
        # Per-instance seeding: ``process_ind`` is a global env SLOT —
        # actor i's env j passes slot i*N+j (factory.build_env_vector), the
        # evaluator a slot past the whole actor fleet.  Same intent as the
        # reference's ``seed + process_ind * num_envs_per_actor``
        # (reference core/envs/atari_env.py:16, where N is asserted 1);
        # slot-based avoids double-scaling when N > 1.
        self.seed = env_params.seed + process_ind
        self.rng = np.random.default_rng(self.seed)
        self.training = True
        # norm_val divides raw observations inside the model forward
        # (reference core/envs/atari_env.py:66-68 / core/model.py).
        self.norm_val: float = 1.0
        self._episode_steps = 0
        self.last_obs: Any = None
        self._renderer = None

    # -- mode switches (reference core/env.py:29-35) ------------------------

    def train(self) -> None:
        self.training = True

    def eval(self) -> None:
        self.training = False

    # -- public surface -----------------------------------------------------

    def reset(self) -> np.ndarray:
        self._episode_steps = 0
        obs = self._reset()
        self.last_obs = obs
        if self._renderer is not None:
            self._renderer.new_episode()
        return obs

    def step(self, action) -> Tuple[np.ndarray, float, bool, Dict[str, Any]]:
        obs, reward, terminal, info = self._step(action)
        self._episode_steps += 1
        if self.params.early_stop and self._episode_steps >= self.params.early_stop:
            terminal = True
            info.setdefault("truncated", True)
        self.last_obs = obs
        return obs, reward, terminal, info

    def attach_renderer(self, dumper) -> None:
        """Route ``render()`` frames to a utils/render.FrameDumper."""
        self._renderer = dumper

    def render(self) -> None:
        """Dump the newest frame through the attached renderer.  The
        reference displayed frames live via cv2.imshow (reference
        core/env.py:51-76); headless equivalent: PNG dump per step."""
        if self._renderer is not None and self.last_obs is not None:
            self._renderer.add(self.last_obs)

    # -- to implement -------------------------------------------------------

    @property
    def state_shape(self) -> Tuple[int, ...]:
        raise NotImplementedError

    @property
    def action_space(self):
        raise NotImplementedError

    def _reset(self) -> np.ndarray:
        raise NotImplementedError

    def _step(self, action) -> Tuple[np.ndarray, float, bool, Dict[str, Any]]:
        raise NotImplementedError
