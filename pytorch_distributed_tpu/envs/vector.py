"""Vector env: N independent env instances stepped as one batch.

The reference reserves ``num_envs_per_actor`` but asserts it to 1
(reference utils/options.py:32, core/envs/atari_env.py:15); here it is
real — the actor issues ONE jitted batched forward for all N envs, which is
how batch-1 inference latency (SURVEY.md §7 "hard parts") is amortised:
on a single-core host, moving from 1x batch-1 to 1x batch-16 inference
multiplies actor throughput ~50x (measured: 24 vs 1348 inferences/s on the
84x84 CNN).

Auto-reset semantics: when env j terminates, ``step`` returns the *reset*
observation for j (so the rollout continues seamlessly) and stashes the
true terminal observation in ``infos[j]["final_obs"]`` — the n-step
assembler must see the real episode boundary, not the reset frame.
"""

from __future__ import annotations

from typing import Any, Dict, List, Sequence, Tuple

import numpy as np


class VectorEnv:
    def __init__(self, envs: Sequence[Any]):
        assert envs, "need at least one env"
        self.envs = list(envs)
        self.num_envs = len(self.envs)

    # -- mode switches pass through ----------------------------------------

    def train(self) -> None:
        for e in self.envs:
            e.train()

    def eval(self) -> None:
        for e in self.envs:
            e.eval()

    @property
    def state_shape(self) -> Tuple[int, ...]:
        return self.envs[0].state_shape

    @property
    def action_space(self):
        return self.envs[0].action_space

    @property
    def norm_val(self) -> float:
        return self.envs[0].norm_val

    def reset(self) -> np.ndarray:
        return np.stack([e.reset() for e in self.envs])

    def step(self, actions) -> Tuple[np.ndarray, np.ndarray, np.ndarray,
                                     List[Dict[str, Any]]]:
        obs_out, rewards, terminals, infos = [], [], [], []
        for e, a in zip(self.envs, actions):
            obs, r, term, info = e.step(a)
            if term:
                info = dict(info)
                info["final_obs"] = obs
                obs = e.reset()
            obs_out.append(obs)
            rewards.append(r)
            terminals.append(term)
            infos.append(info)
        return (np.stack(obs_out),
                np.asarray(rewards, dtype=np.float32),
                np.asarray(terminals, dtype=bool),
                infos)
