"""Gym/Gymnasium adapter env (gated on the package being installed).

The reference's DDPG path targets gym MuJoCo-style continuous-control
tasks (BASELINE.md tracked configs: Pendulum/HalfCheetah/Humanoid); this
image ships neither gym nor MuJoCo, so the self-contained classic envs
(envs/classic.py) carry CI — this adapter is the production path on
machines that have gym installed: any Box/Discrete gym env becomes a
framework Env with the standard surface (slot seeding, [-1,1] action
normalisation for continuous spaces, truncation flagged for bootstrap
semantics).
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import numpy as np

from pytorch_distributed_tpu.envs.base import (
    ContinuousSpace, DiscreteSpace, Env,
)

# canonical game-name -> gym env id
GYM_IDS = {
    "pendulum": "Pendulum-v1",
    "halfcheetah": "HalfCheetah-v4",
    "humanoid": "Humanoid-v4",
    "hopper": "Hopper-v4",
    "walker2d": "Walker2d-v4",
    "ant": "Ant-v4",
    "cartpole": "CartPole-v1",
}


def _import_gym():
    try:
        import gymnasium as gym  # modern fork first

        return gym, True
    except ImportError:
        pass
    try:
        import gym  # legacy

        return gym, False
    except ImportError as e:
        raise ImportError(
            "env_type 'gym' needs gymnasium or gym installed; this image "
            "ships neither — use the self-contained envs (classic / "
            "pong-sim / fake) instead") from e


class GymEnv(Env):
    def __init__(self, env_params, process_ind: int = 0):
        super().__init__(env_params, process_ind)
        gym, self._modern = _import_gym()
        env_id = GYM_IDS.get(env_params.game, env_params.game)
        self._env = gym.make(env_id)
        if not self._modern and not hasattr(self._env, "seed"):
            # legacy-named gym >= 0.26 already speaks the gymnasium API
            # (reset(seed=...), 5-tuple step)
            self._modern = True
        self.norm_val = 1.0
        space = self._env.action_space
        if hasattr(space, "n"):
            self._space = DiscreteSpace(int(space.n))
        else:
            low = np.asarray(space.low, dtype=np.float32)
            high = np.asarray(space.high, dtype=np.float32)
            # symmetric [-1,1] policy convention; per-dim rescale happens in
            # _step (ContinuousSpace carries scalar low/high, gym may not be
            # uniform across dims)
            self._low, self._high = low, high
            self._space = ContinuousSpace(dim=int(np.prod(space.shape)),
                                          low=float(low.min()),
                                          high=float(high.max()))

    @property
    def state_shape(self) -> Tuple[int, ...]:
        return tuple(self._env.observation_space.shape)

    @property
    def action_space(self):
        return self._space

    def _reset(self) -> np.ndarray:
        if self._modern:
            obs, _info = self._env.reset(seed=self.seed + self._episode_seed())
        else:
            self._env.seed(self.seed + self._episode_seed())
            obs = self._env.reset()
        return np.asarray(obs, dtype=np.float32)

    def _episode_seed(self) -> int:
        # fresh-but-deterministic episode seeds from the slot stream
        return int(self.rng.integers(2 ** 20))

    def _step(self, action) -> Tuple[np.ndarray, float, bool, Dict[str, Any]]:
        if isinstance(self._space, ContinuousSpace):
            a = np.clip(np.asarray(action, np.float32).ravel(), -1.0, 1.0)
            action = self._low + (a + 1.0) * 0.5 * (self._high - self._low)
        else:
            action = int(np.asarray(action))
        if self._modern:
            obs, r, terminated, truncated, info = self._env.step(action)
            terminal = bool(terminated or truncated)
            info = dict(info)
            if truncated and not terminated:
                info["truncated"] = True  # bootstrap through time limits
        else:
            obs, r, terminal, info = self._env.step(action)
            info = dict(info)
            if info.get("TimeLimit.truncated"):
                info["truncated"] = True
        return np.asarray(obs, dtype=np.float32), float(r), terminal, info
