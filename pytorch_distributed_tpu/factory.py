"""Registry / factory — the plugin surface.

Equivalent of reference ``utils/factory.py``: type-string keyed dicts for
every pluggable component family (envs :34, memories :37, models :42,
actor/learner/evaluator/tester/logger process functions :22-31), plus the
builder helpers that ``main``/runtime use to turn an ``Options`` into live
objects (the dummy-env shape probe of reference main.py:23-31 lives here as
``probe_env``).  Divergences on purpose: ``dqn-mlp`` is registered (the
reference leaves it out, reference utils/factory.py:42-43), and the builders
return *functional* pieces — Flax modules, apply fns, optax transforms, pure
train-step closures — not stateful torch modules.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

from pytorch_distributed_tpu.config import Options
from pytorch_distributed_tpu.envs import (
    FakeChainEnv, PongSimEnv, make_classic_env,
)
from pytorch_distributed_tpu.envs.atari import AtariEnv
from pytorch_distributed_tpu.memory import (
    PrioritizedReplay, SharedReplay,
)
from pytorch_distributed_tpu.memory.feeder import QueueOwner

# ---------------------------------------------------------------------------
# Component dicts (reference utils/factory.py:22-43)
# ---------------------------------------------------------------------------

def _gym_env(env_params, process_ind: int = 0):
    from pytorch_distributed_tpu.envs.gym_adapter import GymEnv

    return GymEnv(env_params, process_ind)


EnvsDict: Dict[str, Callable] = {
    "atari": AtariEnv,            # reference factory.py:34 "atari"
    "fake": FakeChainEnv,         # test/smoke env (no reference equivalent)
    "classic": make_classic_env,  # cartpole / pendulum
    "pong-sim": PongSimEnv,       # ALE-free Pong clone
    "gym": _gym_env,              # gym/gymnasium adapter (gated on install)
}

MemoriesDict: Dict[str, Optional[Callable]] = {
    "shared": SharedReplay,           # reference factory.py:37 "shared"
    "native": None,                    # C++ lock-free ring (native_ring.py)
    "prioritized": PrioritizedReplay,  # finishes the reference's PER TODO
    "device": None,                    # HBM-resident ring (device_replay.py)
    "device-per": None,                # HBM prioritized ring (device_per.py)
    "sequence": None,                  # episode segments (sequence_replay.py)
    "device-sequence": None,           # HBM segment ring (device_sequence.py)
    "none": None,                      # reference factory.py:38
}

# model ctors bound in build_model below (they need probed shapes)
ModelTypes = ("dqn-cnn", "dqn-cnn-wide", "dqn-mlp", "ddpg-mlp",
              "drqn-mlp", "drqn-cnn", "dtqn-mlp", "dtqn-moe",
              "dtqn-pipe")


def _worker_dicts():
    # Imported lazily: agents modules import jax-heavy pieces and, under
    # spawn, child processes must be able to import this module before
    # choosing their jax platform.
    from pytorch_distributed_tpu.agents import actor as _actor
    from pytorch_distributed_tpu.agents import evaluator as _evaluator
    from pytorch_distributed_tpu.agents import learner as _learner
    from pytorch_distributed_tpu.agents import logger as _logger
    from pytorch_distributed_tpu.agents import recurrent_actor as _ractor
    from pytorch_distributed_tpu.agents import tester as _tester

    return {
        # reference utils/factory.py:22-31 (+ the r2d2 family extension)
        "actors": {"dqn": _actor.run_dqn_actor,
                   "ddpg": _actor.run_ddpg_actor,
                   "r2d2": _ractor.run_r2d2_actor},
        "learners": {"dqn": _learner.run_learner,
                     "ddpg": _learner.run_learner,
                     "r2d2": _learner.run_learner},
        "evaluators": {"dqn": _evaluator.run_evaluator,
                       "ddpg": _evaluator.run_evaluator,
                       "r2d2": _evaluator.run_evaluator},
        "testers": {"dqn": _tester.run_tester,
                    "ddpg": _tester.run_tester,
                    "r2d2": _tester.run_tester},
        "loggers": {"dqn": _logger.run_logger,
                    "ddpg": _logger.run_logger,
                    "r2d2": _logger.run_logger},
    }


def get_worker(role: str, agent_type: str) -> Callable:
    return _worker_dicts()[role + "s"][agent_type]


# ---------------------------------------------------------------------------
# Actor backend routing (ISSUE 4)
# ---------------------------------------------------------------------------

ACTOR_BACKENDS = ("inline", "pipelined", "batched", "device", "anakin")


def anakin_eligible(opt: Options) -> Tuple[bool, str]:
    """Whether this Options can run the co-located Anakin loop (ISSUE
    12): the dqn family, a pure-JAX env implementation, a device replay
    ring for the in-graph scatter, and NCHW ring storage (the fused
    rollout scatters raw rows; the NHWC ingest transpose lives on the
    host feed path it bypasses).  Returns ``(ok, reason)`` so callers
    can warn with the actual blocker."""
    from pytorch_distributed_tpu.envs.device_env import (
        device_env_supported,
    )

    if opt.agent_type != "dqn":
        return False, f"agent_type={opt.agent_type} (dqn only)"
    if not device_env_supported(opt.env_params):
        return False, (f"env_type={opt.env_params.env_type!r} has no "
                       f"device env implementation")
    if opt.memory_type not in ("device", "device-per"):
        return False, (f"memory_type={opt.memory_type!r} (the fused "
                       f"rollout scatters into a device ring: use "
                       f"'device' or 'device-per')")
    if device_ring_channels_last(opt):
        return False, ("device_channels_last=true (the in-graph scatter "
                       "writes NCHW rows)")
    return True, ""


def anakin_active(opt: Options) -> bool:
    """Whether the topology runs the co-located Anakin loop — the env
    fleet lives in the learner process, NO actor workers spawn, and the
    learner delegates to agents/anakin.run_anakin_learner.  One
    predicate shared by the topology (worker table), the learner (loop
    dispatch) and the fleet CLI so the pieces can never disagree."""
    return (getattr(opt.env_params, "actor_backend", "") == "anakin"
            and anakin_eligible(opt)[0])


def resolve_actor_backend(opt: Options, inference=None) -> str:
    """The actor hot-loop schedule actually run, from the
    ``env_params.actor_backend`` knob plus eligibility.

    Decided HERE — one gate shared by the runners (agents/actor.py,
    agents/recurrent_actor.py), the topology (runtime.py decides whether
    to build an InferenceServer from the same predicate via
    ``needs_inference_server``) and the fleet CLI — so the pieces can
    never disagree.  ``batched`` needs a co-located server handle
    (``inference``) and a flat family; ``device`` needs a dqn family
    whose env has a pure-JAX implementation (envs/device_env.py);
    anything else downgrades to ``pipelined`` with a loud warning
    rather than failing a whole fleet over a placement detail (remote
    DCN actor hosts have no server to reach)."""
    backend = getattr(opt.env_params, "actor_backend", "pipelined") \
        or "pipelined"
    if backend not in ACTOR_BACKENDS:
        raise ValueError(
            f"unknown actor_backend: {backend!r} (one of "
            f"{ACTOR_BACKENDS})")
    if backend == "batched":
        import warnings

        if opt.agent_type not in ("dqn", "ddpg"):
            warnings.warn(
                f"actor_backend=batched does not serve agent_type="
                f"{opt.agent_type} (per-env recurrent state stays "
                f"actor-side); falling back to pipelined", stacklevel=2)
            return "pipelined"
        if inference is None:
            warnings.warn(
                "actor_backend=batched but no InferenceClient was wired "
                "in (remote actor host, or a topology without the "
                "server); falling back to pipelined", stacklevel=2)
            return "pipelined"
    if backend == "anakin":
        import warnings

        ok, why = anakin_eligible(opt)
        if ok:
            return "anakin"
        # ineligible: fall through the device backend's own gates (the
        # config.py EnvParams contract: anakin downgrades to "device",
        # which itself may downgrade further to "pipelined")
        warnings.warn(
            f"actor_backend=anakin is not runnable here ({why}); "
            f"falling back to the split-process device backend",
            stacklevel=2)
        backend = "device"
    if backend == "device":
        import warnings

        from pytorch_distributed_tpu.envs.device_env import (
            device_env_supported,
        )

        if opt.agent_type != "dqn":
            warnings.warn(
                f"actor_backend=device serves the flat dqn family only "
                f"(got agent_type={opt.agent_type}); falling back to "
                f"pipelined", stacklevel=2)
            return "pipelined"
        if not device_env_supported(opt.env_params):
            warnings.warn(
                f"actor_backend=device but env_type="
                f"{opt.env_params.env_type!r} has no device env "
                f"implementation (envs/device_env.DEVICE_ENV_FAMILIES); "
                f"falling back to pipelined", stacklevel=2)
            return "pipelined"
    return backend


def build_device_env(opt: Options, process_ind: int, num_envs: int):
    """The pure-JAX env fleet for one device-backend actor slot
    (envs/device_env.py), seeded on the SAME slot contract as
    ``build_env_vector`` (env j of actor i takes slot ``seed + i*N +
    j``) so backend choice never changes the seed stream."""
    from pytorch_distributed_tpu.envs.device_env import (
        build_device_env as _build,
    )

    return _build(opt.env_params, process_ind, num_envs)


def needs_inference_server(opt: Options) -> bool:
    """Whether a topology should stand up the shared InferenceServer for
    its co-located actors (runtime.Topology)."""
    return (getattr(opt.env_params, "actor_backend", "") == "batched"
            and opt.agent_type in ("dqn", "ddpg"))


# ---------------------------------------------------------------------------
# Env probe + builders
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class EnvSpec:
    """What the models/replay need to know about an env — the product of the
    dummy-env probe (reference main.py:23-31 mutates Options with
    state_shape/action_dim/norm_val; here it is an explicit value)."""

    state_shape: Tuple[int, ...]
    discrete: bool
    num_actions: int        # discrete action count (0 if continuous)
    action_dim: int         # continuous action dim (0 if discrete)
    norm_val: float

    @property
    def action_shape(self) -> Tuple[int, ...]:
        return () if self.discrete else (self.action_dim,)

    @property
    def action_dtype(self):
        return np.int32 if self.discrete else np.float32


def build_env(opt: Options, process_ind: int = 0):
    ctor = EnvsDict[opt.env_type]
    return ctor(opt.env_params, process_ind)


def device_backend_active(opt: Options) -> bool:
    """Whether actor slots will run the device env fleet — the
    eligibility part of ``resolve_actor_backend``'s device gate,
    callable without triggering its downgrade warnings (the parent's
    prebuild must not warn about an inference server that is wired
    later)."""
    from pytorch_distributed_tpu.envs.device_env import (
        device_env_supported,
    )

    return (getattr(opt.env_params, "actor_backend", "") == "device"
            and opt.agent_type == "dqn"
            and device_env_supported(opt.env_params))


def _wants_native_pong(opt: Options) -> bool:
    """One gate for the native pong stepper, shared by the construction
    path (build_env_vector) and the parent-side prebuild (prebuild_native)
    so the two can't drift.  Device-backend runs skip it: no actor will
    dlopen the library (the env fleet is a pure-JAX program; the
    evaluator's single env never routes through the batched stepper)."""
    return (opt.env_type == "pong-sim"
            and getattr(opt.env_params, "native_env", True)
            and not device_backend_active(opt))


def build_env_vector(opt: Options, process_ind: int, num_envs: int):
    """N env instances as one batched VectorEnv; env j of actor i gets the
    distinct seed slot i*N + j (the reference's per-process scheme,
    reference atari_env.py:16, extended over the env axis).  For the
    Pong simulator the whole batch steps in one native C++ call
    (native/pong_batch.cpp) when the toolchain is available."""
    from pytorch_distributed_tpu.envs.vector import VectorEnv

    if _wants_native_pong(opt):
        try:
            from native.build import NativeBuildError
        except ImportError:  # native/ not shipped alongside the package
            NativeBuildError = OSError
        try:
            from pytorch_distributed_tpu.envs.native_pong import (
                NativePongVectorEnv,
            )

            return NativePongVectorEnv(opt.env_params, process_ind, num_envs)
        except (ImportError, OSError, NativeBuildError) as e:
            # no native package / toolchain / loadable .so: fall back.
            # Genuine wrapper bugs raise through — silently degrading a
            # fleet onto the ~6x-slower Python path is worse than failing.
            import warnings

            warnings.warn(f"native pong env unavailable ({e}); "
                          "falling back to Python VectorEnv", stacklevel=2)
    ctor = EnvsDict[opt.env_type]
    return VectorEnv([ctor(opt.env_params, process_ind * num_envs + j)
                      for j in range(num_envs)])


def prebuild_native(opt: Options) -> None:
    """Compile the native .so artifacts ONCE in the supervising parent
    before workers spawn — N actors racing identical `g++ -O3` builds of
    the same source is wasted work, and on a congested host some would hit
    the build timeout and silently drop onto the slower Python fallback.
    Children then just dlopen the cached library (native/build.py mtime
    check).  The parent build gets a generous timeout (it is the one that
    matters) and failures are reported loudly — the run still proceeds,
    each worker falling back with its own warning through the same
    gates."""
    import warnings

    def _prebuild(name: str, fallback: str) -> None:
        try:
            from native.build import build_library

            build_library(name, timeout=600.0)
        except Exception as e:  # noqa: BLE001 - degrade with a loud flag
            warnings.warn(f"parent-side native {name} build FAILED ({e}); "
                          f"{fallback}", stacklevel=3)

    if _wants_native_pong(opt):
        _prebuild("pong_batch",
                  "all workers will run the slower Python env")
    if opt.memory_type == "native":
        _prebuild("ring_buffer",
                  "workers fall back to the Python shared replay")
    if opt.env_type == "atari":
        _prebuild("image_ops",
                  "frame preprocessing falls back to numpy")


def probe_env(opt: Options) -> EnvSpec:
    """Instantiate a throwaway env to read shapes (reference main.py:23-31)."""
    env = build_env(opt, process_ind=0)
    space = env.action_space
    discrete = hasattr(space, "n")
    return EnvSpec(
        state_shape=tuple(env.state_shape),
        discrete=discrete,
        num_actions=space.n if discrete else 0,
        action_dim=0 if discrete else space.dim,
        norm_val=float(env.norm_val),
    )


# ---------------------------------------------------------------------------
# Model builders
# ---------------------------------------------------------------------------

def sequence_pack_frames(opt: Options) -> int:
    """Frame-pack factor C for sequence replay (0 = unpacked).

    C-stacked uint8 image segments ship every pixel C times; packing
    stores the de-duplicated frame sequence and the learner rebuilds
    stacks on device (memory/sequence_replay.py SegmentBuilder /
    ops/sequence_losses.py unpack_frame_stacks).  Decided HERE so the
    three parties — actor-side builders, the replay allocation, and the
    learner step — can never disagree on the wire format.  Only the
    pixel R2D2 family qualifies (the dtqn rows are low-dim)."""
    if (opt.memory_type in ("sequence", "device-sequence")
            and opt.model_type == "drqn-cnn"
            and opt.memory_params.state_dtype == "uint8"):
        return opt.env_params.state_cha
    return 0


def lstm_dim_of(opt: Options) -> int:
    """Stored-recurrent-state width for the configured model (the CNN
    variant floors at 512, matching its torso output; transformers store
    a 1-dim placeholder — their context is the segment window itself)."""
    if opt.model_type.startswith("dtqn"):
        return 1
    d = opt.model_params.lstm_dim
    return max(d, 512) if opt.model_type == "drqn-cnn" else d


def build_model(opt: Options, spec: EnvSpec):
    """Flax module for the configured model_type (reference factory.py:42-43
    + model ctor calls in main.py:44)."""
    import jax.numpy as jnp

    from pytorch_distributed_tpu.models import (
        DdpgMlpModel, DqnCnnModel, DqnMlpModel,
    )

    mp_ = opt.model_params
    if opt.model_type == "dqn-cnn":
        return DqnCnnModel(
            action_space=spec.num_actions,
            norm_val=spec.norm_val,
            orthogonal_init=mp_.orthogonal_init,
            compute_dtype=jnp.dtype(mp_.compute_dtype),
        )
    if opt.model_type == "dqn-cnn-wide":
        # the MXU-filling torso family (ISSUE 13): IMPALA-deep residual
        # stack with 128-multiple channel widths (models/dqn_cnn_wide.py)
        from pytorch_distributed_tpu.models.dqn_cnn_wide import (
            DqnCnnWideModel,
        )

        return DqnCnnWideModel(
            action_space=spec.num_actions,
            norm_val=spec.norm_val,
            width=mp_.cnn_wide_width,
            compute_dtype=jnp.dtype(mp_.compute_dtype),
        )
    if opt.model_type == "dqn-mlp":
        return DqnMlpModel(
            action_space=spec.num_actions,
            hidden_dim=mp_.hidden_dim,
            norm_val=spec.norm_val,
        )
    if opt.model_type == "ddpg-mlp":
        assert not spec.discrete, "ddpg-mlp needs a continuous action space"
        return DdpgMlpModel(action_dim=spec.action_dim,
                            norm_val=spec.norm_val)
    if opt.model_type == "drqn-mlp":
        from pytorch_distributed_tpu.models.drqn import DrqnMlpModel

        return DrqnMlpModel(action_space=spec.num_actions,
                            hidden_dim=mp_.hidden_dim,
                            lstm_dim=mp_.lstm_dim,
                            norm_val=spec.norm_val)
    if opt.model_type in ("dtqn-mlp", "dtqn-moe", "dtqn-pipe"):
        from pytorch_distributed_tpu.models.dtqn import DtqnMlpModel

        kw = dict(
            action_space=spec.num_actions,
            state_shape=spec.state_shape,
            # the acting window and the learner's T+1-long segments share
            # one positional table (acting uses leading-aligned windows so
            # positions match the training distribution exactly)
            window=opt.agent_params.seq_len + 1,
            dim=mp_.tf_dim,
            heads=mp_.tf_heads,
            depth=mp_.tf_depth,
            norm_val=spec.norm_val)
        if opt.model_type == "dtqn-moe":
            from pytorch_distributed_tpu.models.moe import DtqnMoeModel

            return DtqnMoeModel(
                num_experts=mp_.moe_experts,
                top_k=mp_.moe_top_k,
                capacity_factor=mp_.moe_capacity_factor,
                **kw)
        if opt.model_type == "dtqn-pipe":
            from pytorch_distributed_tpu.models.dtqn_pipeline import (
                DtqnPipelineModel,
            )

            return DtqnPipelineModel(**kw)
        return DtqnMlpModel(**kw)
    if opt.model_type == "drqn-cnn":
        from pytorch_distributed_tpu.models.drqn import DrqnCnnModel

        return DrqnCnnModel(action_space=spec.num_actions,
                            lstm_dim=lstm_dim_of(opt),
                            norm_val=spec.norm_val,
                            compute_dtype=jnp.dtype(mp_.compute_dtype))
    raise ValueError(f"unknown model_type: {opt.model_type}")


def example_obs(opt: Options, spec: EnvSpec, batch: int = 1):
    import jax.numpy as jnp

    dtype = jnp.uint8 if opt.memory_params.state_dtype == "uint8" \
        else jnp.float32
    return jnp.zeros((batch, *spec.state_shape), dtype=dtype)


def init_params(opt: Options, spec: EnvSpec, model, seed: int):
    import jax

    variables = model.init(jax.random.PRNGKey(seed), example_obs(opt, spec))
    # keep ONLY the param collection: flax init also captures any sown
    # collections (the MoE aux losses, models/moe.py AUX_COLLECTION), and
    # letting those scalars ride inside TrainState.params would make them
    # trainable free parameters seeding every later sow reduce
    return {"params": variables["params"]} if "params" in variables \
        else variables


def ddpg_applies(model) -> Tuple[Callable, Callable]:
    actor_apply = lambda p, o: model.apply(p, o, method=model.forward_actor)
    critic_apply = lambda p, o, a: model.apply(p, o, a,
                                               method=model.forward_critic)
    return actor_apply, critic_apply


# ---------------------------------------------------------------------------
# Train-step builder (the learner's pure XLA program)
# ---------------------------------------------------------------------------

def build_train_state_and_step(opt: Options, spec: EnvSpec, model, params,
                               mesh=None):
    """Returns (TrainState, step_fn) for the configured agent family, wiring
    optimizers/targets exactly as ops/losses.py documents.  ``mesh`` (the
    learner's device mesh) activates sequence-parallel paths: a DTQN model
    on a mesh with sp > 1 swaps its attention for ring attention."""
    from pytorch_distributed_tpu.ops.losses import (
        build_ddpg_train_step, build_ddpg_train_step_coupled,
        build_dqn_train_step, init_ddpg_train_state, init_train_state,
        make_optimizer,
    )
    from pytorch_distributed_tpu.utils import health

    ap = opt.agent_params
    decay = ap.steps if ap.lr_decay else 0
    # in-jit numeric guards (utils/health.py finite_guard): on by
    # default, killable via HealthParams.numeric_guards / the
    # TPU_APEX_HEALTH_NUMERIC_GUARDS env override
    guard = health.resolve(opt.health_params).numeric_guards
    if opt.agent_type == "r2d2":
        from pytorch_distributed_tpu.ops.sequence_losses import (
            build_drqn_train_step,
        )

        # transformers force burn_in 0 below, so only the LSTM family
        # needs a train window left after the burn-in prefix
        assert opt.model_type.startswith("dtqn") \
            or ap.burn_in < ap.seq_len, (
                f"burn_in={ap.burn_in} must leave a train window inside "
                f"seq_len={ap.seq_len} (did a --set seq_len override "
                f"forget burn_in?)")
        tx = make_optimizer(ap.lr, ap.clip_grad, ap.weight_decay,
                            lr_decay_steps=decay)
        state = init_train_state(params, tx)
        kw = dict(
            burn_in=ap.burn_in,
            nstep=ap.nstep,
            gamma=ap.gamma,
            enable_double=ap.enable_double,
            target_model_update=ap.target_model_update,
            rescale_values=ap.value_rescale,
            priority_eta=ap.priority_eta,
            guard=guard,
        )
        if opt.model_type.startswith("dtqn"):
            from pytorch_distributed_tpu.ops.sequence_losses import (
                build_dtqn_train_step,
            )

            # burn-in exists to refresh stale recurrent state; a
            # transformer has none, so every window position trains
            # (DTQN trains all timesteps) and acting never lands on a
            # positional slot without a training signal
            kw["burn_in"] = 0
            train_model = model
            sp = mesh.shape.get("sp", 1) if mesh is not None else 1
            pp = mesh.shape.get("pp", 1) if mesh is not None else 1
            if pp > 1:
                # pipeline parallelism: stage the stacked block family
                # over pp with the GPipe microbatch schedule
                # (parallel/pipeline.py); exclusive with sp — they split
                # the same transformer along different dims
                assert opt.model_type == "dtqn-pipe", (
                    f"pp_size>1 needs model_type dtqn-pipe "
                    f"(got {opt.model_type})")
                assert sp == 1, "pp and sp splits don't compose"
                from pytorch_distributed_tpu.parallel.pipeline import (
                    pipelined_window_apply,
                )

                window_apply = pipelined_window_apply(
                    model, mesh, opt.parallel_params.pp_microbatches)
                step = build_dtqn_train_step(window_apply, tx, **kw)
                return state, step
            if sp > 1:
                # long windows: shard the time axis over sp; attention
                # rides the ring or the Ulysses all-to-all (same params,
                # same math either way)
                from pytorch_distributed_tpu.models.dtqn import (
                    with_ring_attention, with_ulysses_attention,
                )

                assert (ap.seq_len + 1) % sp == 0, (
                    f"sequence-parallel DTQN needs window seq_len+1="
                    f"{ap.seq_len + 1} divisible by mesh sp={sp}")
                strategy = opt.parallel_params.sp_attention
                if strategy == "ulysses":
                    assert opt.model_params.tf_heads % sp == 0, (
                        f"sp_attention=ulysses needs tf_heads="
                        f"{opt.model_params.tf_heads} divisible by mesh "
                        f"sp={sp} (use sp_attention=ring otherwise)")
                    train_model = with_ulysses_attention(model, mesh)
                else:
                    assert strategy == "ring", (
                        f"unknown sp_attention: {strategy}")
                    train_model = with_ring_attention(model, mesh)
            if opt.model_type == "dtqn-moe":
                # MoE: the apply surfaces the sown load-balancing losses
                # as a (q, aux) tuple; the step adds aux_weight * aux
                from pytorch_distributed_tpu.models.moe import (
                    window_q_with_aux,
                )

                window_apply = window_q_with_aux(train_model)
                kw["aux_weight"] = opt.model_params.moe_aux_weight
                # target pass: q only — no mutable sow collection; the
                # frozen network's aux value is never used
                kw["target_window_apply"] = lambda p, obs: \
                    train_model.apply(p, obs, method=train_model.window_q)
            else:
                window_apply = lambda p, obs: train_model.apply(
                    p, obs, method=train_model.window_q)
            step = build_dtqn_train_step(window_apply, tx, **kw)
        else:
            step = build_drqn_train_step(
                model.apply, tx,
                packed_frames=sequence_pack_frames(opt), **kw)
        return state, step

    if opt.agent_type == "dqn":
        tx = make_optimizer(ap.lr, ap.clip_grad, ap.weight_decay,
                            lr_decay_steps=decay)
        state = init_train_state(params, tx)
        train_apply = _dqn_train_apply(opt, model)
        step = build_dqn_train_step(
            train_apply, tx,
            enable_double=ap.enable_double,
            target_model_update=ap.target_model_update,
            guard=guard,
        )
        return state, step

    if opt.agent_type == "ddpg":
        actor_apply, critic_apply = ddpg_applies(model)
        if ap.ddpg_coupled_update:
            tx = make_optimizer(ap.lr, ap.clip_grad, lr_decay_steps=decay)
            state = init_train_state(params, tx)
            step = build_ddpg_train_step_coupled(
                actor_apply, critic_apply, tx,
                target_model_update=ap.target_model_update,
                guard=guard,
            )
        else:
            atx = make_optimizer(ap.lr, ap.clip_grad, lr_decay_steps=decay)
            ctx_ = make_optimizer(ap.critic_lr, ap.clip_grad,
                                  lr_decay_steps=decay)
            state = init_ddpg_train_state(params, atx, ctx_)
            step = build_ddpg_train_step(
                actor_apply, critic_apply, atx, ctx_,
                target_model_update=ap.target_model_update,
                guard=guard,
            )
        return state, step

    raise ValueError(f"unknown agent_type: {opt.agent_type}")


def _dqn_train_apply(opt: Options, model):
    """The learner-side apply for the dqn family: the model's own apply,
    re-based for NHWC ring storage when that knob is live, and swapped
    for the Pallas fused torso (ops/pallas_torso.py) when the ISSUE-13
    ``pallas_torso`` knob is on and runnable.  Decided HERE — one gate
    shared by the sequential step and the megabatch step — so the two
    programs can never train through different torsos.  Actors and
    evaluators never route through this: the param tree is identical,
    so they keep the standard apply."""
    train_apply = model.apply
    nhwc = device_ring_channels_last(opt)
    if nhwc:
        # the HBM ring stores rows NHWC (same param tree, transpose
        # moved from 3x per update to once per ingest — see
        # memory/device_replay.py chunk_to_nhwc)
        train_apply = model.clone(nhwc_input=True).apply
    from pytorch_distributed_tpu.utils.perf import resolve_mxu

    lp = resolve_mxu(opt.learner_perf_params)
    if not lp.pallas_torso:
        return train_apply
    import warnings

    if opt.model_type != "dqn-cnn":
        warnings.warn(
            f"pallas_torso=true serves the dqn-cnn torso only (got "
            f"model_type={opt.model_type}); keeping the XLA apply",
            stacklevel=3)
        return train_apply
    import jax

    if jax.devices()[0].platform != "tpu" and not lp.pallas_interpret:
        # LOUD downgrade, never a silent one: a config that asked for
        # the MXU kernel but runs on a host without one must say so
        warnings.warn(
            "pallas_torso=true but no TPU backend is present "
            "(set pallas_interpret=true for the interpreter-mode CPU "
            "fallback — tier-1 parity tests only; it is slower than "
            "XLA's native conv); keeping the XLA apply", stacklevel=3)
        return train_apply
    from pytorch_distributed_tpu.ops.pallas_torso import (
        build_pallas_torso_apply,
    )
    import jax.numpy as jnp

    return build_pallas_torso_apply(
        norm_val=model.norm_val,
        compute_dtype=jnp.dtype(opt.model_params.compute_dtype),
        nhwc_input=nhwc,
        interpret=lp.pallas_interpret)


def build_megabatch_train_step(opt: Options, model):
    """The ISSUE-13 megabatch twin of ``build_train_state_and_step``'s
    step: a ``(TrainState, batches(M, B)) -> (TrainState, metrics,
    td_abs(M, B), ok(M,))`` group step computing all M minibatch
    gradients in one lane-filling batched backward with sequential
    in-graph optimizer applies (ops/losses.py megabatch builders).

    The optimizer chain is constructed EXACTLY as the sequential
    builder constructs it, so the TrainState the sequential path
    initialised (and checkpointed) is directly consumable.  Returns
    None for families without megabatch support (the sequence/
    transformer families and coupled DDPG) — callers downgrade loudly.
    No mesh parameter on purpose: the supported families' data
    parallelism is SPMD through jit sharding (the sequential builder
    only consumes its mesh for the sequence-parallel DTQN paths, which
    megabatch does not serve).
    """
    from pytorch_distributed_tpu.ops.losses import (
        build_ddpg_megabatch_step, build_dqn_megabatch_step,
        make_optimizer,
    )
    from pytorch_distributed_tpu.utils import health

    ap = opt.agent_params
    decay = ap.steps if ap.lr_decay else 0
    guard = health.resolve(opt.health_params).numeric_guards
    if opt.agent_type == "dqn":
        tx = make_optimizer(ap.lr, ap.clip_grad, ap.weight_decay,
                            lr_decay_steps=decay)
        return build_dqn_megabatch_step(
            _dqn_train_apply(opt, model), tx,
            enable_double=ap.enable_double,
            target_model_update=ap.target_model_update,
            guard=guard,
        )
    if opt.agent_type == "ddpg" and not ap.ddpg_coupled_update:
        actor_apply, critic_apply = ddpg_applies(model)
        atx = make_optimizer(ap.lr, ap.clip_grad, lr_decay_steps=decay)
        ctx_ = make_optimizer(ap.critic_lr, ap.clip_grad,
                              lr_decay_steps=decay)
        return build_ddpg_megabatch_step(
            actor_apply, critic_apply, atx, ctx_,
            target_model_update=ap.target_model_update,
            guard=guard,
        )
    return None


def resolve_megabatch(opt: Options, steps_per_call: int
                      ) -> Tuple[int, int]:
    """Resolve the ISSUE-13 megabatch knob against a dispatch's
    ``steps_per_call``: returns ``(M, K)`` with M clamped to >= 1 and K
    rounded UP to the next multiple of M (the ``steps`` budget already
    tolerates whole-dispatch overshoot; silently truncating updates
    would be worse).  One resolution point shared by the learner and
    its Anakin twin so the two can never disagree on grouping."""
    from pytorch_distributed_tpu.utils.perf import resolve_mxu

    M = max(1, int(resolve_mxu(opt.learner_perf_params).megabatch))
    K = max(1, int(steps_per_call))
    if M > 1 and K % M:
        K = ((K + M - 1) // M) * M
        print(f"[learner] steps_per_dispatch rounded up to {K} "
              f"(multiple of megabatch {M})", flush=True)
    return M, K


def build_replica_grad_apply(opt: Options, model):
    """The ISSUE-15 replica-plane twin of ``build_train_state_and_step``:
    the dqn update factored at the gradient boundary
    (ops/losses.build_dqn_grad_and_apply) so the replica driver can
    allreduce gradients over DCN between the halves.  The optimizer and
    train apply are constructed EXACTLY as the sequential builder
    constructs them (one ``_dqn_train_apply`` gate, one
    ``make_optimizer`` call), so a TrainState initialised — or
    checkpointed — by the solo learner is directly consumable by a
    replica, and vice versa.  Returns ``(grad_fn, apply_grads)`` or
    None for families without replica support (callers downgrade
    loudly)."""
    from pytorch_distributed_tpu.ops.losses import (
        build_dqn_grad_and_apply, make_optimizer,
    )

    if opt.agent_type != "dqn":
        return None
    ap = opt.agent_params
    tx = make_optimizer(ap.lr, ap.clip_grad, ap.weight_decay,
                        lr_decay_steps=(ap.steps if ap.lr_decay else 0))
    return build_dqn_grad_and_apply(
        _dqn_train_apply(opt, model), tx,
        enable_double=ap.enable_double,
        target_model_update=ap.target_model_update,
    )


def replica_active(opt: Options) -> bool:
    """Is the elastic multi-learner plane engaged (ISSUE 15)?  One
    resolution point (parallel.dcn.resolve_replica applies the
    TPU_APEX_REPLICA_* env contract) shared by the runtime wiring, the
    learner delegation and the fleet CLI."""
    from pytorch_distributed_tpu.parallel.dcn import resolve_replica

    return resolve_replica(opt.replica_params).replicas > 1


def published_params(opt: Options, state) -> Any:
    """The param tree the learner publishes to actors: the full model tree
    (merged back for decoupled DDPG, whose TrainState splits it)."""
    if opt.agent_type == "ddpg" and not opt.agent_params.ddpg_coupled_update:
        from pytorch_distributed_tpu.ops.losses import merge_ddpg_params

        return merge_ddpg_params(state.params["actor"],
                                 state.params["critic"])
    return state.params


# ---------------------------------------------------------------------------
# Memory routing
# ---------------------------------------------------------------------------

@dataclass
class MemoryHandles:
    """How the topology plugs a memory_type in:

    - ``actor_side``: what actor processes call ``feed`` on;
    - ``learner_side``: what the learner samples from (and updates
      priorities on);
    - for the shared ring both are the same object (reference
      shared_memory.py's one global buffer); for PER the actor side is a
      queue feeder and the learner side the single-owner tree buffer
      (memory/prioritized.py docstring).
    """

    actor_side: Any
    learner_side: Any


def device_ring_channels_last(opt: Options) -> bool:
    """Whether the HBM ring stores image rows channels-last (NHWC).

    Decided here so build_memory (ring geometry, parent process) and
    build_train_state_and_step (the NHWC train apply, learner process)
    always agree.  Default OFF from measurement, not oversight: the XLA
    profile showed ~25% of fused-step device time in layout copies, but
    an interleaved A/B on the TPU v5 lite (2026-07-31,
    tools/mfu_probe.py machinery) measured the channels-last ring ~13%
    SLOWER (2078 -> 1807 updates/s) — TPU tiled layouts pad the minor
    dimension to the 128 vector lanes, so (..., 84, 4) rows pad the
    4-wide channel axis brutally while the NCHW profile's copies are
    XLA's own (cheaper) preferred re-tilings.  The mechanism stays live
    behind ``--set device_channels_last=true`` (DeviceReplay
    channels_last + DqnCnnModel nhwc_input, layout-equivalence-tested)
    so a per-hardware A/B never needs a source edit — and this predicate
    carries ALL the eligibility conditions (fused device ring + the CNN
    model that owns an nhwc_input switch), so host-replay configs and
    MLP models can never see the NHWC apply regardless of the flag."""
    eligible = (opt.memory_type in ("device", "device-per")
                and opt.model_type == "dqn-cnn")
    return eligible and opt.memory_params.device_channels_last


def build_memory(opt: Options, spec: EnvSpec) -> MemoryHandles:
    mp_ = opt.memory_params
    state_dtype = np.uint8 if mp_.state_dtype == "uint8" else np.float32
    if opt.memory_type in ("shared", "native"):
        ctor = SharedReplay
        if opt.memory_type == "native":
            try:
                from pytorch_distributed_tpu.memory.native_ring import (
                    NativeRingReplay, get_lib,
                )

                get_lib()
                ctor = NativeRingReplay
            except Exception as e:  # noqa: BLE001 - no toolchain: fall back
                import warnings

                warnings.warn(f"native ring unavailable ({e}); "
                              "falling back to Python shared replay",
                              stacklevel=2)
        mem = ctor(
            capacity=mp_.memory_size,
            state_shape=spec.state_shape,
            action_shape=spec.action_shape,
            state_dtype=state_dtype,
            action_dtype=spec.action_dtype,
        )
        return MemoryHandles(actor_side=mem, learner_side=mem)
    if opt.memory_type == "prioritized":
        from pytorch_distributed_tpu.memory import shard_plane

        if shard_plane.sharding_active(opt.shard_params):
            # ISSUE 20: the sharded priority plane — N loopback shards
            # behind the SAME QueueOwner boundary, so the learner loop,
            # feeder, and quarantine path never learn sharding exists.
            # At shards <= 1 this branch is never taken and the plain
            # PER below is constructed bit-identically to every prior
            # release.
            plane, _shards, _reg = shard_plane.build_loopback_plane(
                opt.shard_params,
                capacity=mp_.memory_size,
                state_shape=spec.state_shape,
                action_shape=spec.action_shape,
                state_dtype=state_dtype,
                action_dtype=spec.action_dtype,
                priority_exponent=mp_.priority_exponent,
                importance_weight=mp_.priority_weight,
                importance_anneal_steps=opt.agent_params.steps,
            )
            owner = QueueOwner(plane)
            return MemoryHandles(actor_side=owner.make_feeder(),
                                 learner_side=owner)
        per = PrioritizedReplay(
            capacity=mp_.memory_size,
            state_shape=spec.state_shape,
            action_shape=spec.action_shape,
            state_dtype=state_dtype,
            action_dtype=spec.action_dtype,
            priority_exponent=mp_.priority_exponent,
            importance_weight=mp_.priority_weight,
            importance_anneal_steps=opt.agent_params.steps,
        )
        owner = QueueOwner(per)
        return MemoryHandles(actor_side=owner.make_feeder(),
                             learner_side=owner)
    if opt.memory_type == "sequence":
        from pytorch_distributed_tpu.memory.sequence_replay import (
            SequenceReplay,
        )

        ap = opt.agent_params
        seq = SequenceReplay(
            # memory_size counts transitions everywhere else; overlapping
            # windows mean ~seq_len/overlap rows per transition, so divide
            # by the overlap stride to hold the same history span
            capacity=max(mp_.memory_size
                         // max(ap.seq_len - ap.seq_overlap, 1), 16),
            seq_len=ap.seq_len,
            state_shape=spec.state_shape,
            lstm_dim=lstm_dim_of(opt),
            state_dtype=state_dtype,
            priority_exponent=mp_.priority_exponent,
            importance_weight=mp_.priority_weight,
            importance_anneal_steps=ap.steps * ap.batch_size,
            pack_frames=sequence_pack_frames(opt),
        )
        owner = QueueOwner(seq)
        return MemoryHandles(actor_side=owner.make_feeder(),
                             learner_side=owner)
    if opt.memory_type == "device-sequence":
        from pytorch_distributed_tpu.memory.device_sequence import (
            DeviceSequenceIngest,
        )

        ap = opt.agent_params
        ingest = DeviceSequenceIngest(
            # same segments-per-history-span arithmetic as the host plane
            capacity=max(mp_.memory_size
                         // max(ap.seq_len - ap.seq_overlap, 1), 16),
            seq_len=ap.seq_len,
            state_shape=spec.state_shape,
            lstm_dim=lstm_dim_of(opt),
            state_dtype=state_dtype,
            priority_exponent=mp_.priority_exponent,
            importance_weight=mp_.priority_weight,
            importance_anneal_steps=ap.steps,
            pack_frames=sequence_pack_frames(opt),
        )
        return MemoryHandles(actor_side=ingest.make_feeder(),
                             learner_side=ingest)
    if opt.memory_type in ("device", "device-per"):
        from pytorch_distributed_tpu.memory.device_replay import (
            DevicePerIngest, DeviceReplayIngest,
        )

        geom = dict(
            capacity=mp_.memory_size,
            state_shape=spec.state_shape,
            action_shape=spec.action_shape,
            state_dtype=state_dtype,
            action_dtype=spec.action_dtype,
            channels_last=device_ring_channels_last(opt),
        )
        if opt.memory_type == "device-per":
            ingest = DevicePerIngest(
                priority_exponent=mp_.priority_exponent,
                importance_weight=mp_.priority_weight,
                importance_anneal_steps=opt.agent_params.steps,
                **geom)
        else:
            ingest = DeviceReplayIngest(**geom)
        return MemoryHandles(actor_side=ingest.make_feeder(),
                             learner_side=ingest)
    raise ValueError(f"unknown memory_type: {opt.memory_type}")
