"""Learner update steps as pure jitted functions.

Functional re-design of the reference learner hot loops
(reference core/single_processes/dqn_learner.py:50-95 and
ddpg_learner.py:50-106): where the reference mutates a shared CUDA model
with torch autograd + Adam in an OS process, here each update is a pure
``(TrainState, Batch, key) -> (TrainState, metrics)`` XLA program — the
whole step (forward, backward, optimizer, target update) compiles into one
fused computation that the parallel layer can shard over a device mesh with
gradient all-reduce over ICI (parallel/learner.py).

Semantics parity (each cited):
- n-step target ``r + gamma_n * bootstrap(s1) * (1 - terminal)`` with the
  *stored per-sample* effective discount gamma_n
  (reference dqn_learner.py:73-74);
- optional double-DQN action selection by the online net
  (reference dqn_learner.py:67-71, off by default utils/options.py:139);
- MSE value criterion (reference utils/options.py:114) — Huber available;
- gradient clip by value (torch ``clip_grad_value_``,
  reference dqn_learner.py:80-82; inf for DQN, 40 for DDPG);
- target update: hard every N steps for DQN, soft tau for DDPG
  (reference utils/helpers.py:19-25);
- DDPG: policy loss ``-Q(s, pi(s)).mean()`` + critic TD loss
  (reference ddpg_learner.py:66-86).  The reference couples both losses
  through one Adam step so policy-loss gradients also hit the critic
  (ddpg_learner.py:62-91, SURVEY.md "known quirks"); ``coupled=True``
  reproduces that, the default decouples per-net optimizers.

PER additions beyond the reference (its TODO): importance weights multiply
the per-sample TD loss, and |TD| errors are returned for priority
write-back.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import optax

from pytorch_distributed_tpu.utils.experience import Batch
from pytorch_distributed_tpu.utils.health import finite_guard
from pytorch_distributed_tpu.utils.helpers import global_norm, update_target

PyTree = Any


class TrainState(NamedTuple):
    params: PyTree
    target_params: PyTree
    opt_state: PyTree
    step: jnp.ndarray  # int32 learner step (the global clock's source)


def init_train_state(params: PyTree,
                     tx: optax.GradientTransformation) -> TrainState:
    """Build a fresh TrainState with the target net hard-synced to the
    online net (reference dqn_learner.py:21-35 syncs at start).  The target
    tree is an independent buffer copy — aliasing ``TrainState(params,
    params, ...)`` breaks donation (XLA rejects donating one buffer twice).
    """
    target = jax.tree_util.tree_map(jnp.array, params)
    return TrainState(params, target, tx.init(params), jnp.asarray(0))


def make_optimizer(lr: float, clip_grad: float = float("inf"),
                   weight_decay: float = 0.0,
                   lr_decay_steps: int = 0) -> optax.GradientTransformation:
    """Adam with optional by-value grad clipping, matching the reference's
    Adam + clip_grad_value_ pairing (reference dqn_learner.py:37-39,80-82).
    ``lr_decay_steps > 0`` linearly anneals the lr to zero over that many
    learner steps (the reference's ``lr_decay`` flag, utils/options.py)."""
    chain = []
    if clip_grad != float("inf"):
        chain.append(optax.clip(clip_grad))  # by-value, like clip_grad_value_
    if weight_decay > 0.0:
        chain.append(optax.add_decayed_weights(weight_decay))
    schedule = (optax.linear_schedule(lr, 0.0, lr_decay_steps)
                if lr_decay_steps > 0 else lr)
    chain.append(optax.adam(schedule))
    return optax.chain(*chain)


def _value_loss(pred: jnp.ndarray, target: jnp.ndarray, weight: jnp.ndarray,
                huber: bool) -> Tuple[jnp.ndarray, jnp.ndarray]:
    td = pred - jax.lax.stop_gradient(target)
    if huber:
        per = optax.huber_loss(pred, jax.lax.stop_gradient(target), delta=1.0)
    else:
        # plain squared error, matching the reference's nn.MSELoss
        # (reference utils/options.py:114) — no 1/2 factor, so gradient
        # magnitudes match the reference under identical learning rates
        per = jnp.square(td)
    return jnp.mean(weight * per), jnp.abs(td)


def build_dqn_train_step(
    apply_fn: Callable,
    tx: optax.GradientTransformation,
    *,
    enable_double: bool = False,
    target_model_update: float = 250,
    huber: bool = False,
    axis_name: str | None = None,
    guard: bool = True,
) -> Callable[[TrainState, Batch],
              Tuple[TrainState, Dict[str, jnp.ndarray], jnp.ndarray]]:
    """Returns the DQN update step ``(state, batch) -> (state, metrics,
    td_abs)`` (reference dqn_learner.py:55-95 as one XLA program); ``td_abs``
    feeds PER priority write-back.  ``guard`` (default on) wraps the step
    with the in-jit finite check (utils/health.finite_guard): a
    non-finite step passes the state through unchanged and reports
    ``learner/skipped`` instead of poisoning Adam."""

    def step(state: TrainState, batch: Batch):
        def loss_fn(params):
            q = apply_fn(params, batch.state0)                       # (B, A)
            a = batch.action.astype(jnp.int32).reshape(-1, 1)
            q_sel = jnp.take_along_axis(q, a, axis=1)[:, 0]
            q_next = apply_fn(state.target_params, batch.state1)     # (B, A)
            if enable_double:
                a_next = jnp.argmax(apply_fn(params, batch.state1), axis=-1)
                bootstrap = jnp.take_along_axis(
                    q_next, a_next[:, None], axis=1)[:, 0]
            else:
                bootstrap = jnp.max(q_next, axis=-1)
            target = (batch.reward
                      + batch.gamma_n * bootstrap * (1.0 - batch.terminal1))
            loss, td_abs = _value_loss(q_sel, target, batch.weight, huber)
            return loss, (td_abs, jnp.mean(jnp.max(q, axis=-1)))

        (loss, (td_abs, q_mean)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(state.params)
        # data-parallel: mean grads across the mesh's dp axis if present
        grads = _pmean(grads, axis_name)
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        new_step = state.step + 1
        target_params = update_target(state.target_params, params, new_step,
                                      target_model_update)
        metrics = {
            "learner/critic_loss": loss,
            "learner/q_mean": q_mean,
            "learner/grad_norm": global_norm(grads),
        }
        return (TrainState(params, target_params, opt_state, new_step),
                metrics, td_abs)

    return finite_guard(step) if guard else step


def init_ddpg_train_state(
    full_params: PyTree,
    actor_tx: optax.GradientTransformation,
    critic_tx: optax.GradientTransformation,
) -> TrainState:
    """TrainState for the decoupled DDPG update: params/opt_state are
    {'actor':..., 'critic':...} dicts over the split module tree; the target
    is an independent buffer copy (same donation-safety constraint as
    ``init_train_state``)."""
    split = split_ddpg_params(full_params)
    target = jax.tree_util.tree_map(jnp.array, split)
    return TrainState(
        split, target,
        {"actor": actor_tx.init(split["actor"]),
         "critic": critic_tx.init(split["critic"])},
        jnp.asarray(0))


def build_ddpg_train_step(
    actor_apply_fn: Callable,
    critic_apply_fn: Callable,
    actor_tx: optax.GradientTransformation,
    critic_tx: optax.GradientTransformation,
    *,
    target_model_update: float = 1e-3,
    huber: bool = False,
    axis_name: str | None = None,
    guard: bool = True,
) -> Callable:
    """Decoupled DDPG update: separate critic and actor gradient steps with
    per-net optimizers (textbook DDPG; see module docstring re the
    reference's coupled variant).

    ``TrainState.params``/``opt_state`` are dicts {'actor':..., 'critic':...}
    over the single DdpgMlpModel param tree split by submodule prefix — see
    ``split_ddpg_params``/``merge_ddpg_params``.
    """

    def step(state: TrainState, batch: Batch):
        params = state.params
        target = state.target_params

        # ---- critic update (reference ddpg_learner.py:76-86) ----
        target_full = merge_ddpg_params(target["actor"], target["critic"])

        def critic_loss_fn(critic_params):
            full = merge_ddpg_params(params["actor"], critic_params)
            q = critic_apply_fn(full, batch.state0, batch.action)
            a_next = actor_apply_fn(target_full, batch.state1)
            q_next = critic_apply_fn(target_full, batch.state1, a_next)
            tgt = (batch.reward
                   + batch.gamma_n * q_next * (1.0 - batch.terminal1))
            return _value_loss(q, tgt, batch.weight, huber)

        (critic_loss, td_abs), critic_grads = jax.value_and_grad(
            critic_loss_fn, has_aux=True)(params["critic"])
        critic_grads = _pmean(critic_grads, axis_name)
        critic_updates, critic_opt = critic_tx.update(
            critic_grads, state.opt_state["critic"], params["critic"])
        new_critic = optax.apply_updates(params["critic"], critic_updates)

        # ---- actor update (reference ddpg_learner.py:66-74) ----
        def actor_loss_fn(actor_params):
            full = merge_ddpg_params(actor_params, new_critic)
            a = actor_apply_fn(full, batch.state0)
            q = critic_apply_fn(full, batch.state0, a)
            return -jnp.mean(q)

        actor_loss, actor_grads = jax.value_and_grad(actor_loss_fn)(
            params["actor"])
        actor_grads = _pmean(actor_grads, axis_name)
        actor_updates, actor_opt = actor_tx.update(
            actor_grads, state.opt_state["actor"], params["actor"])
        new_actor = optax.apply_updates(params["actor"], actor_updates)

        new_params = {"actor": new_actor, "critic": new_critic}
        new_step = state.step + 1
        # soft target every step (reference ddpg_learner.py:95, tau=1e-3)
        new_target = update_target(target, new_params, new_step,
                                   target_model_update)
        metrics = {
            "learner/critic_loss": critic_loss,
            "learner/actor_loss": actor_loss,
            # norm over BOTH nets' grads so a diverging policy is visible
            "learner/grad_norm": global_norm(
                {"actor": actor_grads, "critic": critic_grads}),
        }
        return (TrainState(new_params, new_target,
                           {"actor": actor_opt, "critic": critic_opt},
                           new_step),
                metrics, td_abs)

    return finite_guard(step) if guard else step


def build_ddpg_train_step_coupled(
    actor_apply_fn: Callable,
    critic_apply_fn: Callable,
    tx: optax.GradientTransformation,
    *,
    target_model_update: float = 1e-3,
    huber: bool = False,
    axis_name: str | None = None,
    guard: bool = True,
) -> Callable:
    """Reference-faithful coupled DDPG update: one optimizer over the full
    param tree, one gradient step of ``policy_loss + critic_loss`` — so the
    policy-loss gradient also deposits into critic params, exactly the
    behaviour of the reference's single zero_grad / double backward /
    single Adam step (reference ddpg_learner.py:62-91).  TrainState.params
    is the *merged* tree here."""

    def step(state: TrainState, batch: Batch):
        def loss_fn(full):
            # critic TD loss (reference ddpg_learner.py:76-86)
            q = critic_apply_fn(full, batch.state0, batch.action)
            a_next = actor_apply_fn(state.target_params, batch.state1)
            q_next = critic_apply_fn(state.target_params, batch.state1, a_next)
            tgt = (batch.reward
                   + batch.gamma_n * q_next * (1.0 - batch.terminal1))
            critic_loss, td_abs = _value_loss(q, tgt, batch.weight, huber)
            # policy loss (reference ddpg_learner.py:66-74)
            a = actor_apply_fn(full, batch.state0)
            actor_loss = -jnp.mean(critic_apply_fn(full, batch.state0, a))
            return critic_loss + actor_loss, (critic_loss, actor_loss, td_abs)

        (_, (critic_loss, actor_loss, td_abs)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(state.params)
        grads = _pmean(grads, axis_name)
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        new_step = state.step + 1
        new_target = update_target(state.target_params, params, new_step,
                                   target_model_update)
        metrics = {
            "learner/critic_loss": critic_loss,
            "learner/actor_loss": actor_loss,
            "learner/grad_norm": global_norm(grads),
        }
        return (TrainState(params, new_target, opt_state, new_step),
                metrics, td_abs)

    return finite_guard(step) if guard else step


# ---------------------------------------------------------------------------
# DDPG param-tree surgery: the model is one Flax module whose top-level
# submodules are actor_* / critic_* (models/ddpg_mlp.py setup()); split so
# each optimizer owns exactly its net.
# ---------------------------------------------------------------------------

def split_ddpg_params(full: PyTree) -> Dict[str, PyTree]:
    inner = full["params"]
    actor = {k: v for k, v in inner.items() if k.startswith("actor")}
    critic = {k: v for k, v in inner.items() if k.startswith("critic")}
    assert actor and critic, f"unexpected DDPG param layout: {list(inner)}"
    return {"actor": {"params": actor}, "critic": {"params": critic}}


def merge_ddpg_params(actor: PyTree, critic: PyTree) -> PyTree:
    return {"params": {**actor["params"], **critic["params"]}}


def _pmean(tree: PyTree, axis_name: str | None) -> PyTree:
    """Mean-reduce gradients across a mesh axis (the ICI all-reduce).  Only
    needed under shard_map, where collectives are explicit; under plain jit
    with sharded batch inputs XLA inserts the all-reduce itself, and
    axis_name stays None."""
    if axis_name is None:
        return tree
    return jax.lax.pmean(tree, axis_name=axis_name)
