"""Learner update steps as pure jitted functions.

Functional re-design of the reference learner hot loops
(reference core/single_processes/dqn_learner.py:50-95 and
ddpg_learner.py:50-106): where the reference mutates a shared CUDA model
with torch autograd + Adam in an OS process, here each update is a pure
``(TrainState, Batch, key) -> (TrainState, metrics)`` XLA program — the
whole step (forward, backward, optimizer, target update) compiles into one
fused computation that the parallel layer can shard over a device mesh with
gradient all-reduce over ICI (parallel/learner.py).

Semantics parity (each cited):
- n-step target ``r + gamma_n * bootstrap(s1) * (1 - terminal)`` with the
  *stored per-sample* effective discount gamma_n
  (reference dqn_learner.py:73-74);
- optional double-DQN action selection by the online net
  (reference dqn_learner.py:67-71, off by default utils/options.py:139);
- MSE value criterion (reference utils/options.py:114) — Huber available;
- gradient clip by value (torch ``clip_grad_value_``,
  reference dqn_learner.py:80-82; inf for DQN, 40 for DDPG);
- target update: hard every N steps for DQN, soft tau for DDPG
  (reference utils/helpers.py:19-25);
- DDPG: policy loss ``-Q(s, pi(s)).mean()`` + critic TD loss
  (reference ddpg_learner.py:66-86).  The reference couples both losses
  through one Adam step so policy-loss gradients also hit the critic
  (ddpg_learner.py:62-91, SURVEY.md "known quirks"); ``coupled=True``
  reproduces that, the default decouples per-net optimizers.

PER additions beyond the reference (its TODO): importance weights multiply
the per-sample TD loss, and |TD| errors are returned for priority
write-back.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import optax

from pytorch_distributed_tpu.utils.experience import Batch
from pytorch_distributed_tpu.utils.health import finite_guard
from pytorch_distributed_tpu.utils.helpers import global_norm, update_target

PyTree = Any


class TrainState(NamedTuple):
    params: PyTree
    target_params: PyTree
    opt_state: PyTree
    step: jnp.ndarray  # int32 learner step (the global clock's source)


def init_train_state(params: PyTree,
                     tx: optax.GradientTransformation) -> TrainState:
    """Build a fresh TrainState with the target net hard-synced to the
    online net (reference dqn_learner.py:21-35 syncs at start).  The target
    tree is an independent buffer copy — aliasing ``TrainState(params,
    params, ...)`` breaks donation (XLA rejects donating one buffer twice).
    """
    target = jax.tree_util.tree_map(jnp.array, params)
    return TrainState(params, target, tx.init(params), jnp.asarray(0))


def make_optimizer(lr: float, clip_grad: float = float("inf"),
                   weight_decay: float = 0.0,
                   lr_decay_steps: int = 0) -> optax.GradientTransformation:
    """Adam with optional by-value grad clipping, matching the reference's
    Adam + clip_grad_value_ pairing (reference dqn_learner.py:37-39,80-82).
    ``lr_decay_steps > 0`` linearly anneals the lr to zero over that many
    learner steps (the reference's ``lr_decay`` flag, utils/options.py)."""
    chain = []
    if clip_grad != float("inf"):
        chain.append(optax.clip(clip_grad))  # by-value, like clip_grad_value_
    if weight_decay > 0.0:
        chain.append(optax.add_decayed_weights(weight_decay))
    schedule = (optax.linear_schedule(lr, 0.0, lr_decay_steps)
                if lr_decay_steps > 0 else lr)
    chain.append(optax.adam(schedule))
    return optax.chain(*chain)


def _value_loss(pred: jnp.ndarray, target: jnp.ndarray, weight: jnp.ndarray,
                huber: bool) -> Tuple[jnp.ndarray, jnp.ndarray]:
    td = pred - jax.lax.stop_gradient(target)
    if huber:
        per = optax.huber_loss(pred, jax.lax.stop_gradient(target), delta=1.0)
    else:
        # plain squared error, matching the reference's nn.MSELoss
        # (reference utils/options.py:114) — no 1/2 factor, so gradient
        # magnitudes match the reference under identical learning rates
        per = jnp.square(td)
    return jnp.mean(weight * per), jnp.abs(td)


def build_dqn_train_step(
    apply_fn: Callable,
    tx: optax.GradientTransformation,
    *,
    enable_double: bool = False,
    target_model_update: float = 250,
    huber: bool = False,
    axis_name: str | None = None,
    guard: bool = True,
) -> Callable[[TrainState, Batch],
              Tuple[TrainState, Dict[str, jnp.ndarray], jnp.ndarray]]:
    """Returns the DQN update step ``(state, batch) -> (state, metrics,
    td_abs)`` (reference dqn_learner.py:55-95 as one XLA program); ``td_abs``
    feeds PER priority write-back.  ``guard`` (default on) wraps the step
    with the in-jit finite check (utils/health.finite_guard): a
    non-finite step passes the state through unchanged and reports
    ``learner/skipped`` instead of poisoning Adam."""

    def step(state: TrainState, batch: Batch):
        def loss_fn(params):
            q = apply_fn(params, batch.state0)                       # (B, A)
            a = batch.action.astype(jnp.int32).reshape(-1, 1)
            q_sel = jnp.take_along_axis(q, a, axis=1)[:, 0]
            q_next = apply_fn(state.target_params, batch.state1)     # (B, A)
            if enable_double:
                a_next = jnp.argmax(apply_fn(params, batch.state1), axis=-1)
                bootstrap = jnp.take_along_axis(
                    q_next, a_next[:, None], axis=1)[:, 0]
            else:
                bootstrap = jnp.max(q_next, axis=-1)
            target = (batch.reward
                      + batch.gamma_n * bootstrap * (1.0 - batch.terminal1))
            loss, td_abs = _value_loss(q_sel, target, batch.weight, huber)
            return loss, (td_abs, jnp.mean(jnp.max(q, axis=-1)))

        (loss, (td_abs, q_mean)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(state.params)
        # data-parallel: mean grads across the mesh's dp axis if present
        grads = _pmean(grads, axis_name)
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        new_step = state.step + 1
        target_params = update_target(state.target_params, params, new_step,
                                      target_model_update)
        metrics = {
            "learner/critic_loss": loss,
            "learner/q_mean": q_mean,
            "learner/grad_norm": global_norm(grads),
        }
        return (TrainState(params, target_params, opt_state, new_step),
                metrics, td_abs)

    return finite_guard(step) if guard else step


def build_dqn_grad_and_apply(
    apply_fn: Callable,
    tx: optax.GradientTransformation,
    *,
    enable_double: bool = False,
    target_model_update: float = 250,
    huber: bool = False,
) -> Tuple[Callable, Callable]:
    """The ISSUE-15 replica split of ``build_dqn_train_step``: the same
    update factored at the gradient boundary so N data-parallel learner
    replicas can allreduce over DCN between the two halves.

    - ``grad_fn(state, batch) -> (grads, ok, metrics, td_abs)`` computes
      the gradients at the CURRENT params (the exact loss/double-DQN/
      |TD| math of the fused step) plus a finiteness flag ``ok`` (f32
      0/1 over loss, td and every grad leaf — the per-contribution twin
      of ``finite_guard``: a diverged replica's NaN gradient must be
      excluded from the reduce, not poison every survivor).
    - ``apply_grads(state, grads, ok) -> state`` applies an (already
      reduced) gradient tree: optimizer update, step increment and the
      target cadence chained exactly as the fused step chains them;
      ``ok <= 0`` selects the INPUT state through unchanged (a round
      with zero valid contributions is a skipped step, like the guard).

    The halves compose to the fused step's semantics; at world size 1
    the reduced gradient IS the local gradient (mean over one
    contributor divides by 1.0 — an IEEE identity), which is what makes
    the degraded-to-solo parity oracle (tests/test_replicas.py) a
    bit-exact check rather than a tolerance one."""

    def grad_fn(state: TrainState, batch: Batch):
        def loss_fn(params):
            q = apply_fn(params, batch.state0)
            a = batch.action.astype(jnp.int32).reshape(-1, 1)
            q_sel = jnp.take_along_axis(q, a, axis=1)[:, 0]
            q_next = apply_fn(state.target_params, batch.state1)
            if enable_double:
                a_next = jnp.argmax(apply_fn(params, batch.state1),
                                    axis=-1)
                bootstrap = jnp.take_along_axis(
                    q_next, a_next[:, None], axis=1)[:, 0]
            else:
                bootstrap = jnp.max(q_next, axis=-1)
            target = (batch.reward
                      + batch.gamma_n * bootstrap
                      * (1.0 - batch.terminal1))
            loss, td_abs = _value_loss(q_sel, target, batch.weight,
                                       huber)
            return loss, (td_abs, jnp.mean(jnp.max(q, axis=-1)))

        (loss, (td_abs, q_mean)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(state.params)
        ok = jnp.isfinite(loss) & jnp.all(jnp.isfinite(td_abs))
        for leaf in jax.tree_util.tree_leaves(grads):
            ok = ok & jnp.all(jnp.isfinite(leaf))
        metrics = {
            "learner/critic_loss": loss,
            "learner/q_mean": q_mean,
            "learner/grad_norm": global_norm(grads),
        }
        return grads, ok.astype(jnp.float32), metrics, td_abs

    def apply_grads(state: TrainState, grads, ok):
        updates, opt_state = tx.update(grads, state.opt_state,
                                       state.params)
        params = optax.apply_updates(state.params, updates)
        new_step = state.step + 1
        target_params = update_target(state.target_params, params,
                                      new_step, target_model_update)
        new = TrainState(params, target_params, opt_state, new_step)
        # ok <= 0: the whole round was invalid — pass the input state
        # through per-leaf, exactly finite_guard's skip semantics
        return jax.tree_util.tree_map(
            lambda a, b: jnp.where(ok > 0, a, b), new, state)

    return grad_fn, apply_grads


def _per_minibatch_ok(*arrays, grads=None):
    """(M,) float32 validity mask over a megabatch group: 1.0 where every
    per-minibatch quantity (loss/td rows, every grad leaf) is finite —
    the per-minibatch twin of ``finite_guard``'s whole-step check, so a
    poisoned minibatch skips ITS update without discarding the group's
    other M-1 updates."""
    ok = None
    for a in arrays:
        flat = a.reshape(a.shape[0], -1) if a.ndim > 1 else a[:, None]
        this = jnp.all(jnp.isfinite(flat), axis=1)
        ok = this if ok is None else ok & this
    if grads is not None:
        for leaf in jax.tree_util.tree_leaves(grads):
            this = jnp.all(jnp.isfinite(leaf.reshape(leaf.shape[0], -1)),
                           axis=1)
            ok = this if ok is None else ok & this
    return ok.astype(jnp.float32)


def build_dqn_megabatch_step(
    apply_fn: Callable,
    tx: optax.GradientTransformation,
    *,
    enable_double: bool = False,
    target_model_update: float = 250,
    huber: bool = False,
    axis_name: str | None = None,
    guard: bool = True,
) -> Callable:
    """ISSUE-13 megabatch group step: ``(state, batches) -> (state,
    metrics, td_abs, ok)`` where ``batches`` carries M minibatches as
    (M, B)-leading leaves.

    All M per-minibatch gradients are computed at the GROUP-ENTRY
    params in ONE batched forward/backward (``jax.vmap`` over the
    minibatch axis — XLA sees (M*B)-row lane-filling GEMMs instead of M
    dispatch-bound small ones), then the M optimizer updates apply
    SEQUENTIALLY in-graph: Adam moments, the step counter and the
    target-update cadence chain exactly as M separate
    ``build_dqn_train_step`` calls would.  The one divergence from M
    sequential steps is within-group gradient freshness (gradients see
    the group-entry params, the Stooke & Abbeel 2018 large-effective-
    batch trade); with M=1 the program is the sequential step's exact
    semantics.  The tier-1 oracle (tests/test_megabatch.py) pins the
    program against an unfused reference of these semantics.

    ``guard`` applies the finite check PER MINIBATCH: a non-finite
    minibatch skips its own update (params/opt/target/step pass
    through), its td_abs row is zeroed, and ``metrics[SKIPPED_KEY]``
    counts the group's skips; ``ok`` (M,) float lets the PER write-back
    suppress exactly the skipped rows."""
    from pytorch_distributed_tpu.utils.health import SKIPPED_KEY

    def minibatch_loss(params, target_params, batch: Batch):
        q = apply_fn(params, batch.state0)                       # (B, A)
        a = batch.action.astype(jnp.int32).reshape(-1, 1)
        q_sel = jnp.take_along_axis(q, a, axis=1)[:, 0]
        q_next = apply_fn(target_params, batch.state1)           # (B, A)
        if enable_double:
            a_next = jnp.argmax(apply_fn(params, batch.state1), axis=-1)
            bootstrap = jnp.take_along_axis(
                q_next, a_next[:, None], axis=1)[:, 0]
        else:
            bootstrap = jnp.max(q_next, axis=-1)
        target = (batch.reward
                  + batch.gamma_n * bootstrap * (1.0 - batch.terminal1))
        loss, td_abs = _value_loss(q_sel, target, batch.weight, huber)
        return loss, (td_abs, jnp.mean(jnp.max(q, axis=-1)))

    def step(state: TrainState, batches: Batch):
        grad_fn = jax.value_and_grad(minibatch_loss, has_aux=True)
        (losses, (td_abs, q_means)), grads = jax.vmap(
            grad_fn, in_axes=(None, None, 0))(
                state.params, state.target_params, batches)
        grads = _pmean(grads, axis_name)
        M = losses.shape[0]
        ok = (_per_minibatch_ok(losses, td_abs, q_means, grads=grads)
              if guard else jnp.ones((M,), jnp.float32))

        def apply_one(carry, x):
            params, opt_state, target_params, step_c = carry
            g, ok_i = x
            updates, new_opt = tx.update(g, opt_state, params)
            new_params = optax.apply_updates(params, updates)
            new_step = step_c + 1
            new_target = update_target(target_params, new_params,
                                       new_step, target_model_update)
            keep = ok_i > 0.5
            sel = lambda n, o: jax.tree_util.tree_map(
                lambda a, b: jnp.where(keep, a, b), n, o)
            return (sel(new_params, params), sel(new_opt, opt_state),
                    sel(new_target, target_params),
                    jnp.where(keep, new_step, step_c)), None

        (params, opt_state, target_params, new_step), _ = jax.lax.scan(
            apply_one,
            (state.params, state.opt_state, state.target_params,
             state.step),
            (grads, ok))
        last_grad = jax.tree_util.tree_map(lambda l: l[-1], grads)
        metrics = {
            "learner/critic_loss": losses[-1],
            "learner/q_mean": q_means[-1],
            "learner/grad_norm": global_norm(last_grad),
        }
        if guard:
            metrics[SKIPPED_KEY] = jnp.sum(1.0 - ok)
        td_abs = jnp.where(ok[:, None] > 0.5, td_abs,
                           jnp.zeros_like(td_abs))
        return (TrainState(params, target_params, opt_state, new_step),
                metrics, td_abs, ok)

    return step


def build_ddpg_megabatch_step(
    actor_apply_fn: Callable,
    critic_apply_fn: Callable,
    actor_tx: optax.GradientTransformation,
    critic_tx: optax.GradientTransformation,
    *,
    target_model_update: float = 1e-3,
    huber: bool = False,
    axis_name: str | None = None,
    guard: bool = True,
) -> Callable:
    """Decoupled-DDPG twin of ``build_dqn_megabatch_step``: same
    ``(state, batches(M, B)) -> (state, metrics, td_abs, ok)`` group
    contract.

    Group semantics (tests/test_megabatch.py pins the unfused
    reference): all M critic gradients batched at the group-entry
    params; the M critic updates apply sequentially; all M actor
    gradients batched at (group-entry actor, the FINAL post-group
    critic) — for M=1 this is exactly ``build_ddpg_train_step``'s
    "actor sees the freshly-updated critic"; the M actor updates apply
    sequentially and the soft target update chains per minibatch with
    the per-step (actor_i, critic_i) pair.

    Guard semantics (per minibatch, documented divergence from the
    whole-step ``finite_guard``): the critic-stage mask (critic
    loss/td/grads finite) gates the critic chain; the COMBINED mask
    (critic & actor stages) gates the actor/target/step chain, zeroes
    td_abs rows and is the returned ``ok`` — so a minibatch whose
    actor stage alone is non-finite keeps its (finite) critic update.
    """
    from pytorch_distributed_tpu.utils.health import SKIPPED_KEY

    def critic_loss_fn(critic_params, actor_params, target_full,
                       batch: Batch):
        full = merge_ddpg_params(actor_params, critic_params)
        q = critic_apply_fn(full, batch.state0, batch.action)
        a_next = actor_apply_fn(target_full, batch.state1)
        q_next = critic_apply_fn(target_full, batch.state1, a_next)
        tgt = (batch.reward
               + batch.gamma_n * q_next * (1.0 - batch.terminal1))
        return _value_loss(q, tgt, batch.weight, huber)

    def actor_loss_fn(actor_params, critic_params, batch: Batch):
        full = merge_ddpg_params(actor_params, critic_params)
        a = actor_apply_fn(full, batch.state0)
        return -jnp.mean(critic_apply_fn(full, batch.state0, a))

    def step(state: TrainState, batches: Batch):
        params, target = state.params, state.target_params
        target_full = merge_ddpg_params(target["actor"], target["critic"])

        # ---- stage 1: M critic grads at group entry, one batched bwd ----
        cgrad_fn = jax.value_and_grad(critic_loss_fn, has_aux=True)
        (closs, td_abs), cgrads = jax.vmap(
            cgrad_fn, in_axes=(None, None, None, 0))(
                params["critic"], params["actor"], target_full, batches)
        cgrads = _pmean(cgrads, axis_name)
        M = closs.shape[0]
        ones = jnp.ones((M,), jnp.float32)
        ok_c = (_per_minibatch_ok(closs, td_abs, grads=cgrads)
                if guard else ones)

        def capply(carry, x):
            cp, copt = carry
            g, ok_i = x
            updates, new_opt = critic_tx.update(g, copt, cp)
            new_cp = optax.apply_updates(cp, updates)
            keep = ok_i > 0.5
            sel = lambda n, o: jax.tree_util.tree_map(
                lambda a, b: jnp.where(keep, a, b), n, o)
            new_cp = sel(new_cp, cp)
            return (new_cp, sel(new_opt, copt)), new_cp

        (final_critic, critic_opt), critics = jax.lax.scan(
            capply, (params["critic"], state.opt_state["critic"]),
            (cgrads, ok_c))

        # ---- stage 2: M actor grads at (entry actor, final critic) ----
        agrad_fn = jax.value_and_grad(actor_loss_fn)
        aloss, agrads = jax.vmap(agrad_fn, in_axes=(None, None, 0))(
            params["actor"], final_critic, batches)
        agrads = _pmean(agrads, axis_name)
        ok = ok_c * (_per_minibatch_ok(aloss, grads=agrads)
                     if guard else ones)

        def aapply(carry, x):
            ap_, aopt, tgt, step_c = carry
            g, ok_i, critic_i = x
            updates, new_opt = actor_tx.update(g, aopt, ap_)
            new_ap = optax.apply_updates(ap_, updates)
            new_step = step_c + 1
            new_tgt = update_target(
                tgt, {"actor": new_ap, "critic": critic_i}, new_step,
                target_model_update)
            keep = ok_i > 0.5
            sel = lambda n, o: jax.tree_util.tree_map(
                lambda a, b: jnp.where(keep, a, b), n, o)
            return (sel(new_ap, ap_), sel(new_opt, aopt),
                    sel(new_tgt, tgt),
                    jnp.where(keep, new_step, step_c)), None

        (final_actor, actor_opt, new_target, new_step), _ = jax.lax.scan(
            aapply,
            (params["actor"], state.opt_state["actor"], target,
             state.step),
            (agrads, ok, critics))

        last_g = jax.tree_util.tree_map(
            lambda l: l[-1], {"actor": agrads, "critic": cgrads})
        metrics = {
            "learner/critic_loss": closs[-1],
            "learner/actor_loss": aloss[-1],
            "learner/grad_norm": global_norm(last_g),
        }
        if guard:
            metrics[SKIPPED_KEY] = jnp.sum(1.0 - ok)
        td_abs = jnp.where(ok[:, None] > 0.5, td_abs,
                           jnp.zeros_like(td_abs))
        new_state = TrainState(
            {"actor": final_actor, "critic": final_critic}, new_target,
            {"actor": actor_opt, "critic": critic_opt}, new_step)
        return new_state, metrics, td_abs, ok

    return step


def init_ddpg_train_state(
    full_params: PyTree,
    actor_tx: optax.GradientTransformation,
    critic_tx: optax.GradientTransformation,
) -> TrainState:
    """TrainState for the decoupled DDPG update: params/opt_state are
    {'actor':..., 'critic':...} dicts over the split module tree; the target
    is an independent buffer copy (same donation-safety constraint as
    ``init_train_state``)."""
    split = split_ddpg_params(full_params)
    target = jax.tree_util.tree_map(jnp.array, split)
    return TrainState(
        split, target,
        {"actor": actor_tx.init(split["actor"]),
         "critic": critic_tx.init(split["critic"])},
        jnp.asarray(0))


def build_ddpg_train_step(
    actor_apply_fn: Callable,
    critic_apply_fn: Callable,
    actor_tx: optax.GradientTransformation,
    critic_tx: optax.GradientTransformation,
    *,
    target_model_update: float = 1e-3,
    huber: bool = False,
    axis_name: str | None = None,
    guard: bool = True,
) -> Callable:
    """Decoupled DDPG update: separate critic and actor gradient steps with
    per-net optimizers (textbook DDPG; see module docstring re the
    reference's coupled variant).

    ``TrainState.params``/``opt_state`` are dicts {'actor':..., 'critic':...}
    over the single DdpgMlpModel param tree split by submodule prefix — see
    ``split_ddpg_params``/``merge_ddpg_params``.
    """

    def step(state: TrainState, batch: Batch):
        params = state.params
        target = state.target_params

        # ---- critic update (reference ddpg_learner.py:76-86) ----
        target_full = merge_ddpg_params(target["actor"], target["critic"])

        def critic_loss_fn(critic_params):
            full = merge_ddpg_params(params["actor"], critic_params)
            q = critic_apply_fn(full, batch.state0, batch.action)
            a_next = actor_apply_fn(target_full, batch.state1)
            q_next = critic_apply_fn(target_full, batch.state1, a_next)
            tgt = (batch.reward
                   + batch.gamma_n * q_next * (1.0 - batch.terminal1))
            return _value_loss(q, tgt, batch.weight, huber)

        (critic_loss, td_abs), critic_grads = jax.value_and_grad(
            critic_loss_fn, has_aux=True)(params["critic"])
        critic_grads = _pmean(critic_grads, axis_name)
        critic_updates, critic_opt = critic_tx.update(
            critic_grads, state.opt_state["critic"], params["critic"])
        new_critic = optax.apply_updates(params["critic"], critic_updates)

        # ---- actor update (reference ddpg_learner.py:66-74) ----
        def actor_loss_fn(actor_params):
            full = merge_ddpg_params(actor_params, new_critic)
            a = actor_apply_fn(full, batch.state0)
            q = critic_apply_fn(full, batch.state0, a)
            return -jnp.mean(q)

        actor_loss, actor_grads = jax.value_and_grad(actor_loss_fn)(
            params["actor"])
        actor_grads = _pmean(actor_grads, axis_name)
        actor_updates, actor_opt = actor_tx.update(
            actor_grads, state.opt_state["actor"], params["actor"])
        new_actor = optax.apply_updates(params["actor"], actor_updates)

        new_params = {"actor": new_actor, "critic": new_critic}
        new_step = state.step + 1
        # soft target every step (reference ddpg_learner.py:95, tau=1e-3)
        new_target = update_target(target, new_params, new_step,
                                   target_model_update)
        metrics = {
            "learner/critic_loss": critic_loss,
            "learner/actor_loss": actor_loss,
            # norm over BOTH nets' grads so a diverging policy is visible
            "learner/grad_norm": global_norm(
                {"actor": actor_grads, "critic": critic_grads}),
        }
        return (TrainState(new_params, new_target,
                           {"actor": actor_opt, "critic": critic_opt},
                           new_step),
                metrics, td_abs)

    return finite_guard(step) if guard else step


def build_ddpg_train_step_coupled(
    actor_apply_fn: Callable,
    critic_apply_fn: Callable,
    tx: optax.GradientTransformation,
    *,
    target_model_update: float = 1e-3,
    huber: bool = False,
    axis_name: str | None = None,
    guard: bool = True,
) -> Callable:
    """Reference-faithful coupled DDPG update: one optimizer over the full
    param tree, one gradient step of ``policy_loss + critic_loss`` — so the
    policy-loss gradient also deposits into critic params, exactly the
    behaviour of the reference's single zero_grad / double backward /
    single Adam step (reference ddpg_learner.py:62-91).  TrainState.params
    is the *merged* tree here."""

    def step(state: TrainState, batch: Batch):
        def loss_fn(full):
            # critic TD loss (reference ddpg_learner.py:76-86)
            q = critic_apply_fn(full, batch.state0, batch.action)
            a_next = actor_apply_fn(state.target_params, batch.state1)
            q_next = critic_apply_fn(state.target_params, batch.state1, a_next)
            tgt = (batch.reward
                   + batch.gamma_n * q_next * (1.0 - batch.terminal1))
            critic_loss, td_abs = _value_loss(q, tgt, batch.weight, huber)
            # policy loss (reference ddpg_learner.py:66-74)
            a = actor_apply_fn(full, batch.state0)
            actor_loss = -jnp.mean(critic_apply_fn(full, batch.state0, a))
            return critic_loss + actor_loss, (critic_loss, actor_loss, td_abs)

        (_, (critic_loss, actor_loss, td_abs)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(state.params)
        grads = _pmean(grads, axis_name)
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        new_step = state.step + 1
        new_target = update_target(state.target_params, params, new_step,
                                   target_model_update)
        metrics = {
            "learner/critic_loss": critic_loss,
            "learner/actor_loss": actor_loss,
            "learner/grad_norm": global_norm(grads),
        }
        return (TrainState(params, new_target, opt_state, new_step),
                metrics, td_abs)

    return finite_guard(step) if guard else step


# ---------------------------------------------------------------------------
# DDPG param-tree surgery: the model is one Flax module whose top-level
# submodules are actor_* / critic_* (models/ddpg_mlp.py setup()); split so
# each optimizer owns exactly its net.
# ---------------------------------------------------------------------------

def split_ddpg_params(full: PyTree) -> Dict[str, PyTree]:
    inner = full["params"]
    actor = {k: v for k, v in inner.items() if k.startswith("actor")}
    critic = {k: v for k, v in inner.items() if k.startswith("critic")}
    assert actor and critic, f"unexpected DDPG param layout: {list(inner)}"
    return {"actor": {"params": actor}, "critic": {"params": critic}}


def merge_ddpg_params(actor: PyTree, critic: PyTree) -> PyTree:
    return {"params": {**actor["params"], **critic["params"]}}


def _pmean(tree: PyTree, axis_name: str | None) -> PyTree:
    """Mean-reduce gradients across a mesh axis (the ICI all-reduce).  Only
    needed under shard_map, where collectives are explicit; under plain jit
    with sharded batch inputs XLA inserts the all-reduce itself, and
    axis_name stays None."""
    if axis_name is None:
        return tree
    return jax.lax.pmean(tree, axis_name=axis_name)
