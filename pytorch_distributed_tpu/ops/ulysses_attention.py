"""Ulysses-style all-to-all sequence parallelism.

The second long-context strategy next to ring attention
(ops/ring_attention.py; no reference equivalent — the reference has no
attention at all).  Where the ring rotates K/V blocks and keeps the
sequence axis sharded throughout, Ulysses (Jacobs et al. 2023, DeepSpeed
Ulysses) re-shards: one all-to-all over the sp axis turns
time-sharded (B, H, T/n, D) into head-sharded (B, H/n, T, D), every device
runs plain full attention over its head subset with the ENTIRE sequence
visible, and a second all-to-all restores time sharding.

Trade-off vs the ring (why both exist): Ulysses moves Q, K, V and the
output once each (4 all-to-alls total) regardless of sequence length and
then runs the cheapest possible attention body; the ring moves K/V
``n-1`` times but never materialises full-T scores and supports head
counts smaller than the mesh axis.  Short-to-medium windows with enough
heads favor Ulysses; very long windows or few-head models favor the ring.

``ulysses_attention`` matches ``full_attention`` exactly up to fp
reduction order; the equivalence tests pin all three against each other
on the 8-virtual-device CPU mesh.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from pytorch_distributed_tpu.ops.ring_attention import (
    full_attention, sharded_attention_call,
)


def _ulysses_body(q, k, v, *, axis_name: str, causal: bool):
    # (B, H, T_local, D) time-sharded -> (B, H/n, T, D) head-sharded
    a2a = functools.partial(jax.lax.all_to_all, axis_name=axis_name,
                            split_axis=1, concat_axis=2, tiled=True)
    qf, kf, vf = a2a(q), a2a(k), a2a(v)
    out = full_attention(qf, kf, vf, causal=causal)
    # heads back together, time back to shards
    return jax.lax.all_to_all(out, axis_name=axis_name, split_axis=2,
                              concat_axis=1, tiled=True)


def ulysses_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                      mesh: Mesh, axis: str = "sp", causal: bool = True,
                      batch_axis: Optional[str] = "dp") -> jnp.ndarray:
    """Sequence-parallel attention via head/time all-to-all: (B, H, T, D)
    with T sharded over ``axis`` (and optionally B over ``batch_axis``).
    Requires H divisible by the sp axis size."""
    n = mesh.shape[axis]
    assert q.shape[1] % n == 0, (
        f"ulysses needs heads {q.shape[1]} divisible by mesh {axis}={n} "
        "(use ring attention for few-head models)")
    body = functools.partial(_ulysses_body, axis_name=axis, causal=causal)
    return sharded_attention_call(body, q, k, v, mesh, axis, batch_axis)
