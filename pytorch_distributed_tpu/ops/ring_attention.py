"""Ring attention: sequence-parallel attention over the mesh's sp axis.

No reference equivalent (the reference has no attention at all); this is
the long-context backbone the TPU framework provides for transformer
models over long windows (models/dtqn.py): the sequence axis is sharded
across devices, each device holds one Q/K/V block, and K/V blocks rotate
around the ring via ``jax.lax.ppermute`` over ICI while every device
accumulates its Q block's attention with a numerically stable online
softmax (the blockwise/flash recipe of Liu et al. 2023, "Ring Attention
with Blockwise Transformers").  Compute of step s overlaps the transfer
of step s+1's blocks — XLA pipelines the ppermute against the matmuls —
so the ring hides ICI latency behind MXU work.

Causality across blocks is resolved by carrying each K/V block's global
offset around the ring with it: a (Tq_local, Tk_local) position mask is
rebuilt per step from the query shard's offset and the visiting block's
offset.

``ring_attention`` is the sharded entry point (shard_map over an existing
mesh); ``full_attention`` is the single-device reference both tests and
small models use.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

NEG_INF = -1e30


def full_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                   causal: bool = True) -> jnp.ndarray:
    """Plain softmax attention, (B, H, T, D) in and out — the reference
    implementation ring_attention must match."""
    scale = q.shape[-1] ** -0.5
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        tq, tk = scores.shape[-2], scores.shape[-1]
        mask = jnp.tril(jnp.ones((tq, tk), bool), k=tk - tq)
        scores = jnp.where(mask, scores, NEG_INF)
    return jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(scores, axis=-1), v)


def _ring_body(q, k, v, *, axis_name: str, causal: bool, num_blocks: int):
    """Per-device shard_map body: online-softmax accumulation over the
    ring of K/V blocks."""
    scale = q.shape[-1] ** -0.5
    tq = q.shape[2]
    tk = k.shape[2]
    my = jax.lax.axis_index(axis_name)
    B, H = q.shape[0], q.shape[1]

    q_pos = my * tq + jnp.arange(tq)                     # global q positions

    def step(carry, _):
        k_blk, v_blk, blk_idx, m, l, o = carry
        k_pos = blk_idx * tk + jnp.arange(tk)
        scores = jnp.einsum("bhqd,bhkd->bhqk", q, k_blk) * scale
        if causal:
            mask = q_pos[:, None] >= k_pos[None, :]
            scores = jnp.where(mask[None, None], scores, NEG_INF)
        s_max = jnp.max(scores, axis=-1)                 # (B, H, tq)
        m_new = jnp.maximum(m, s_max)
        # guard: a fully-masked step keeps m at NEG_INF; exp(NEG_INF-
        # NEG_INF) must not produce NaN
        p = jnp.exp(scores - m_new[..., None])
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1)
        o_new = o * corr[..., None] + jnp.einsum("bhqk,bhkd->bhqd", p, v_blk)
        # rotate K/V (and their block index) to the next device over ICI
        perm = [(i, (i + 1) % num_blocks) for i in range(num_blocks)]
        k_next = jax.lax.ppermute(k_blk, axis_name, perm)
        v_next = jax.lax.ppermute(v_blk, axis_name, perm)
        idx_next = jax.lax.ppermute(blk_idx, axis_name, perm)
        return (k_next, v_next, idx_next, m_new, l_new, o_new), None

    init = (
        k, v, my,
        jnp.full((B, H, tq), NEG_INF, q.dtype),          # running max
        jnp.zeros((B, H, tq), q.dtype),                  # normalizer
        jnp.zeros_like(q),                               # output acc
    )
    (_, _, _, m, l, o), _ = jax.lax.scan(step, init, None,
                                         length=num_blocks)
    return o / jnp.maximum(l[..., None], 1e-30)


def ring_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                   mesh: Mesh, axis: str = "sp", causal: bool = True,
                   batch_axis: Optional[str] = "dp") -> jnp.ndarray:
    """Sequence-parallel attention: (B, H, T, D) with T sharded over
    ``axis`` (and optionally B over ``batch_axis``).  Matches
    ``full_attention`` up to fp reduction order."""
    num_blocks = mesh.shape[axis]
    bspec = batch_axis if (batch_axis and mesh.shape[batch_axis] > 1) \
        else None
    spec = P(bspec, None, axis, None)
    body = functools.partial(_ring_body, axis_name=axis, causal=causal,
                             num_blocks=num_blocks)
    fn = jax.shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec, check_vma=False)
    return fn(q, k, v)
