"""Ring attention: sequence-parallel attention over the mesh's sp axis.

No reference equivalent (the reference has no attention at all); this is
the long-context backbone the TPU framework provides for transformer
models over long windows (models/dtqn.py): the sequence axis is sharded
across devices, each device holds one Q/K/V block, and K/V blocks rotate
around the ring via ``jax.lax.ppermute`` over ICI while every device
accumulates its Q block's attention with a numerically stable online
softmax (the blockwise/flash recipe of Liu et al. 2023, "Ring Attention
with Blockwise Transformers").  Compute of step s overlaps the transfer
of step s+1's blocks — XLA pipelines the ppermute against the matmuls —
so the ring hides ICI latency behind MXU work.

Causality across blocks is resolved by carrying each K/V block's global
offset around the ring with it: a (Tq_local, Tk_local) position mask is
rebuilt per step from the query shard's offset and the visiting block's
offset.

``ring_attention`` is the sharded entry point (shard_map over an existing
mesh); ``full_attention`` is the single-device reference both tests and
small models use.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from pytorch_distributed_tpu.utils.helpers import shard_map

NEG_INF = -1e30


def full_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                   causal: bool = True,
                   key_pad_mask: Optional[jnp.ndarray] = None
                   ) -> jnp.ndarray:
    """Plain softmax attention, (B, H, T, D) in and out — the reference
    implementation ring_attention must match.  ``key_pad_mask`` (B, Tk)
    marks valid keys (models/dtqn.py masks unfilled acting-window slots
    with it)."""
    scale = q.shape[-1] ** -0.5
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    tq, tk = scores.shape[-2], scores.shape[-1]
    if causal:
        mask = jnp.tril(jnp.ones((tq, tk), bool), k=tk - tq)
        scores = jnp.where(mask, scores, NEG_INF)
    if key_pad_mask is not None:
        scores = jnp.where(key_pad_mask[:, None, None, :], scores, NEG_INF)
    return jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(scores, axis=-1), v)


def _ring_body(q, k, v, *, axis_name: str, causal: bool, num_blocks: int):
    """Per-device shard_map body: online-softmax accumulation over the
    ring of K/V blocks.  The device's own block is folded in before the
    loop, so the ring rotates exactly num_blocks - 1 times and the visiting
    block's identity is derived from the step counter (nothing but K/V
    rides the ring)."""
    scale = q.shape[-1] ** -0.5
    tq = q.shape[2]
    tk = k.shape[2]
    my = jax.lax.axis_index(axis_name)
    B, H = q.shape[0], q.shape[1]

    q_pos = my * tq + jnp.arange(tq)                     # global q positions

    def fold(acc, k_blk, v_blk, blk_idx):
        m, l, o = acc
        k_pos = blk_idx * tk + jnp.arange(tk)
        scores = jnp.einsum("bhqd,bhkd->bhqk", q, k_blk) * scale
        if causal:
            mask = q_pos[:, None] >= k_pos[None, :]
            scores = jnp.where(mask[None, None], scores, NEG_INF)
        s_max = jnp.max(scores, axis=-1)                 # (B, H, tq)
        m_new = jnp.maximum(m, s_max)
        p = jnp.exp(scores - m_new[..., None])
        # a row that has seen no unmasked key yet has m_new == NEG_INF and
        # exp(NEG_INF - NEG_INF) == 1 would accumulate garbage V; with the
        # own (causal-diagonal) block folded first this cannot happen for
        # equal q/k shards, but guard it rather than rely on the invariant
        p = jnp.where((m_new == NEG_INF)[..., None], 0.0, p)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1)
        o_new = o * corr[..., None] + jnp.einsum("bhqk,bhkd->bhqd", p,
                                                 v_blk)
        return m_new, l_new, o_new

    acc0 = (
        jnp.full((B, H, tq), NEG_INF, q.dtype),          # running max
        jnp.zeros((B, H, tq), q.dtype),                  # normalizer
        jnp.zeros_like(q),                               # output acc
    )
    acc = fold(acc0, k, v, my)                           # own block, step 0

    perm = [(i, (i + 1) % num_blocks) for i in range(num_blocks)]

    def step(carry, s):
        k_blk, v_blk, m, l, o = carry
        # rotate, then fold the block that just arrived (originally from
        # device (my - s) mod n)
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        m, l, o = fold((m, l, o), k_blk, v_blk,
                       (my - s) % num_blocks)
        return (k_blk, v_blk, m, l, o), None

    if num_blocks > 1:
        (_, _, m, l, o), _ = jax.lax.scan(
            step, (k, v, *acc), jnp.arange(1, num_blocks))
    else:
        m, l, o = acc
    return o / jnp.maximum(l[..., None], 1e-30)


def sharded_attention_call(body, q, k, v, mesh: Mesh, axis: str,
                           batch_axis: Optional[str]) -> jnp.ndarray:
    """Shared shard_map entry for the sequence-parallel strategies: T
    sharded over ``axis``, B optionally over ``batch_axis``; ``body`` is
    the per-device (q, k, v) -> out function (ring or Ulysses)."""
    bspec = batch_axis if (batch_axis and mesh.shape[batch_axis] > 1) \
        else None
    spec = P(bspec, None, axis, None)
    fn = shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                   out_specs=spec, check_vma=False)
    return fn(q, k, v)


def ring_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                   mesh: Mesh, axis: str = "sp", causal: bool = True,
                   batch_axis: Optional[str] = "dp") -> jnp.ndarray:
    """Sequence-parallel attention: (B, H, T, D) with T sharded over
    ``axis`` (and optionally B over ``batch_axis``).  Matches
    ``full_attention`` up to fp reduction order."""
    body = functools.partial(_ring_body, axis_name=axis, causal=causal,
                             num_blocks=mesh.shape[axis])
    return sharded_attention_call(body, q, k, v, mesh, axis, batch_axis)
