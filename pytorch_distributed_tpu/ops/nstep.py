"""n-step transition assembly.

The trickiest logic in the reference lives inline in its actor loops as four
parallel deques with a steady-state push of the [-2] window entry and a
two-case episode-end flush (reference core/single_processes/dqn_actor.py:
54-61, 110-122, 133-163) — and has no tests.  Here it is a standalone,
unit-tested component with the same semantics, defined constructively:

For each time step t of an episode, emit the n-step transition

    (s_t, a_t, R_t, gamma_m, s_{t+m}, term_{t+m}),
    R_t = sum_{k=0}^{m-1} gamma^k r_{t+k},  gamma_m = gamma^m,
    m = min(nstep, T - t)   (T = episode length)

i.e. windows shrink at the episode tail instead of bootstrapping across the
boundary, and the stored effective discount gamma_m is what the learner uses
for its bootstrap term (reference dqn_learner.py:73-74 with the per-sample
``gamma1s``).  Terminal flag is 1 iff the window reaches the true episode
end (so truncation via early_stop still bootstraps).

Two implementations:
- ``NStepAssembler`` — incremental/host-side, O(1) per step, used by actor
  processes;
- ``nstep_from_episode`` — vectorized over a whole recorded episode
  (numpy), used by tests as the ground truth and by batched/vector-env
  actors to convert rollout chunks in one shot.
"""

from __future__ import annotations

from collections import deque
from typing import List

import numpy as np

from pytorch_distributed_tpu.utils.experience import Transition


class NStepAssembler:
    """Feed (s, a, r, s', terminal, truncated) once per env step; yields zero
    or more finished n-step ``Transition``s per feed.  Call ``flush()`` (or
    feed a terminal step) at episode end."""

    def __init__(self, nstep: int, gamma: float):
        assert nstep >= 1
        self.nstep = nstep
        self.gamma = gamma
        self._buf: deque = deque()  # pending (s, a, r, s_last, term) windows

    def feed(self, state0, action, reward, state1, terminal: bool,
             truncated: bool = False, prov=None) -> List[Transition]:
        """``truncated`` marks episode ends that should still bootstrap
        (time-limit truncation): windows close but terminal stays 0.
        ``prov`` is the transition's provenance vector minted at THIS
        action (utils/experience.make_prov); it rides the window and is
        attached to the emitted row — emissions pop FIFO, so provenance
        stays aligned with the window that opened on its action."""
        self._buf.append([state0, action, 0.0, 0, state1, False, prov])
        # accumulate this reward into every open window
        for row in self._buf:
            row[2] += (self.gamma ** row[3]) * reward
            row[3] += 1
            row[4] = state1
        out: List[Transition] = []
        if terminal or truncated:
            # every open window closes at s_{T}; they are terminal iff the
            # episode truly ended (truncation still bootstraps)
            is_true_terminal = terminal and not truncated
            while self._buf:
                out.append(self._emit(self._buf.popleft(),
                                      terminal=is_true_terminal))
        else:
            # steady state: the oldest window reaches n steps
            while self._buf and self._buf[0][3] >= self.nstep:
                out.append(self._emit(self._buf.popleft(), terminal=False))
        return out

    def flush(self) -> List[Transition]:
        """Close all pending windows without a terminal (e.g. an actor
        shutting down mid-episode); emitted rows bootstrap from their last
        state."""
        out = [self._emit(row, terminal=False) for row in self._buf]
        self._buf.clear()
        return out

    def _emit(self, row, terminal: bool) -> Transition:
        state0, action, r_sum, m, state1, _, prov = row
        return Transition(
            state0=np.asarray(state0),
            action=np.asarray(action),
            reward=np.float32(r_sum),
            gamma_n=np.float32(self.gamma ** m),
            state1=np.asarray(state1),
            terminal1=np.float32(1.0 if terminal else 0.0),
            prov=prov,
        )

    def reset(self) -> None:
        self._buf.clear()

    @property
    def pending(self) -> int:
        return len(self._buf)


def nstep_from_episode(states: np.ndarray, actions: np.ndarray,
                       rewards: np.ndarray, nstep: int, gamma: float,
                       terminal: bool = True) -> Transition:
    """Vectorized ground truth over one episode.

    states: (T+1, ...) including the final state; actions/rewards: (T,).
    Returns a Transition batch of T rows.  ``terminal``=False marks a
    truncated episode (bootstrap through the last state).
    """
    T = len(rewards)
    assert states.shape[0] == T + 1
    m = np.minimum(nstep, T - np.arange(T))
    r_sum = np.zeros(T, dtype=np.float64)
    for k in range(nstep):
        valid = np.arange(T) + k < T
        r_sum[valid] += (gamma ** k) * rewards[np.arange(T)[valid] + k]
    end = np.arange(T) + m
    term = np.where(end == T, 1.0 if terminal else 0.0, 0.0)
    return Transition(
        state0=states[:T],
        action=actions,
        reward=r_sum.astype(np.float32),
        gamma_n=(gamma ** m).astype(np.float32),
        state1=states[end],
        terminal1=term.astype(np.float32),
    )
