"""Pallas TPU kernels: the fused dqn-cnn torso as hand-tiled MXU matmuls.

The MFU probe (tools/mfu_probe.py, BENCH_r03) attributes the flagship
learner's 0.15-0.17 MFU ceiling to two structural costs in XLA's conv
lowering of the Nature CNN: the 4/32/64-wide conv channels underfill the
128-lane MXU, and ~25% of device time goes to XLA's own re-tiling
(layout copies between conv ops).  This module attacks the second cost:
every GEMM in the torso — the three im2col'd convolutions, the FC-512
and the Q head — runs as ONE hand-tiled Pallas kernel each, with the
contraction and lane dimensions padded to the 128-lane grid ONCE at the
kernel boundary instead of re-tiled between every XLA op.  Patch
extraction (im2col) stays in XLA: strided slices are layout-friendly
and differentiate for free, so the kernel surface is exactly the GEMMs
the MXU runs.

Differentiability: the matmul kernel carries a ``jax.custom_vjp`` whose
backward is two more invocations of the same kernel (dx = g @ w^T,
dw = x^T @ g), so the whole torso trains through Pallas — forward AND
backward GEMMs bypass the re-tiling.

Numerics: accumulation is fp32 on the MXU (``preferred_element_type``),
outputs rounded to the compute dtype between layers, mirroring XLA's
bf16 conv behaviour; parity vs the XLA reference is tolerance-based
(tests/test_pallas_torso.py, fwd + grads, bf16 and fp32), not bitwise —
fp summation order inside a hand-tiled GEMM differs from XLA's.

CPU story: ``interpret=True`` runs the same kernels under the Pallas
interpreter so the tier-1 parity tests execute on this image; the
production gate (factory._dqn_train_apply) only engages the kernel on a
TPU backend (or under the explicit ``pallas_interpret`` knob) and
downgrades LOUDLY otherwise.  Knobs: config.LearnerPerfParams
(``TPU_APEX_MXU_PALLAS_TORSO`` / ``TPU_APEX_MXU_PALLAS_INTERPRET``).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp

# pallas imports deferred so CPU-only environments that never touch the
# kernels don't pay for (or break on) experimental imports at module
# load — the ops/pallas_sampling.py convention
pl = None
pltpu = None


def _ensure_pallas() -> None:
    global pl, pltpu
    if pl is None:
        from jax.experimental import pallas as _pl
        from jax.experimental.pallas import tpu as _pltpu

        pl = _pl
        pltpu = _pltpu


def _round_up(n: int, m: int) -> int:
    return -(-n // m) * m


def _mm_kernel(x_ref, w_ref, o_ref):
    """One grid step = one (TM, TK) x-tile @ one (TK, Np) w-tile,
    accumulated into the (TM, Np) output tile across the contraction
    grid axis (the output block is revisited for every k-step; fp32
    accumulation on the MXU)."""
    @pl.when(pl.program_id(1) == 0)
    def _init():
        o_ref[:] = jnp.zeros_like(o_ref)

    o_ref[:] += jnp.dot(x_ref[:], w_ref[:],
                        preferred_element_type=jnp.float32)


# one (M-tile, K-tile) block per grid step.  BOTH dims are tiled: the
# backward dw = x^T @ g GEMM contracts over B*OH*OW rows (51k at the
# production batch 128 on Conv_0), so an untiled contraction dim would
# stage ~26 MB x-tiles and blow the ~16 MB VMEM budget on exactly the
# TPU the kernel targets.  Worst resident set per step is now
# (TM, TK) + (TK, Np) + (TM, Np) — ~1.5 MB at the FC-512's Np=512.
_TILE_M = 128
_TILE_K = 512
_LANES = 128


def _mm(x: jax.Array, w: jax.Array, interpret: bool) -> jax.Array:
    """Padded, tiled ``x (M, K) @ w (K, N) -> (M, N) fp32`` through the
    Pallas kernel.  Pads K and N up to the 128-lane grid and M up to the
    tile height ONCE here — the re-tiling XLA would otherwise re-derive
    between ops happens exactly once per GEMM."""
    _ensure_pallas()
    m, k = x.shape
    k2, n = w.shape
    assert k == k2, (x.shape, w.shape)
    tk = min(_TILE_K, _round_up(k, _LANES))
    kp, np_ = _round_up(k, tk), _round_up(n, _LANES)
    mp = _round_up(m, _TILE_M)
    if (mp, kp) != (m, k):
        x = jnp.pad(x, ((0, mp - m), (0, kp - k)))
    if (kp, np_) != (k, n):
        w = jnp.pad(w, ((0, kp - k), (0, np_ - n)))
    out = pl.pallas_call(
        _mm_kernel,
        grid=(mp // _TILE_M, kp // tk),
        in_specs=[
            pl.BlockSpec((_TILE_M, tk), lambda i, j: (i, j)),
            pl.BlockSpec((tk, np_), lambda i, j: (j, 0)),
        ],
        out_specs=pl.BlockSpec((_TILE_M, np_), lambda i, j: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((mp, np_), jnp.float32),
        interpret=interpret,
    )(x, w)
    return out[:m, :n]


@functools.lru_cache(maxsize=4)
def make_mxu_matmul(interpret: bool = False):
    """A differentiable ``(x, w) -> x @ w`` whose forward and backward
    GEMMs all run through the hand-tiled kernel (custom VJP: dx = g @
    w^T, dw = x^T @ g).  Cached per interpret flag so repeated apply
    builds share one jaxpr identity."""

    @jax.custom_vjp
    def mm(x, w):
        return _mm(x, w, interpret)

    def fwd(x, w):
        return _mm(x, w, interpret), (x, w)

    def bwd(res, g):
        x, w = res
        g = g.astype(jnp.float32)
        dx = _mm(g, w.T.astype(jnp.float32), interpret).astype(x.dtype)
        dw = _mm(x.T.astype(jnp.float32), g, interpret).astype(w.dtype)
        return dx, dw

    mm.defvjp(fwd, bwd)
    return mm


def _patches(x: jax.Array, k: int, stride: int) -> jax.Array:
    """im2col: (B, H, W, C) -> (B, OH, OW, k*k*C) with patch features in
    (kh, kw, c) order — exactly ``kernel.reshape(k*k*C, out)``'s HWIO
    flattening, so the GEMM consumes the flax Conv kernel verbatim."""
    h, w = x.shape[1], x.shape[2]
    oh = (h - k) // stride + 1
    ow = (w - k) // stride + 1
    cols = []
    for di in range(k):
        for dj in range(k):
            cols.append(x[:, di:di + oh * stride:stride,
                          dj:dj + ow * stride:stride, :])
    return jnp.concatenate(cols, axis=-1)


# the Nature-CNN torso geometry the kernel serves (models/dqn_cnn.py):
# (flax param scope, kernel size, stride)
_CONV_LAYERS: Tuple[Tuple[str, int, int], ...] = (
    ("Conv_0", 8, 4), ("Conv_1", 4, 2), ("Conv_2", 3, 1),
)


def build_pallas_torso_apply(norm_val: float = 255.0,
                             compute_dtype=jnp.bfloat16,
                             nhwc_input: bool = False,
                             interpret: bool = False):
    """The learner-side ``(variables, obs) -> q`` apply running the
    whole dqn-cnn torso through the MXU matmul kernel.

    Consumes the EXACT DqnCnnModel param tree (Conv_0/1/2 + Dense_0/1),
    so checkpoints, the ParamStore publication plane and the actors'
    standard apply are untouched — only the learner's train program
    swaps its torso.  Wired by factory._dqn_train_apply behind the
    ``pallas_torso`` knob."""
    mm = make_mxu_matmul(interpret)

    def apply_fn(variables, x):
        p = variables["params"]
        x = x.astype(compute_dtype) / jnp.asarray(norm_val,
                                                  dtype=compute_dtype)
        if not nhwc_input:
            x = jnp.transpose(x, (0, 2, 3, 1))
        for name, k, stride in _CONV_LAYERS:
            ker = p[name]["kernel"]
            bias = p[name]["bias"]
            pat = _patches(x, k, stride)
            b, oh, ow, feat = pat.shape
            cout = ker.shape[-1]
            y = mm(pat.reshape(b * oh * ow, feat).astype(compute_dtype),
                   ker.reshape(feat, cout).astype(compute_dtype))
            y = y.astype(compute_dtype) + bias.astype(compute_dtype)
            x = jax.nn.relu(y).reshape(b, oh, ow, cout)
        b = x.shape[0]
        x = x.reshape(b, -1)
        y = mm(x, p["Dense_0"]["kernel"].astype(compute_dtype))
        x = jax.nn.relu(y.astype(compute_dtype)
                        + p["Dense_0"]["bias"].astype(compute_dtype))
        q = mm(x, p["Dense_1"]["kernel"].astype(compute_dtype))
        q = (q.astype(compute_dtype)
             + p["Dense_1"]["bias"].astype(compute_dtype))
        return q.astype(jnp.float32)

    return apply_fn
