"""Pallas TPU kernel: hierarchical inverse-CDF sampling for prioritized
replay.

The XLA path in memory/device_per.py draws proportional samples by
materializing the full N-row cumulative sum every learner step
(``cumsum`` + ``searchsorted`` over the whole priority vector,
device_per.py per_sample).  At Atari-57 scale (N in the millions) that is
an O(N) HBM write + read per step for 128 draws.  The hierarchical scheme
here does the O(N) work once as a block *reduction* (no cumsum
materialization) and then touches only one priority block per draw:

1. (XLA) ``block_sums[b] = sum(priority[b*K:(b+1)*K])`` — a reduction XLA
   fuses, output is N/K floats;
2. (XLA) tiny ``cumsum`` + ``searchsorted`` over the N/K block sums picks
   the block and residual target per draw;
3. (Pallas) one kernel instance per draw DMAs exactly its block row from
   HBM to VMEM (scalar-prefetched block index steers the BlockSpec
   index_map), runs the in-block inverse-CDF scan on the VPU, and emits
   the local offset.

Exact-equivalence contract: for identical uniforms the hierarchical
sampler returns exactly the inverse-CDF index of the flat scheme (modulo
fp addition order inside a block), verified in tests against the flat
reference in interpret mode.

Sharding note: the kernel addresses the priority vector as one local
array, so the Pallas path engages only when replay rows are unsharded
(single-chip, or replicated rings).  dp-sharded rings keep the XLA path —
per-chip sampling work there is N/ndev and the gather already rides the
same collectives as the row fetch.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp

DEFAULT_BLOCK = 1024  # one f32 min-tile superblock (8 x 128); 4 KB per draw


def _tril(n: int, strict: bool = False):
    """Lower-triangular ones, built from 2D iotas (1D iota does not lower
    on TPU)."""
    r = jax.lax.broadcasted_iota(jnp.int32, (n, n), 0)
    c = jax.lax.broadcasted_iota(jnp.int32, (n, n), 1)
    return ((c < r) if strict else (c <= r)).astype(jnp.float32)


def _draw_kernel(block_ids_ref, targets_ref, prio_ref, out_ref):
    """One grid step = one draw: in-superblock inverse-CDF search.

    ``prio_ref`` is the (1, 8, SUB) priority superblock the index_map
    selected from this draw's scalar-prefetched block id (8 sublanes x SUB
    lanes — the min f32 tile); ``targets_ref`` holds the residual target
    u - block_cdf[b-1].  Pallas TPU has no cumsum lowering, so prefix sums
    run as triangular matmuls on the MXU: P = tile @ L^T gives in-row
    inclusive prefixes, a strict-triangular 8x8 matvec gives row offsets;
    the row-major global prefix G then yields the index as a pure
    count(G <= t) reduction — no dynamic indexing anywhere.
    """
    i = pl.program_id(0)
    tile = prio_ref[0]                                   # (8, SUB)
    sub = tile.shape[1]
    t = targets_ref[i]
    pref = jax.lax.dot_general(
        tile, _tril(sub), (((1,), (1,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32)              # in-row prefixes
    row_sums = jnp.sum(tile, axis=1, keepdims=True)      # (8, 1)
    offs = jax.lax.dot_general(
        _tril(tile.shape[0], strict=True), row_sums,
        (((1,), (0,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32)              # (8, 1) exclusive
    g = pref + offs                                      # row-major prefix
    local = jnp.sum((g <= t).astype(jnp.int32))
    out_ref[i] = jnp.minimum(local, tile.shape[0] * sub - 1)


# pallas imports deferred so CPU-only environments that never touch the
# TPU path don't pay for (or break on) experimental imports at module load
pl = None
pltpu = None


def _ensure_pallas() -> None:
    global pl, pltpu
    if pl is None:
        from jax.experimental import pallas as _pl
        from jax.experimental.pallas import tpu as _pltpu

        pl = _pl
        pltpu = _pltpu


@functools.partial(jax.jit,
                   static_argnames=("batch_size", "block", "interpret"))
def hierarchical_sample(priority: jax.Array, key: jax.Array,
                        batch_size: int, block: int = DEFAULT_BLOCK,
                        interpret: bool = False
                        ) -> Tuple[jax.Array, jax.Array]:
    """Proportional sample of ``batch_size`` indices from an (N,) priority
    vector (zeros = empty rows, never drawn).  Returns (idx, probs).
    """
    _ensure_pallas()
    n = priority.shape[0]
    sub = block // 8  # lanes per sublane row; superblock = 8 x sub = block
    assert block % 8 == 0 and sub % 128 == 0, block
    num_blocks = -(-n // block)
    padded = num_blocks * block
    p = priority
    if padded != n:
        p = jnp.pad(priority, (0, padded - n))
    p3 = p.reshape(num_blocks, 8, sub)

    # phase 1+2 (XLA): block reduction + tiny top-level inverse CDF
    block_sums = p3.sum(axis=(1, 2))
    block_cdf = jnp.cumsum(block_sums)
    total = block_cdf[-1]
    u = jax.random.uniform(key, (batch_size,)) * total
    bid = jnp.clip(jnp.searchsorted(block_cdf, u, side="right"),
                   0, num_blocks - 1).astype(jnp.int32)
    prev = jnp.where(bid > 0, block_cdf[bid - 1], 0.0)
    targets = (u - prev).astype(jnp.float32)

    # phase 3 (Pallas): per-draw in-superblock scan; one (8, sub) DMA per
    # draw.  Each grid step emits one scalar, so the output lives whole in
    # SMEM and every step writes its own slot (sequential grid => no write
    # races).
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,  # block_ids, targets
        grid=(batch_size,),
        in_specs=[
            pl.BlockSpec((1, 8, sub), lambda i, bids, tgts: (bids[i], 0, 0)),
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.SMEM),
    )
    local = pl.pallas_call(
        _draw_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((batch_size,), jnp.int32),
        interpret=interpret,
    )(bid, targets, p3)

    idx = jnp.minimum(bid * block + local, n - 1)
    # fp-order disagreement between the XLA block reduction and the MXU
    # prefix sums can (rarely, at a block's upper CDF edge) clamp a draw
    # onto a zero-priority row; a 0-prob draw would blow up its IS weight
    # and let the priority write-back make an empty row drawable, so remap
    # those draws to the max-priority row instead.
    fallback = jnp.argmax(priority).astype(jnp.int32)
    idx = jnp.where(priority[idx] > 0, idx, fallback)
    probs = priority[idx] / jnp.maximum(total, 1e-12)
    return idx, probs


def flat_sample(priority: jax.Array, key: jax.Array, batch_size: int
                ) -> Tuple[jax.Array, jax.Array]:
    """The flat XLA reference scheme (device_per.py per_sample's search),
    exposed here so tests can pin hierarchical == flat on shared
    uniforms."""
    cdf = jnp.cumsum(priority)
    total = cdf[-1]
    u = jax.random.uniform(key, (batch_size,)) * total
    idx = jnp.clip(jnp.searchsorted(cdf, u, side="right"),
                   0, priority.shape[0] - 1).astype(jnp.int32)
    return idx, priority[idx] / jnp.maximum(total, 1e-12)
