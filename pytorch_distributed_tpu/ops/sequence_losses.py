"""R2D2 sequence update as one pure XLA program.

The recurrent counterpart of ops/losses.py build_dqn_train_step: consumes
a SegmentBatch (memory/sequence_replay.py), runs

    burn-in unroll (stored state, gradients stopped)
    -> train-window unroll (online + target nets)
    -> within-window n-step double-DQN targets with value rescaling
    -> masked, IS-weighted MSE
    -> Adam -> target update

all under one jit.  Key R2D2 mechanics (Kapturowski et al. 2019), each a
flag so ablations stay possible:

- **stored state + burn-in**: the sampled segment carries the actor's LSTM
  state at its first step; the first ``burn_in`` steps are replayed only
  to refresh that state under current weights (both online and target
  nets), no loss on them.
- **value rescaling**: targets use h(x) = sign(x)(sqrt(|x|+1)-1) + eps*x
  and its closed-form inverse instead of reward clipping.
- **sequence priorities**: eta-blended max/mean of per-step |TD| over
  valid steps, returned as ``td_abs`` for the replay's write-back — the
  same contract Batch-based steps use, so the learner loop is unchanged.

``lax.scan`` carries the LSTM over time (compiler-friendly control flow —
no Python loop over T); the n-step lookahead is a static unroll over
``nstep`` shifted views (nstep is small and static).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Tuple

import jax
import jax.numpy as jnp
import optax

from pytorch_distributed_tpu.memory.sequence_replay import SegmentBatch
from pytorch_distributed_tpu.ops.losses import TrainState
from pytorch_distributed_tpu.utils.health import finite_guard
from pytorch_distributed_tpu.utils.helpers import global_norm, update_target

PyTree = Any

RESCALE_EPS = 1e-3


def value_rescale(x: jnp.ndarray, eps: float = RESCALE_EPS) -> jnp.ndarray:
    return jnp.sign(x) * (jnp.sqrt(jnp.abs(x) + 1.0) - 1.0) + eps * x


def value_unrescale(x: jnp.ndarray, eps: float = RESCALE_EPS) -> jnp.ndarray:
    # closed-form inverse of value_rescale
    return jnp.sign(x) * (
        jnp.square((jnp.sqrt(1.0 + 4.0 * eps * (jnp.abs(x) + 1.0 + eps))
                    - 1.0) / (2.0 * eps)) - 1.0)


def unpack_frame_stacks(frames: jnp.ndarray, C: int,
                        seq_len: int) -> jnp.ndarray:
    """Rebuild C-stacked observations from a frame-packed segment
    (memory/sequence_replay.py SegmentBuilder pack_frames): frames
    (B, T+C, H, W) -> stacks (B, T+1, C, H, W), stack t = frames
    [t, t+C) with channel 0 oldest — exactly the env's frame-stack
    layout.  Runs inside the jitted step: the C-fold de-duplication
    lives on the wire/host, the redundancy is re-materialised only in
    device HBM where it is cheap."""
    return jnp.stack([frames[:, i:i + seq_len + 1] for i in range(C)],
                     axis=2)


def unroll(apply_fn: Callable, params: PyTree, carry,
           obs_tm: jnp.ndarray) -> Tuple[Any, jnp.ndarray]:
    """Scan the single-step recurrent apply over a time-major observation
    sequence (T, B, *S) -> (carry_out, q_seq (T, B, A))."""

    def step(c, o):
        q, c2 = apply_fn(params, o, c)
        return c2, q

    return jax.lax.scan(step, carry, obs_tm)


def nstep_window_returns(boot: jnp.ndarray, r_tm: jnp.ndarray,
                         d_tm: jnp.ndarray, m_tm: jnp.ndarray, *,
                         nstep: int, gamma: float) -> jnp.ndarray:
    """Within-window n-step returns, shared by the DRQN and DTQN steps.

    For each window position t:
        G_t = sum_{k<K} gamma^k r_{t+k} * alive_{t,k}
              + gamma^K * alive_{t,K} * boot_{t+K}
    with K = min(nstep, n_valid - t, L - t) — the lookahead shrinks at the
    window end AND at masked tails (truncated episodes end their segment
    without a terminal, so the bootstrap comes from the last valid
    position's successor obs, which SegmentBuilder stores right after the
    tail) — and alive_{t,k} = prod_{j<k} (1 - terminal_{t+j}) zeroing the
    bootstrap past real deaths.  ``boot`` is (L+1, B) already unrescaled;
    r/d/m are time-major (L, B).
    """
    L = r_tm.shape[0]
    pad = lambda x: jnp.concatenate(
        [x, jnp.zeros((nstep, *x.shape[1:]), x.dtype)], axis=0)
    r_p, d_p, m_p = pad(r_tm), pad(d_tm), pad(m_tm)
    ret = jnp.zeros_like(r_tm)
    alive = jnp.ones_like(r_tm)
    for k in range(nstep):  # static unroll; nstep is small
        ret = ret + (gamma ** k) * r_p[k:k + L] * alive * m_p[k:k + L]
        alive = alive * (1.0 - d_p[k:k + L])
    idx_t = jnp.arange(L)[:, None]                               # (L, 1)
    n_valid = jnp.sum(m_tm, axis=0).astype(jnp.int32)            # (B,)
    boot_idx = jnp.minimum(jnp.minimum(idx_t + nstep, n_valid[None, :]), L)
    boot_at = jnp.take_along_axis(boot, boot_idx, axis=0)        # (L, B)
    K = jnp.maximum(boot_idx - idx_t, 0).astype(jnp.float32)
    return ret + (gamma ** K) * alive * boot_at


def _masked_loss_and_priority(q_sel, target, m_tm, weight, eta):
    """IS-weighted masked MSE + eta-blended per-sequence priorities."""
    td = q_sel - jax.lax.stop_gradient(target)
    w = weight[None, :]
    loss = jnp.sum(jnp.square(td) * m_tm * w) / jnp.maximum(
        jnp.sum(m_tm), 1.0)
    td_abs = jnp.abs(td) * m_tm
    valid = jnp.maximum(jnp.sum(m_tm, axis=0), 1.0)
    seq_pr = (eta * jnp.max(td_abs, axis=0)
              + (1 - eta) * jnp.sum(td_abs, axis=0) / valid)
    return loss, seq_pr


def _bootstrap_values(q_tm, q_target_tm, enable_double, h_inv):
    """Per-position bootstrap values (double-DQN optional), unrescaled."""
    if enable_double:
        a_star = jnp.argmax(q_tm, axis=-1)
        boot = jnp.take_along_axis(q_target_tm, a_star[..., None],
                                   axis=-1)[..., 0]
    else:
        boot = jnp.max(q_target_tm, axis=-1)
    return h_inv(boot)


def _apply_update(state, grads, loss, seq_pr, q_mean, tx,
                  target_model_update, extra_metrics=None):
    updates, opt_state = tx.update(grads, state.opt_state, state.params)
    params = optax.apply_updates(state.params, updates)
    new_step = state.step + 1
    target_params = update_target(state.target_params, params, new_step,
                                  target_model_update)
    metrics = {
        "learner/critic_loss": loss,
        "learner/q_mean": q_mean,
        "learner/grad_norm": global_norm(grads),
    }
    if extra_metrics:
        metrics.update(extra_metrics)
    return (TrainState(params, target_params, opt_state, new_step),
            metrics, seq_pr)


def build_drqn_train_step(
    apply_fn: Callable,
    tx: optax.GradientTransformation,
    *,
    burn_in: int = 10,
    nstep: int = 5,
    gamma: float = 0.99,
    enable_double: bool = True,
    target_model_update: float = 2500,
    rescale_values: bool = True,
    priority_eta: float = 0.9,
    axis_name: str | None = None,
    packed_frames: int = 0,
    guard: bool = True,
) -> Callable[[TrainState, SegmentBatch],
              Tuple[TrainState, Dict[str, jnp.ndarray], jnp.ndarray]]:
    """Returns ``(state, batch) -> (state, metrics, seq_priorities)``.

    ``packed_frames=C``: ``batch.obs`` arrives frame-packed (B, T+C, H,
    W) and the stacks are rebuilt on device (unpack_frame_stacks) — the
    R2D2 pixel path's host->device transfer shrinks ~C-fold."""

    h = value_rescale if rescale_values else (lambda x: x)
    h_inv = value_unrescale if rescale_values else (lambda x: x)

    def step(state: TrainState, batch: SegmentBatch):
        T = batch.action.shape[1]
        obs = batch.obs
        if packed_frames:
            obs = unpack_frame_stacks(obs, packed_frames, T)
        obs_tm = jnp.moveaxis(obs, 0, 1)            # (T+1, B, *S)
        train_len = T - burn_in
        carry0 = (batch.c0, batch.h0)

        # target-side state refresh + full unroll (no gradients flow here)
        tcarry, _ = (unroll(apply_fn, state.target_params, carry0,
                            obs_tm[:burn_in])
                     if burn_in else (carry0, None))
        _, q_target_tm = unroll(apply_fn, state.target_params, tcarry,
                                obs_tm[burn_in:])   # (train_len+1, B, A)

        # time-major views of the train window
        a_tm = jnp.moveaxis(batch.action, 0, 1)[burn_in:]        # (L, B)
        r_tm = jnp.moveaxis(batch.reward, 0, 1)[burn_in:]
        d_tm = jnp.moveaxis(batch.terminal, 0, 1)[burn_in:]
        m_tm = jnp.moveaxis(batch.mask, 0, 1)[burn_in:]

        def loss_fn(params):
            bcarry, _ = (unroll(apply_fn, params, carry0, obs_tm[:burn_in])
                         if burn_in else (carry0, None))
            bcarry = jax.lax.stop_gradient(bcarry)
            _, q_tm = unroll(apply_fn, params, bcarry, obs_tm[burn_in:])
            q_sel = jnp.take_along_axis(
                q_tm[:train_len], a_tm[..., None].astype(jnp.int32),
                axis=-1)[..., 0]                                  # (L, B)
            boot = _bootstrap_values(q_tm, q_target_tm, enable_double,
                                     h_inv)                       # (L+1, B)
            target = h(nstep_window_returns(boot, r_tm, d_tm, m_tm,
                                            nstep=nstep, gamma=gamma))
            loss, seq_pr = _masked_loss_and_priority(
                q_sel, target, m_tm, batch.weight, priority_eta)
            return loss, (seq_pr, jnp.mean(jnp.max(q_tm, axis=-1)))

        (loss, (seq_pr, q_mean)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(state.params)
        if axis_name is not None:
            grads = jax.lax.pmean(grads, axis_name)
        return _apply_update(state, grads, loss, seq_pr, q_mean, tx,
                             target_model_update)

    return finite_guard(step) if guard else step


def build_dtqn_train_step(
    window_apply: Callable,
    tx: optax.GradientTransformation,
    *,
    burn_in: int = 10,
    nstep: int = 5,
    gamma: float = 0.99,
    enable_double: bool = True,
    target_model_update: float = 2500,
    rescale_values: bool = True,
    priority_eta: float = 0.9,
    axis_name: str | None = None,
    aux_weight: float = 0.0,
    target_window_apply: Callable | None = None,
    guard: bool = True,
) -> Callable[[TrainState, SegmentBatch],
              Tuple[TrainState, Dict[str, jnp.ndarray], jnp.ndarray]]:
    """Transformer (DTQN) sequence update: same contract as
    build_drqn_train_step but ONE causal pass per segment instead of a
    time scan — ``window_apply(params, obs_seq (B,T+1,*S)) -> (B,T+1,A)``
    (models/dtqn.py window_q).  There is no stored recurrent state: the
    burn-in prefix participates as attention context only (positions
    before ``burn_in`` are excluded from the loss).

    MoE models (models/moe.py) pass a ``window_apply`` returning
    ``(q, aux)`` instead — the auxiliary load-balancing loss joins the TD
    loss with weight ``aux_weight`` and surfaces as
    ``learner/moe_aux``.  ``target_window_apply``, when given, evaluates
    the target-network pass — MoE passes a q-only apply here so the
    frozen pass skips the mutable sow collection whose aux value is
    discarded anyway (round-2 advisor finding)."""

    h = value_rescale if rescale_values else (lambda x: x)
    h_inv = value_unrescale if rescale_values else (lambda x: x)

    def split_apply(params, obs):
        out = window_apply(params, obs)
        # tuple-vs-array is static python structure, resolved at trace time
        return out if isinstance(out, tuple) else (out, jnp.float32(0.0))

    def target_apply(params, obs):
        if target_window_apply is not None:
            return target_window_apply(params, obs)
        return split_apply(params, obs)[0]

    def step(state: TrainState, batch: SegmentBatch):
        T = batch.action.shape[1]
        train_len = T - burn_in
        # (L+1, B, A) over the train window, burn-in kept as context
        to_tm = lambda q: jnp.moveaxis(q, 0, 1)[burn_in:]
        q_target_tm = to_tm(target_apply(state.target_params, batch.obs))

        a_tm = jnp.moveaxis(batch.action, 0, 1)[burn_in:]
        r_tm = jnp.moveaxis(batch.reward, 0, 1)[burn_in:]
        d_tm = jnp.moveaxis(batch.terminal, 0, 1)[burn_in:]
        m_tm = jnp.moveaxis(batch.mask, 0, 1)[burn_in:]

        def loss_fn(params):
            q, aux = split_apply(params, batch.obs)
            q_tm = to_tm(q)
            q_sel = jnp.take_along_axis(
                q_tm[:train_len], a_tm[..., None].astype(jnp.int32),
                axis=-1)[..., 0]
            boot = _bootstrap_values(q_tm, q_target_tm, enable_double,
                                     h_inv)
            target = h(nstep_window_returns(boot, r_tm, d_tm, m_tm,
                                            nstep=nstep, gamma=gamma))
            loss, seq_pr = _masked_loss_and_priority(
                q_sel, target, m_tm, batch.weight, priority_eta)
            loss = loss + aux_weight * aux
            return loss, (seq_pr, jnp.mean(jnp.max(q_tm, axis=-1)), aux)

        (loss, (seq_pr, q_mean, aux)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(state.params)
        if axis_name is not None:
            grads = jax.lax.pmean(grads, axis_name)
        extra = {"learner/moe_aux": aux} if aux_weight else None
        return _apply_update(state, grads, loss, seq_pr, q_mean, tx,
                             target_model_update, extra)

    return finite_guard(step) if guard else step
