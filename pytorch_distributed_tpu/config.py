"""Configuration system.

TPU-native re-design of the reference's config layer (``utils/options.py`` in
the reference repo: the ``CONFIGS`` 5-tuple table at :10-14 and the
``Params``/``EnvParams``/``MemoryParams``/``ModelParams``/``AgentParams``/
``Options`` class hierarchy at :16-175).

Differences from the reference, on purpose:

- Plain frozen-by-convention dataclasses instead of mutually-inheriting
  classes with class-attribute singletons; an ``Options`` instance is an
  explicit value that is passed around (and pickled across process spawns).
- A real CLI (``--config``, ``--mode``, ``--num-actors``, ...) in
  ``main.py`` on top of the table — the reference is edit-the-file only
  (reference ``README.md:41-49``).
- Hyperparameter *values* mirror the reference defaults exactly
  (reference ``utils/options.py:108-168``) so learning behaviour is
  comparable; each is annotated with its reference source.
"""

from __future__ import annotations

import dataclasses
import os
import time
from dataclasses import dataclass, field
from typing import Any, Optional, Tuple

# ---------------------------------------------------------------------------
# The CONFIGS table: each row bundles compatible component choices, exactly
# like reference utils/options.py:10-14 —
#   [agent_type, env_type, game, memory_type, model_type]
# Rows 0 is the reference's only row (dqn/atari/pong/shared/dqn-cnn).  The
# extra rows cover the driver BASELINE.json tracked configs plus self-
# contained debug/bench envs that need no ALE install.
# ---------------------------------------------------------------------------
CONFIGS = [
    # agent_type, env_type,    game,          memory_type, model_type
    ["dqn",       "atari",     "pong",        "shared",    "dqn-cnn"],   # 0 (reference row 0)
    ["dqn",       "fake",      "chain",       "shared",    "dqn-mlp"],   # 1 smoke/debug
    ["ddpg",      "classic",   "pendulum",    "shared",    "ddpg-mlp"],  # 2
    ["dqn",       "classic",   "cartpole",    "shared",    "dqn-mlp"],   # 3
    ["dqn",       "pong-sim",  "pong",        "shared",    "dqn-cnn"],   # 4 ALE-free Pong clone
    ["dqn",       "atari",     "breakout",    "shared",    "dqn-cnn"],   # 5
    ["dqn",       "pong-sim",  "pong",        "prioritized", "dqn-cnn"], # 6 PER
    ["dqn",       "atari",     "pong",        "prioritized", "dqn-cnn"], # 7 PER on ALE
    ["dqn",       "pong-sim",  "pong",        "device",      "dqn-cnn"], # 8 HBM replay (flagship TPU)
    ["ddpg",      "gym",       "halfcheetah", "shared",      "ddpg-mlp"],# 9  (BASELINE config 4; needs gym+mujoco)
    ["ddpg",      "gym",       "humanoid",    "shared",      "ddpg-mlp"],# 10 (BASELINE config 5; needs gym+mujoco)
    ["dqn",       "atari",     "breakout",    "device",      "dqn-cnn"], # 11 Atari-57 sweep row (needs ALE)
    ["dqn",       "pong-sim",  "pong",        "device-per",  "dqn-cnn"], # 12 HBM PER, fully fused
    ["r2d2",      "fake",      "chain",       "sequence",    "drqn-mlp"],# 13 recurrent smoke
    ["r2d2",      "pong-sim",  "pong",        "device-sequence", "drqn-cnn"],# 14 R2D2 pixels, HBM segment ring
    ["r2d2",      "fake",      "chain",       "sequence",    "dtqn-mlp"],# 15 transformer Q (DTQN)
    ["ddpg",      "classic",   "reacher",     "shared",      "ddpg-mlp"],# 16 multi-dim continuous control
    ["r2d2",      "fake",      "chain",       "sequence",    "dtqn-moe"],# 17 MoE transformer Q (expert parallel)
    ["r2d2",      "fake",      "chain",       "sequence",    "dtqn-pipe"],# 18 staged transformer Q (pipeline parallel)
    ["dqn",       "pong-sim",  "pong",        "device-per",  "dqn-cnn-wide"],# 19 MXU-filling wide torso (ISSUE 13)
]


# ---------------------------------------------------------------------------
# The env-knob declaration table (ISSUE 9).  Every TPU_APEX_* / *_FAULTS
# environment variable the fleet reads MUST have a row here — (name,
# where-read, one-line doc) — and a matching row in the knob tables of
# README.md and TESTING.md.  ``tools/apexlint.py`` (knob-registry rule)
# mechanically diffs this table against the env reads it finds in code
# and against both docs, in BOTH directions: an undeclared read, a
# declared-but-never-read row, and an undocumented knob are each
# findings.  Names ending in ``*`` declare a family (per-field override
# planes built from a prefix constant); ``*_FAULTS`` is the per-plane
# fault-injection suffix family.  Plain string tuples on purpose: the
# linter parses this literal via ast, no import.
# ---------------------------------------------------------------------------
KNOBS = (
    ("TPU_APEX_PERF", "utils/perf.py",
     "master perf-plane switch (shorthand for TPU_APEX_PERF_ENABLED)"),
    ("TPU_APEX_PERF_*", "utils/perf.py",
     "per-field PerfParams overrides (e.g. TPU_APEX_PERF_PEAK_FLOPS)"),
    ("TPU_APEX_TRACE", "utils/tracing.py",
     "chunk tracing on/off (default on; 0 ships plain chunks)"),
    ("TPU_APEX_TRACE_SAMPLE", "utils/tracing.py",
     "per-event span row sampling rate"),
    ("TPU_APEX_QUARANTINE", "utils/health.py",
     "process-wide ingest-quarantine kill switch"),
    ("TPU_APEX_HEALTH_*", "utils/health.py",
     "per-field HealthParams overrides (e.g. TPU_APEX_HEALTH_HANG_DEADLINE)"),
    ("TPU_APEX_PROFILE", "utils/profiling.py",
     "directory for TensorBoard-viewable device traces"),
    ("TPU_APEX_BLACKBOX_DIR", "utils/flight_recorder.py",
     "blackbox dump directory, exported to spawn children"),
    ("TPU_APEX_RUN_ID", "utils/flight_recorder.py",
     "run id stamped on blackbox dumps + quarantine files"),
    ("DCN_FAULTS_*", "utils/faults.py",
     "wire-role fault specs (DCN_FAULTS_CLIENT / DCN_FAULTS_GATEWAY)"),
    ("*_FAULTS", "utils/faults.py",
     "per-plane fault specs (CKPT_/FEEDER_/LEARNER_/ACTOR_FAULTS)"),
    ("DCN_IDLE_DEADLINE", "parallel/dcn.py",
     "gateway idle-connection reap deadline, seconds"),
    ("TPU_APEX_METRICS", "utils/telemetry.py",
     "mission-control metrics plane switch (shorthand for "
     "TPU_APEX_METRICS_ENABLED)"),
    ("TPU_APEX_METRICS_*", "utils/telemetry.py",
     "per-field MetricsParams overrides (e.g. "
     "TPU_APEX_METRICS_OPENMETRICS, TPU_APEX_METRICS_PUSH_S)"),
    ("TPU_APEX_ALERT_*", "utils/telemetry.py",
     "per-field AlertParams overrides (e.g. TPU_APEX_ALERT_RULES)"),
    ("TPU_APEX_FLOW", "utils/flow.py",
     "flow-control plane switch (shorthand for TPU_APEX_FLOW_ENABLED)"),
    ("TPU_APEX_FLOW_*", "utils/flow.py",
     "per-field FlowParams overrides (e.g. TPU_APEX_FLOW_LOCAL_POLICY, "
     "TPU_APEX_FLOW_CLIENT_RING)"),
    ("TPU_APEX_ANAKIN_*", "agents/anakin.py",
     "per-field AnakinParams overrides (e.g. TPU_APEX_ANAKIN_ROLLOUT_RATIO, "
     "TPU_APEX_ANAKIN_DOUBLE_BUFFER)"),
    ("TPU_APEX_MXU_*", "utils/perf.py",
     "per-field LearnerPerfParams overrides — the ISSUE-13 MFU-campaign "
     "levers (e.g. TPU_APEX_MXU_MEGABATCH, TPU_APEX_MXU_PALLAS_TORSO)"),
    ("TPU_APEX_REPLICA_*", "parallel/dcn.py",
     "per-field ReplicaParams overrides — the ISSUE-15 multi-learner "
     "replica plane (e.g. TPU_APEX_REPLICA_REPLICAS, "
     "TPU_APEX_REPLICA_LEASE_S)"),
    ("TPU_APEX_GATEWAY_*", "parallel/dcn.py",
     "per-field GatewayParams overrides — the ISSUE-16 gateway "
     "high-availability plane (e.g. TPU_APEX_GATEWAY_ENABLED, "
     "TPU_APEX_GATEWAY_LEASE_S, TPU_APEX_GATEWAY_ENDPOINTS)"),
    ("TPU_APEX_WIRE", "utils/bandwidth.py",
     "bandwidth-accounting plane switch (shorthand for "
     "TPU_APEX_WIRE_ENABLED)"),
    ("TPU_APEX_WIRE_*", "utils/bandwidth.py",
     "per-field BandwidthParams overrides — the ISSUE-18 byte-exact "
     "wire/ring/checkpoint accountant (e.g. TPU_APEX_WIRE_SPAWN, "
     "TPU_APEX_WIRE_RATE_FLOOR_S)"),
    ("TPU_APEX_SHARD_*", "memory/shard_plane.py",
     "per-field ShardParams overrides — the ISSUE-20 sharded "
     "prioritized-replay plane (e.g. TPU_APEX_SHARD_SHARDS, "
     "TPU_APEX_SHARD_LEASE_S, TPU_APEX_SHARD_COORDINATOR)"),
)


def _default_refs() -> str:
    """Run signature ``{machine}_{timestamp}`` keying checkpoints and logs
    (reference utils/options.py:37-51)."""
    machine = os.uname().nodename.split(".")[0] or "machine"
    return f"{machine}_{time.strftime('%y%m%d%H%M%S')}"


@dataclass
class EnvParams:
    """Env-layer knobs (reference utils/options.py:54-69)."""

    env_type: str = "atari"
    game: str = "pong"
    seed: int = 100
    # State layout: ``state_cha`` is the history length (stacked frames for
    # CNNs, 1 for MLPs); hei/wid are the per-frame spatial dims.
    state_cha: int = 4
    state_hei: int = 84
    state_wid: int = 84
    # Max emulator frames per episode before truncation
    # (reference utils/options.py:69; "early_stop").
    early_stop: int = 12500
    # Life-loss-as-terminal & action-repeat semantics toggled by mode
    # (reference core/env.py:29-35).
    action_repetition: int = 4
    # Vector-env width per actor process.  The reference asserts this to 1
    # (utils/options.py:32, atari_env.py:15); here >1 is supported by the
    # sim envs and batched inference.
    num_envs_per_actor: int = 1
    # Actor hot-loop schedule/placement (ISSUE 4 + ISSUE 7):
    #   "pipelined" — two-stage software pipeline (default): the jitted
    #                 act for tick k+1 is dispatched asynchronously while
    #                 the host feeds tick k; bit-identical streams to
    #                 "inline" under a fixed seed.
    #   "inline"    — the serial dispatch-sync-step-feed loop; the
    #                 fallback and the determinism reference.
    #   "batched"   — SEED-style shared inference: actors hold no model
    #                 and submit obs to the InferenceServer thread in the
    #                 accelerator-owning process (agents/inference.py).
    #                 dqn/ddpg with a co-located server only; downgrades
    #                 to "pipelined" otherwise (factory.
    #                 resolve_actor_backend).
    #   "device"    — Sebulba/Anakin on-device env fleet (ISSUE 7): the
    #                 env itself is a pure-JAX program
    #                 (envs/device_env.py) and ONE donated scan advances
    #                 all N envs x device_rollout_ticks ticks fused with
    #                 the policy forward and on-device n-step assembly
    #                 (models/policies.build_fused_rollout) — no host
    #                 env step at all; one D2H per dispatch ships the
    #                 finished transition chunk.  dqn families with a
    #                 device env implementation only (pong-sim);
    #                 downgrades to "pipelined" otherwise.
    #   "anakin"    — the CLOSED Anakin loop (ISSUE 12): the env fleet
    #                 lives IN the learner process and one driver
    #                 alternates the donated fused rollout (emit=
    #                 "replay", scattering straight into the device
    #                 replay ring) with the fused learner dispatch
    #                 against the same HBM ring — no actor processes,
    #                 no spawn queue, no D2H on the experience path at
    #                 all (agents/anakin.py).  The acting params ARE the
    #                 train state's params (the published version is the
    #                 acting version by construction).  dqn + a device
    #                 env implementation + a device replay ring
    #                 (memory_type "device"/"device-per") only;
    #                 downgrades to "device" otherwise.  Knobs:
    #                 AnakinParams.
    actor_backend: str = "pipelined"
    # Ticks per fused device rollout dispatch (actor_backend="device"):
    # K env steps of all N envs run inside one XLA program, amortizing
    # dispatch latency and the chunk D2H over K*N frames.  Weight-sync
    # and stat cadences quantize to K ticks.
    device_rollout_ticks: int = 8
    # Device env family selector: "auto" derives it from env_type
    # (pong-sim -> the "pong" device port).  Naming a family explicitly
    # pins/documents the choice and must MATCH the env_type's own
    # device family (a family can never substitute a different game
    # than the host config runs — mismatches raise).
    # envs/device_env.DEVICE_ENV_FAMILIES.
    device_env_family: str = "auto"
    render: bool = False
    # Step sim envs through the first-party C++ batched stepper
    # (native/pong_batch.cpp) when the toolchain builds it; the Python
    # per-env loop is the fallback either way.
    native_env: bool = True

    @property
    def state_shape(self) -> Tuple[int, ...]:
        if self.state_hei > 1 or self.state_cha > 1:
            return (self.state_cha, self.state_hei, self.state_wid)
        return (self.state_wid,)


@dataclass
class MemoryParams:
    """Replay-memory knobs (reference utils/options.py:72-94)."""

    memory_type: str = "shared"
    memory_size: int = 50000           # reference utils/options.py:78-80
    enable_per: bool = False           # reference leaves PER unfinished (":82 TODO")
    # uint8 states for image observations, float32 for low-dim
    # (reference utils/options.py:84-91).
    state_dtype: str = "uint8"
    # PER exponents (reference utils/options.py:92-94; Ape-X paper values).
    priority_exponent: float = 0.6
    priority_weight: float = 0.4
    # Save/restore replay CONTENTS with the train-state checkpoint (the
    # resume leg the reference lacks, SURVEY.md §5).  Off by default:
    # image replays serialize to large files; written once at run end.
    checkpoint_replay: bool = False
    # NHWC (channels-last) storage for HBM device rings — a per-hardware
    # A/B knob (--set device_channels_last=true), NOT a tuning default:
    # measured ~13% SLOWER on the TPU v5 lite (XLA pads the 4-wide minor
    # channel axis to the 128 vector lanes) but kept live for hardware
    # where the trade flips (factory.device_ring_channels_last docstring
    # has the measurement).
    device_channels_last: bool = False
    # NOTE: device-resident (HBM) replay is selected via
    # ``memory_type="device"`` (CONFIGS row 8), not a flag here: the buffer
    # is sharded across the learner mesh's dp axis and sampled on device
    # fused into the train step (memory/device_replay.py).


@dataclass
class ModelParams:
    """Model knobs (reference utils/options.py:97-105 is empty; we add the
    few things the models actually need)."""

    model_type: str = "dqn-cnn"
    hidden_dim: int = 256              # dqn-mlp width (reference dqn_mlp_model.py:18-26)
    lstm_dim: int = 256                # recurrent core width (drqn-* models)
    # transformer Q-net (dtqn-*) geometry
    tf_dim: int = 128
    tf_heads: int = 4
    tf_depth: int = 2
    # dqn-cnn-wide (ISSUE 13): base channel width of the MXU-filling
    # IMPALA-deep torso — multiples of 128 fill the 128-lane MXU the
    # Nature CNN's 4/32/64 channels underfill (models/dqn_cnn_wide.py)
    cnn_wide_width: int = 128
    # MoE (dtqn-moe) routing: expert count, choices per token, per-row
    # slot headroom, and the Switch load-balancing loss weight
    # (models/moe.py)
    moe_experts: int = 8
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_aux_weight: float = 0.01
    # Apply orthogonal init for the CNN.  The reference *defines* orthogonal
    # init but never applies it (dqn_cnn_model.py:33 commented out) — here it
    # is applied and this flag documents the deliberate divergence.
    orthogonal_init: bool = True
    # Compute dtype for the forward/backward pass on TPU (params stay fp32).
    compute_dtype: str = "bfloat16"


@dataclass
class AgentParams:
    """Algorithm + process-cadence hyperparameters.

    DQN values mirror reference utils/options.py:112-141; DDPG values mirror
    :142-168.  ``build_agent_params`` below selects per-family defaults.
    """

    agent_type: str = "dqn"
    # --- generic (reference :117-127 / :146-156) ---
    steps: int = 500000                # max learner steps
    # Wall-clock budget for the run, seconds; 0 = unlimited.  When it
    # expires the learner ends the run exactly as if ``steps`` was reached
    # (final checkpoint, clean join).  Used by time-boxed benches/drives;
    # no reference equivalent (runs there end on steps only).
    max_seconds: float = 0.0
    gamma: float = 0.99
    clip_grad: float = float("inf")    # dqn: inf; ddpg: 40.0
    lr: float = 1e-4
    lr_decay: bool = False
    weight_decay: float = 0.0
    actor_sync_freq: int = 100         # dqn: 100; ddpg: 400
    # --- logger cadences (reference :128-133 / :157-162) ---
    logger_freq: int = 15              # secs
    actor_freq: int = 250              # actor steps; ddpg: 2500
    learner_freq: int = 100            # learner steps; ddpg: 1000
    evaluator_freq: int = 30           # secs; ddpg: 60
    evaluator_nepisodes: int = 2
    tester_nepisodes: int = 50
    # Unix niceness applied to the evaluator process (0 = none).  Its
    # bursty batch-1 greedy episodes starved the learner on an
    # oversubscribed host (runtime._child_main).  On a 1-core host a
    # nice'd evaluator runs its episodes more slowly, which thins how
    # many curve points land per wall-clock hour — but each point still
    # carries cadence-true capture attribution (step + wall of the
    # weight snapshot, agents/evaluator.py), so crossings stay exact;
    # lower this only when eval DENSITY (not accuracy) matters more
    # than learner throughput (--set evaluator_nice=0).
    evaluator_nice: int = 5
    # --- TPU-native publication/checkpoint cadence (no reference
    # equivalent: there weight visibility is implicit shared-CUDA and only
    # the evaluator checkpoints) ---
    param_publish_freq: int = 10       # learner steps between ParamStore publishes
    # Checkpoint-epoch cadence: learner steps between coordinated epoch
    # saves (train state + replay when checkpoint_replay + clocks/RNG,
    # committed atomically — utils/checkpoint.py save_epoch).  0 = final
    # epoch only.  With checkpoint_replay on, EVERY epoch carries the
    # replay contents (the crash-consistency point of the subsystem), so
    # size the cadence to what the replay serialization costs.
    checkpoint_freq: int = 0
    # Committed epochs kept on disk; older ones are garbage-collected
    # after each successful commit (the newest complete epoch is never
    # collected).
    checkpoint_retain: int = 3
    # --- off-policy core (reference :134-137 / :163-166) ---
    learn_start: int = 5000            # ddpg: 250
    batch_size: int = 128              # ddpg: 64
    # Cap on samples-drawn-per-transition-collected (replay ratio): the
    # learner throttles when learner_step * batch_size exceeds
    # max_replay_ratio * global actor steps.  0 disables.  No reference
    # equivalent — there the GPU learner can't outrun 8 CPU actors; a TPU
    # learner can outrun any actor fleet, collapsing replay diversity, so
    # the pacing knob is first-class here (standard in Ape-X-family
    # systems).
    max_replay_ratio: float = 0.0
    # Device-replay learners fuse this many update steps into ONE
    # dispatched XLA program (lax.scan over sample+train): program-launch
    # latency, not chip compute, bounds the hot loop when dispatch is
    # high-latency (tunnelled dev chips; congested hosts).  0 = auto
    # (32 on TPU, 1 elsewhere).  Cadences (publish/checkpoint/stats) are
    # quantized to the dispatch size, and the ``steps`` budget itself may
    # overshoot by up to K-1 updates (the final dispatch is whole).
    steps_per_dispatch: int = 0
    target_model_update: float = 250   # >=1: hard every N steps; <1: soft tau
    nstep: int = 5
    # --- dqn specifics (reference :138-141) ---
    enable_double: bool = False
    eps: float = 0.4                   # Ape-X per-actor epsilon base
    eps_alpha: float = 7.0
    eps_eval: float = 0.0              # greedy at eval
    # --- r2d2 specifics (no reference equivalent; Kapturowski et al. 2019
    # defaults — the sequence family extends the reference's capability
    # set, SURVEY.md §5 "long-context" note) ---
    seq_len: int = 80                  # replay segment length
    seq_overlap: int = 40              # segment overlap (adjacent windows)
    burn_in: int = 40                  # stored-state refresh prefix
    value_rescale: bool = True         # h(x) target transform
    priority_eta: float = 0.9          # max/mean blend for seq priorities
    # --- ddpg specifics (reference :167-168 + random_process.py) ---
    critic_lr: float = 1e-3
    ou_theta: float = 0.15
    ou_sigma: float = 0.3
    ou_mu: float = 0.0
    # Keep the reference's single-optimizer gradient coupling between the
    # DDPG policy loss and critic params?  The reference couples them
    # (ddpg_learner.py:62-91: one zero_grad, both backwards, one Adam over
    # all params).  Default False = decoupled per-net optimizers (the
    # textbook DDPG), True reproduces reference behaviour bit-for-bit.
    ddpg_coupled_update: bool = False


def build_agent_params(agent_type: str, **overrides: Any) -> AgentParams:
    """Per-family defaults, mirroring the if/elif in reference
    utils/options.py:111-168."""
    if agent_type == "dqn":
        p = AgentParams(agent_type="dqn")
    elif agent_type == "r2d2":
        # R2D2 paper cadences; learn_start/batch count SEGMENTS here
        p = AgentParams(
            agent_type="r2d2",
            enable_double=True,
            nstep=5,
            batch_size=64,
            learn_start=64,
            target_model_update=2500,
        )
    elif agent_type == "ddpg":
        p = AgentParams(
            agent_type="ddpg",
            clip_grad=40.0,
            actor_sync_freq=400,
            actor_freq=2500,
            learner_freq=1000,
            evaluator_freq=60,
            learn_start=250,
            batch_size=64,
            target_model_update=1e-3,
        )
    else:
        raise ValueError(f"unknown agent_type: {agent_type}")
    return dataclasses.replace(p, **overrides)


@dataclass
class HealthParams:
    """Training health sentinel knobs (utils/health.py; no reference
    equivalent — the reference has no numeric/liveness protection at
    all).  Every field is env-overridable as
    ``TPU_APEX_HEALTH_<FIELD>`` (``health.resolve``), the same
    spawn-inheritance contract the fault planes use, so drills flip
    knobs without plumbing."""

    # In-jit finite check on loss/grad-norm/TD: a non-finite step is
    # skipped in-graph (params/opt-state pass through unchanged, PER
    # write-back suppressed) and counted as ``learner/skipped``.
    numeric_guards: bool = True
    # Host-side rolling anomaly detector, evaluated on the learner's
    # stats cadence: loss EWMA z-score bound, grad-norm/|TD| spike
    # ratio vs their own EWMAs, and the consecutive-anomalous-window
    # streak that triggers a rollback.
    anomaly_zmax: float = 8.0
    grad_spike: float = 100.0
    anomaly_threshold: int = 3
    # Priority-distribution floor (ISSUE 8): the detector's
    # ``priority_collapse`` signal fires when the PER leaves' normalized
    # effective sample size (ESS / rows, from the priority X-ray) drops
    # under this — sampling has concentrated onto ~ess_floor * rows
    # rows even though total mass still looks healthy.
    ess_floor: float = 0.02
    # Automatic in-process rollback to the last good checkpoint epoch on
    # sustained divergence (needs committed epochs: checkpoint_freq > 0
    # or a preemption save).  ``max_rollbacks`` bounds the budget before
    # the learner escalates to a fatal exit; each successive rollback
    # targets one epoch OLDER than the previous one's restore point
    # (the newest epoch may itself hold already-diverged params).
    rollback: bool = True
    max_rollbacks: int = 2
    # Ingest quarantine: validate chunks at the single-owner ingest
    # boundaries and write offenders to {log_dir}/quarantine/ instead of
    # replay (also gated process-wide by TPU_APEX_QUARANTINE).
    quarantine: bool = True
    quarantine_max_files: int = 64
    # Hang watchdog: seconds a worker may go without a progress mark
    # before the supervisor SIGKILLs and respawns it (EXIT_HUNG, paid
    # from the slot's RestartBudget).  0 disables the watchdog (the
    # default: a safe deadline depends on the host's compile times —
    # production fleets should set it to a few multiples of their
    # longest legitimate stall, e.g. 180).  ``hang_grace`` extends the
    # deadline before a worker's FIRST mark, covering jit compiles.
    hang_deadline: float = 0.0
    hang_grace: float = 120.0


@dataclass
class PerfParams:
    """Performance observability plane knobs (utils/perf.py; no
    reference equivalent — the reference publishes no throughput
    numbers at all, BASELINE.md).  Every field is env-overridable as
    ``TPU_APEX_PERF_<FIELD>`` via ``perf.resolve`` (the bare
    ``TPU_APEX_PERF=1`` shorthand maps to ``enabled``), the same
    spawn-inheritance contract the health/fault planes use."""

    # Master switch: continuously export learner MFU / updates-per-s,
    # actor env-frames-per-s, replay-ratio and per-role memory
    # watermarks as metrics rows on the normal cadences.  Off by
    # default: the per-step cost is one counter add, but the one-time
    # cost is an extra AOT compile of the fused step (for its
    # cost_analysis FLOPs) at learner startup.
    enabled: bool = False
    # Peak dense FLOP/s per chip for the MFU ratio.  0 = auto from the
    # device kind (utils/perf.PEAK_FLOPS); unknown kinds (CPU, new TPU
    # generations) export achieved FLOP/s but no MFU row unless this is
    # set explicitly (``TPU_APEX_PERF_PEAK_FLOPS=...``).
    peak_flops: float = 0.0
    # Per-role memory watermarks on the drain cadence: device
    # live/peak bytes from ``device.memory_stats()`` where the backend
    # reports them (TPU), host RSS current/peak everywhere.
    memory_watermarks: bool = True
    # Retrace detector: track the jit cache size of registered hot-path
    # programs and flag any growth after the warmup window — a recompile
    # after warmup means a shape/dtype leak is silently paying compile
    # latency on the hot path.
    retrace_detector: bool = True
    # Opt-in transfer audit (``jax.transfer_guard``-based): run the
    # fused learner dispatch under a disallow guard, attribute any
    # IMPLICIT host<->device transfer to its python call site, then
    # retry the dispatch with transfers allowed.  The fused hot path is
    # transfer-free by construction, so any hit is a regression.
    # Explicit ``device_put``s never trip it (they are intended by
    # definition).
    transfer_audit: bool = False
    # Upper bound, seconds, on one on-demand T_PROFILE trace window
    # (parallel/dcn.py): the verb is sessionless and unauthenticated
    # inside the cluster, so a typo'd duration must not pin the
    # profiler for an hour.
    profile_window_max: float = 30.0


@dataclass
class MetricsParams:
    """Mission-control metrics-plane knobs (utils/telemetry.py; no
    reference equivalent — the reference has no fleet-level telemetry
    at all).  Every field is env-overridable as
    ``TPU_APEX_METRICS_<FIELD>`` via ``telemetry.resolve_metrics``
    (bare ``TPU_APEX_METRICS=1`` maps to ``enabled``), the same
    spawn-inheritance contract the health/perf planes use."""

    # Master switch: aggregate every role's scalar stream into bounded
    # fleet time series, evaluate the alert rules on the poll cadence,
    # and serve ``alerts``/``series`` blocks on the gateway STATUS
    # verb.  Off by default: the plane is one tail-read + rule pass
    # per cadence, but it is an operator surface, not a training one.
    enabled: bool = False
    # Local tail-ingest + alert-evaluation cadence, seconds.
    poll_s: float = 2.0
    # Remote-host T_METRICS push cadence, seconds (the fleet actor
    # hosts' MetricsPusher).
    push_s: float = 5.0
    # Retention tiers: raw points cover ``raw_span_s`` seconds (capped
    # at ``raw_points`` per series); coarser 10 s / 60 s bucket tiers
    # extend history without unbounded memory (SeriesRing docstring).
    raw_span_s: float = 300.0
    raw_points: int = 1024
    # Distinct (tag, role) series bound — overflow is counted
    # (``series_dropped``), never silent.
    max_series: int = 512
    # Points per series in the STATUS ``series`` block (fleet_top's
    # sparklines; the block rides every STATUS reply, so keep it small).
    series_points: int = 32
    # Extra tags for the STATUS series block, comma-separated (the
    # vital-sign defaults + rule tags are always included).
    series_tags: str = ""
    # Opt-in OpenMetrics/Prometheus text endpoint (stdlib HTTP, GET
    # /metrics) on the aggregator host.
    openmetrics: bool = False
    openmetrics_port: int = 9108


@dataclass
class AlertParams:
    """Declarative SLO/alert rules over the aggregated fleet series
    (utils/telemetry.py AlertEngine).  Env-overridable as
    ``TPU_APEX_ALERT_<FIELD>``; ``TPU_APEX_ALERT_RULES`` replaces the
    whole rule set (``;``-separated DSL lines)."""

    # Evaluate rules at all (the metrics plane can aggregate without
    # alerting, e.g. for a pure-dashboard deployment).
    enabled: bool = True
    # The rule set, one DSL line per rule, ``;``-separated::
    #
    #   name: tag absent 120s            (absence/staleness)
    #   name: tag > 100 for 60s          (threshold with dwell)
    #   name: tag < 0.02 frac 0.5 over 300s   (windowed burn-rate)
    #
    # "" = telemetry.DEFAULT_RULES (learner-stall absence, staleness
    # burn-rate, priority-ESS collapse).
    rules: str = ""
    # Seconds a firing rule must observe clean before it resolves
    # (hysteresis against flapping series).  0 = resolve on the first
    # clean evaluation.
    resolve_s: float = 0.0


@dataclass
class FlowParams:
    """End-to-end flow-control / graceful-degradation knobs (ISSUE 11;
    utils/flow.py — no reference equivalent: the reference blocks on a
    full shared ring and has no overload story at all).  Every field is
    env-overridable as ``TPU_APEX_FLOW_<FIELD>`` via
    ``flow.resolve_flow`` (bare ``TPU_APEX_FLOW=0`` maps to
    ``enabled``), the same spawn-inheritance contract the
    health/perf/metrics planes use.

    The plane is ON by default but INERT until the gateway's pressure
    signal crosses ``throttle_at``: in the healthy state no credits
    ride the wire, no chunk is ever shed, and the per-chunk cost is a
    few dict/float ops (bench.py ``flow_overhead``)."""

    # Master switch.  Off = the pre-ISSUE-11 behaviour everywhere: no
    # credits, no admission control, blocking local feeders.
    enabled: bool = True
    # Client-side bounded buffer (CHUNKS) a creditless DcnClient parks
    # experience in; overflow drops the OLDEST chunk (newest experience
    # wins, Ape-X priority-on-arrival), counted + provenance-stamped.
    client_ring: int = 256
    # Local transports (spawn-queue feeder, device-replay ingest
    # pending): "block" = the pre-ISSUE-11 backpressure stall (default);
    # "shed" = bounded drop-oldest with counted drops, the same
    # degradation contract the DCN client ring gives remote actors.
    local_policy: str = "block"
    # Feeder-side ring bound (CHUNKS) and device-ingest pending bound
    # (ROWS) under local_policy="shed".
    feeder_ring: int = 64
    max_pending_rows: int = 65536
    # Per-slot admission token bucket (CHUNKS/s + burst) metering the
    # throttled state's credit grants — and, at brownout tier 3, the
    # gateway-side shed of non-credit-aware peers.
    bucket_rate: float = 200.0
    bucket_burst: float = 400.0
    # Credit grant cap per ack while throttled (the healthy state
    # grants no credit field at all = unlimited; shedding grants 0).
    credits_throttled: int = 4
    # Overload state machine thresholds on the gateway pressure signal
    # (0..1, e.g. ingest-queue utilization): sustained >= throttle_at
    # escalates one state per ``dwell_s``; sustained < recover_at for
    # ``recover_s`` de-escalates one state (hysteresis — the band
    # between the two never flaps).
    throttle_at: float = 0.75
    shed_at: float = 0.92
    recover_at: float = 0.50
    dwell_s: float = 1.0
    recover_s: float = 3.0
    # Brownout ladder: seconds of SUSTAINED shedding before the tier
    # climbs one rung (1 = shed telemetry pushes, 2 = + trace
    # sampling, 3 = + oldest experience).  De-escalation rides the
    # same ``recover_s`` hysteresis as the states.
    brownout_dwell_s: float = 5.0


@dataclass
class BandwidthParams:
    """Byte-exact bandwidth-accounting knobs (ISSUE 18;
    utils/bandwidth.py — no reference equivalent: the reference counts
    neither bytes nor frames anywhere).  Every field is
    env-overridable as ``TPU_APEX_WIRE_<FIELD>`` via
    ``bandwidth.resolve_bandwidth`` (bare ``TPU_APEX_WIRE=0`` maps to
    ``enabled``), the same spawn-inheritance contract the
    flow/perf/metrics planes use.

    ON by default, counter-only hot path: one dict lookup + two
    integer adds per frame (bench.py ``wire_overhead`` gates it under
    the 0.02 absolute overhead band)."""

    # Master switch.  Off = no counters, no wire/* series, no byte
    # legs in the flow conservation ledger.
    enabled: bool = True
    # Account spawn-queue mint/drain boundaries (QueueFeeder flush,
    # QueueOwner / DeviceReplayIngest drain) — linear in chunk rows at
    # flush cadence, not per-frame; off leaves only the wire planes.
    spawn: bool = True
    # Minimum seconds between emit_scalars snapshots for a
    # ``wire/<link>/bytes_per_s`` rate to be computed (guards the
    # delta against a ~0 denominator on back-to-back emits).
    rate_floor_s: float = 0.05


@dataclass
class AnakinParams:
    """Co-located Anakin-loop knobs (ISSUE 12; agents/anakin.py — no
    reference equivalent: the reference always runs actors as separate
    processes).  Every field is env-overridable as
    ``TPU_APEX_ANAKIN_<FIELD>`` via ``anakin.resolve_anakin``, the same
    spawn-inheritance contract the health/perf/flow planes use.  Active
    only under ``env_params.actor_backend="anakin"``."""

    # Duty-cycle setpoint: target env frames collected per learner
    # update.  The scheduler dispatches rollouts while
    # ``frames < updates * rollout_ratio`` (after the min-fill warmup)
    # and learner steps otherwise.  0 = strict alternation: one rollout
    # dispatch, one learner dispatch, repeat.
    rollout_ratio: float = 0.0
    # Ring rows required before the FIRST learner dispatch (per half in
    # double-buffer mode).  0 = derive from agent_params.learn_start
    # (clamped to the ring/half capacity like the learner's warmup
    # gate).
    min_fill: int = 0
    # Double-buffered replay halves: the ring is split into two
    # half-capacity rings — learner dispatches sample the STABLE half
    # while rollouts scatter into the other; the halves swap once the
    # write half holds ``min_fill`` fresh rows.  Sampling never reads a
    # row the current rollout cycle is writing, and the PER priority
    # write-back lands in the sample half only — write races are
    # excluded by construction, not by ordering.  Costs replay
    # diversity (each dispatch samples from half the history), so the
    # default is the strict alternation of ONE ring, where dispatch
    # ordering already serializes writers and readers.
    double_buffer: bool = False
    # Drain the cross-process ingest queue between dispatches (chunks
    # from remote DCN actor hosts landing at the gateway).  The
    # co-located fleet itself never touches the queue; this keeps a
    # hybrid topology (anakin learner + remote device actors) live.
    drain_ingest: bool = True


@dataclass
class ReplicaParams:
    """Elastic multi-learner replica plane knobs (ISSUE 15;
    parallel/dcn.py ReplicaRegistry / agents/learner.py replica driver —
    no reference equivalent: the reference's ``num_learners > 1`` hook
    races unsynchronized Adam steps on one shared CUDA model).  Every
    field is env-overridable as ``TPU_APEX_REPLICA_<FIELD>`` via
    ``parallel.dcn.resolve_replica``, the same spawn-inheritance
    contract the health/perf/flow planes use.

    N data-parallel learner replicas train one logical model over DCN:
    replicas hold renewable LEASES with monotonic generation numbers on
    the lead gateway; a missed lease expires the replica and FENCES its
    stragglers (a stale-generation gradient or priority write-back is a
    counted reject, never applied — the slot-fencing contract of PR 1,
    lifted to the learner plane).  The gradient exchange is a
    generation-stamped allreduce round that reconfigures on membership
    change: when a replica dies mid-round, survivors complete the round
    over the surviving set within one lease window; at N=1 the survivor
    is bit-identical to the solo learner (tests/test_replicas.py
    oracle).  The dp-mesh ``psum`` path (parallel/learner.py) stays the
    in-host fast path — this plane composes ACROSS hosts."""

    # Configured replica count (1 = plane off: the solo learner runs
    # exactly as before, no registry, no stamps).  The plane is elastic
    # below this: fewer live members is a DEGRADED (alerted) state, not
    # an error.
    replicas: int = 1
    # Lease window, seconds: a replica that neither renews nor submits
    # within it is expired and fenced.  Also the round-stall window —
    # once any member has contributed to a round, members that stay
    # silent past one lease window are expelled and the round completes
    # over the surviving set.
    lease_s: float = 5.0
    # Background renew cadence, seconds (0 = lease_s / 3).
    renew_s: float = 0.0
    # Hard cap, seconds, on one blocking round exchange before the
    # submitting replica gives up (0 = 3 lease windows — strictly after
    # the stall expulsion above, so it only fires on a wedged registry).
    round_timeout_s: float = 0.0
    # Seconds a pending rejoiner may take to load the barrier epoch and
    # activate before its join is cancelled and survivors proceed.
    join_timeout_s: float = 30.0
    # Lead gateway ``host:port`` a remote replica host dials
    # (fleet.py --role learner-replica --coordinator).
    coordinator: str = ""


@dataclass
class GatewayParams:
    """Gateway high-availability plane knobs (ISSUE 16;
    parallel/dcn.py DcnGateway HA role / GatewayJournal — no reference
    equivalent: the reference's single mp.Queue hub dies with the
    learner process).  Every field is env-overridable as
    ``TPU_APEX_GATEWAY_<FIELD>`` via ``parallel.dcn.resolve_gateway``,
    the same spawn-inheritance contract the health/perf/flow/replica
    planes use.

    The primary gateway journals its mutable control state (slot
    incarnations, tick dedup high-waters, cumulative flow ledgers,
    clock counters) to an append-only fsynced WAL under
    ``{log_dir}/gateway/`` and serves it to a warm standby over the
    sessionless ``T_SYNC`` verb.  Primary and standby carry a
    monotonic *term* (the PR-14 replica-generation pattern lifted one
    level up) persisted in ``TERM.json`` on the SHARED log_dir — the
    same shared-storage requirement checkpoint resume already has.
    The standby promotes when the primary goes silent for one lease
    window; a resurrected stale-term primary fences itself against the
    on-disk term and its writes are counted rejects
    (``gateway_term_fenced``), never applied.  With ``enabled`` False
    (the default) no journal is written, STATUS carries no ``gateway``
    block and the wire is byte-identical to the pre-HA protocol."""

    # Master switch.  Off = the single-gateway topology of PRs 1-15,
    # bit-for-bit: no term, no WAL, no sync verb traffic.
    enabled: bool = False
    # Primary lease window, seconds: the standby promotes once it has
    # failed to sync for this long.  Also bounds how long a fenced
    # primary can run before noticing the on-disk term moved.
    lease_s: float = 2.0
    # Standby sync cadence, seconds (journal records are pulled with
    # sessionless T_SYNC requests at this rate; sync lag on STATUS is
    # quantized by it).
    sync_s: float = 0.25
    # Standby bind ``host:port`` for fleet.py --role gateway-standby
    # ("" = 0.0.0.0 on an ephemeral port).
    standby: str = ""
    # Ordered client dial list ``host:port,host:port`` (primary first).
    # Exported to spawned actors so DcnClient redials the next endpoint
    # on terminal disconnect.  "" = single-endpoint (pre-HA) dialing.
    endpoints: str = ""


@dataclass
class ShardParams:
    """Sharded prioritized-replay plane knobs (ISSUE 20;
    memory/shard_plane.py ShardRegistry / ShardedReplayPlane — no
    reference equivalent: the reference's replay is one host's shared
    pages, full stop).  Every field is env-overridable as
    ``TPU_APEX_SHARD_<FIELD>`` via ``memory.shard_plane.resolve_shard``,
    the same spawn-inheritance contract the health/perf/flow/replica
    planes use.

    The INES topology (PAPERS.md): each gateway host owns a replay ring
    SHARD with its own local sum/min trees, and the learner samples
    through a two-level tree — a global priority-mass vector over
    shards routes stratified sample values to the shard that owns the
    mass stratum, which answers locally (sample where experience lands,
    never ship raw transitions twice).  Shard membership is lease-fenced
    with monotonic generations (the PR-14 replica contract): a shard
    that misses its lease window is expired, its priority mass leaves
    the global vector, its transitions are counted into the
    ``shard_lost`` ledger bucket (conservation stays EXACT through the
    loss), and any |TD| write-back stamped with its dead generation is
    a counted reject — never applied.  At ``shards <= 1`` the plane is
    off: the single-host PER path runs bit-identically, no registry,
    no verbs, no STATUS block."""

    # Configured shard count (<= 1 = plane off: build_memory constructs
    # the plain single-host PrioritizedReplay exactly as before).  The
    # plane is elastic below this: fewer live shards is a DEGRADED
    # (alerted) state, not an error.
    shards: int = 0
    # Lease window, seconds: a shard host that neither renews (renews
    # carry its mass/fill/ingest report) nor serves within it is
    # expired and fenced.
    lease_s: float = 5.0
    # Background renew cadence, seconds (0 = lease_s / 3).
    renew_s: float = 0.0
    # Global mass-vector refresh cadence on the sample path, seconds
    # (0 = refresh at EVERY sample — exact priority proportions, the
    # loopback/tier-1 default; wire fleets trade a bounded staleness
    # window for fewer T_SMASS round-trips by raising this).
    mass_refresh_s: float = 0.0
    # Seconds a rejoining shard may take to re-lease, warm its ring,
    # and activate at the rejoin barrier before the join is cancelled.
    join_timeout_s: float = 30.0
    # Coordinator gateway ``host:port`` a remote shard host dials
    # (fleet.py --role replay-shard --coordinator).
    coordinator: str = ""


@dataclass
class LearnerPerfParams:
    """MFU-campaign knobs (ISSUE 13; no reference equivalent — the
    reference never measures device utilization at all).  Every field
    is env-overridable as ``TPU_APEX_MXU_<FIELD>`` via
    ``utils/perf.resolve_mxu``, the same spawn-inheritance contract the
    health/perf/flow planes use.  All three levers are OPT-IN: the
    defaults reproduce the pre-campaign learner bit-for-bit."""

    # Megabatch factor M for the fused device-replay learner step (dqn
    # and ddpg flat families): each scan group samples M minibatches in
    # ONE widened gather (consuming the SAME M keys the sequential
    # schedule would) and computes all M per-minibatch gradients in one
    # lane-filling (M*B, ...) batched forward/backward at the
    # group-entry params, then applies the M optimizer updates
    # SEQUENTIALLY in-graph (Adam moments, step counter, target-update
    # cadence and PER |TD| write-backs chain exactly as M separate
    # steps).  The one semantic divergence from M sequential steps is
    # within-group gradient freshness — gradients see the group-entry
    # params instead of the per-step params — the large-effective-batch
    # trade Stooke & Abbeel (2018) validate for the DQN family; the
    # tier-1 oracle (tests/test_megabatch.py) pins the program
    # bit-exactly against an unfused reference of the same semantics.
    # 1 = off (the pre-campaign program); must divide
    # ``steps_per_dispatch``.
    megabatch: int = 1
    # Pallas fused conv-stack/Q-head torso for dqn-cnn
    # (ops/pallas_torso.py): the learner's train apply runs the torso
    # as hand-tiled 128-lane MXU matmul kernels (im2col) instead of
    # XLA's conv lowering, bypassing the ~25% of device time
    # mfu_probe.py attributes to XLA re-tiling.  Loud downgrade to the
    # XLA apply when Pallas/TPU is unavailable (unless
    # ``pallas_interpret``).  Actors/evaluators keep the standard
    # apply — the param tree is identical.
    pallas_torso: bool = False
    # Run the Pallas torso kernels in interpreter mode (CPU hosts):
    # the tier-1 parity tests use this; production CPU runs should
    # leave it off (interpret mode is slower than XLA's native conv).
    pallas_interpret: bool = False


@dataclass
class ParallelParams:
    """TPU topology knobs — no reference equivalent (the reference is a
    single-node torch.multiprocessing program, SURVEY.md §2); this is where
    the mesh/sharding design lives."""

    # Logical mesh axes over jax.devices().  data parallel ("dp") carries the
    # batch + gradient psum over ICI; model parallel ("mp") is available for
    # tensor-sharded heads on wide models.
    dp_size: int = -1                  # -1: all devices on dp
    mp_size: int = 1
    # sequence/context parallel: shards the time axis of long windows;
    # ring attention moves K/V around this axis over ICI
    # (ops/ring_attention.py)
    sp_size: int = 1
    # sp strategy: "ring" (K/V rotation, any head count) or "ulysses"
    # (head/time all-to-all, needs heads % sp == 0;
    # ops/ulysses_attention.py docstring has the trade-off)
    sp_attention: str = "ring"
    # expert parallel: MoE expert kernels shard over the ep axis
    # (dtqn-moe only; parallel/expert_parallel.py)
    ep_size: int = 1
    # pipeline parallel: stacked DTQN blocks stage over the pp axis with
    # a GPipe microbatch schedule (dtqn-pipe only; parallel/pipeline.py)
    pp_size: int = 1
    pp_microbatches: int = 4
    # Donate learner buffers (params/opt_state) to the jit step.
    donate: bool = True
    # Multi-host: call jax.distributed.initialize (DCN) before device init.
    multihost: bool = False
    coordinator_address: Optional[str] = None
    num_processes: int = 1
    process_id: int = 0


@dataclass
class Options:
    """Aggregate of everything a run needs — equivalent of reference
    ``Options`` (utils/options.py:171-175) but an explicit instance."""

    # --- run identity (reference Params, utils/options.py:17-51) ---
    mode: int = 1                      # 1 = train, 2 = test model_file
    config: int = 1
    seed: int = 100
    refs: str = field(default_factory=_default_refs)
    root_dir: str = field(default_factory=os.getcwd)
    num_actors: int = 8
    num_learners: int = 1
    model_file: Optional[str] = None   # finetune/test source checkpoint
    # Resume mode for the checkpoint-epoch tier (utils/checkpoint.py):
    #   "auto"  — resume from the newest complete epoch under
    #             ``{model_name}_ckpt`` if one exists (falling back to the
    #             legacy ``_state`` snapshot), else start fresh;
    #   "must"  — refuse to start without a resumable checkpoint (what
    #             ``--resume REFS`` sets: a preempted run restarted by an
    #             orchestrator must never silently train from scratch);
    #   "never" — ignore existing checkpoints (fresh run even if the refs
    #             collide with an old one's).
    resume: str = "auto"
    visualize: bool = True

    agent_type: str = "dqn"
    env_type: str = "fake"
    game: str = "chain"
    memory_type: str = "shared"
    model_type: str = "dqn-mlp"

    env_params: EnvParams = field(default_factory=EnvParams)
    memory_params: MemoryParams = field(default_factory=MemoryParams)
    model_params: ModelParams = field(default_factory=ModelParams)
    agent_params: AgentParams = field(default_factory=AgentParams)
    parallel_params: ParallelParams = field(default_factory=ParallelParams)
    health_params: HealthParams = field(default_factory=HealthParams)
    perf_params: PerfParams = field(default_factory=PerfParams)
    metrics_params: MetricsParams = field(default_factory=MetricsParams)
    alert_params: AlertParams = field(default_factory=AlertParams)
    flow_params: FlowParams = field(default_factory=FlowParams)
    anakin_params: AnakinParams = field(default_factory=AnakinParams)
    learner_perf_params: LearnerPerfParams = field(
        default_factory=LearnerPerfParams)
    replica_params: ReplicaParams = field(default_factory=ReplicaParams)
    gateway_params: GatewayParams = field(default_factory=GatewayParams)
    shard_params: ShardParams = field(default_factory=ShardParams)

    @property
    def model_dir(self) -> str:
        return os.path.join(self.root_dir, "models")

    @property
    def model_name(self) -> str:
        # reference utils/options.py:42
        return os.path.join(self.model_dir, f"{self.refs}")

    @property
    def log_dir(self) -> str:
        # reference utils/options.py:51
        return os.path.join(self.root_dir, "logs", self.refs)


def parse_set_overrides(pairs) -> dict:
    """Parse repeatable CLI ``--set key=value`` pairs into an overrides
    dict (int/float auto-typed, else string) — shared by main.py and the
    fleet launcher."""
    out = {}
    for kv in pairs:
        k, _, v = kv.partition("=")
        if v.lower() in ("true", "false"):
            v = v.lower() == "true"
        else:
            for cast in (int, float):
                try:
                    v = cast(v)
                    break
                except ValueError:
                    continue
        out[k] = v
    return out


def build_options(config: int = 1, **overrides: Any) -> Options:
    """Construct an Options from a CONFIGS row index + keyword overrides.

    Mirrors what reference Params.__init__ does at utils/options.py:26
    (unpacking the CONFIGS row) plus the shape bookkeeping EnvParams does at
    :54-69, then applies overrides (our CLI affordance).
    """
    agent_type, env_type, game, memory_type, model_type = CONFIGS[config]

    # Selector overrides must land before sub-param construction so the
    # per-family defaults they derive (hyperparams, shapes, dtypes, PER flag)
    # stay consistent.
    selectors = ("agent_type", "env_type", "game", "memory_type", "model_type")
    agent_type = overrides.pop("agent_type", agent_type)
    env_type = overrides.pop("env_type", env_type)
    game = overrides.pop("game", game)
    memory_type = overrides.pop("memory_type", memory_type)
    model_type = overrides.pop("model_type", model_type)

    if "cnn" in model_type:
        env_shape = dict(state_cha=4, state_hei=84, state_wid=84)
        state_dtype = "uint8"
    else:
        # Low-dim envs report their own width at probe time; 0 = fill in
        # from the env probe in main (reference main.py:23-31 does the same
        # dummy-env probe).
        env_shape = dict(state_cha=1, state_hei=1, state_wid=0)
        state_dtype = "float32"

    opt = Options(
        config=config,
        agent_type=agent_type,
        env_type=env_type,
        game=game,
        memory_type=memory_type,
        model_type=model_type,
        env_params=EnvParams(env_type=env_type, game=game, **env_shape),
        memory_params=MemoryParams(
            memory_type=memory_type,
            state_dtype=state_dtype,
            enable_per=(memory_type == "prioritized"),
            # sequence replay is prioritized by default with the R2D2
            # constants (alpha 0.9 / beta0 0.6); --set overrides still land
            **({"priority_exponent": 0.9, "priority_weight": 0.6}
               if memory_type in ("sequence", "device-sequence") else {}),
        ),
        model_params=ModelParams(model_type=model_type),
        agent_params=build_agent_params(agent_type),
    )

    # Route simple top-level overrides to the right sub-dataclass.
    for key, val in overrides.items():
        assert key not in selectors  # popped above
        hits = []
        for sub in ("env_params", "memory_params", "model_params",
                    "agent_params", "parallel_params", "health_params",
                    "perf_params", "metrics_params", "alert_params",
                    "flow_params", "anakin_params",
                    "learner_perf_params", "replica_params",
                    "gateway_params", "shard_params"):
            subobj = getattr(opt, sub)
            if hasattr(subobj, key):
                hits.append((sub, subobj))
        if len(hits) > 1:
            # a field living on several sub-params ("enabled" is on the
            # perf/metrics/alert planes): a bare override would silently
            # flip every plane at once — refuse, name the candidates
            raise ValueError(
                f"ambiguous option {key!r}: lives on "
                f"{', '.join(s for s, _ in hits)} — set the field "
                f"directly (opt.<sub>.{key}) or use the plane's env "
                f"knob (TPU_APEX_*)")
        routed = False
        for _sub, subobj in hits:
            setattr(subobj, key, val)
            routed = True
        if hasattr(opt, key):
            setattr(opt, key, val)
            routed = True
        if not routed:
            raise ValueError(f"unknown option: {key}")

    # Keep seed coherent across sub-params.
    opt.env_params.seed = opt.seed
    if opt.mode == 2 and opt.model_file is None:
        # reference utils/options.py:45-48: test mode defaults to the
        # current run's checkpoint path.
        opt.model_file = opt.model_name
    return opt
