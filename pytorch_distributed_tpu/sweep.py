"""Benchmark sweep runner: one training run per game, results to JSONL.

The BASELINE tracked configs include "DQN Breakout + Atari-57, 256
actors"; this is the launcher for that scale.  Each game gets its own
training run (own refs/checkpoints/logs) followed by a mode-2 test of the
final checkpoint; one summary line per game appends to
``{root_dir}/sweep_results.jsonl`` so a partially-completed sweep is
resumable (finished games are skipped).

    # 2-game smoke on the ALE-free simulator path
    python -m pytorch_distributed_tpu.sweep --config 4 --games pong \
        --set steps=2000

    # the full 57-game suite at Ape-X scale, 256 actors per game
    # (16 actors x 16 envs each; use the fleet CLI to spread hosts)
    python -m pytorch_distributed_tpu.sweep --config 11 --games all \
        --num-actors 16 --set num_envs_per_actor=16
"""

from __future__ import annotations

import argparse
import json
import os
import time
from typing import List, Optional

from pytorch_distributed_tpu.config import (
    build_options, parse_set_overrides,
)
from pytorch_distributed_tpu.envs.atari57 import resolve_games


def _results_path(root_dir: str) -> str:
    return os.path.join(root_dir, "sweep_results.jsonl")


def _norm(game: str) -> str:
    """Canonical game id for resume bookkeeping: the rom loader treats
    hyphenated and underscored ids as the same game, so resume must too."""
    return game.replace("-", "_")


def completed_games(root_dir: str) -> set:
    path = _results_path(root_dir)
    if not os.path.exists(path):
        return set()
    done = set()
    with open(path) as f:
        for line in f:
            if not line.strip():
                continue
            try:
                done.add(_norm(json.loads(line)["game"]))
            except (json.JSONDecodeError, KeyError):
                # a run killed mid-append leaves a torn tail; that game
                # simply reruns — resume must not abort on it
                continue
    return done


def run_sweep(config: int, games: List[str], overrides: dict,
              root_dir: Optional[str] = None,
              backend: str = "process") -> List[dict]:
    from pytorch_distributed_tpu import runtime

    root_dir = root_dir or os.getcwd()
    # the sweep owns these per-run keys; silently duplicating them as
    # kwargs would TypeError inside build_options
    for reserved in ("game", "root_dir", "mode", "model_file"):
        if reserved in overrides:
            raise ValueError(
                f"--set {reserved}=... conflicts with sweep-managed "
                f"options (use the dedicated flags instead)")
    done = completed_games(root_dir)
    results = []
    for game in games:
        if _norm(game) in done:
            print(f"[sweep] {game}: already in results, skipping")
            continue
        t0 = time.time()
        opt = build_options(config, game=game, root_dir=root_dir,
                            **overrides)
        print(f"[sweep] {game}: training -> {opt.refs}")
        runtime.train(opt, backend=backend)
        test_opt = build_options(config, game=game, root_dir=root_dir,
                                 mode=2, model_file=opt.model_name,
                                 **overrides)
        stats = runtime.test(test_opt)
        rec = {
            "game": game,
            "refs": opt.refs,
            "wall_s": round(time.time() - t0, 1),
            **{k: float(v) for k, v in stats.items()},
        }
        os.makedirs(root_dir, exist_ok=True)
        with open(_results_path(root_dir), "a") as f:
            f.write(json.dumps(rec) + "\n")
        results.append(rec)
        print(f"[sweep] {game}: {rec}")
    return results


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(
        prog="pytorch_distributed_tpu.sweep",
        description="per-game benchmark sweep (Atari-57 and friends)")
    ap.add_argument("--config", type=int, required=True)
    ap.add_argument("--games", type=str, default="all",
                    help='"all" = Atari-57 suite, or comma-separated names')
    ap.add_argument("--num-actors", type=int, default=None)
    ap.add_argument("--root-dir", type=str, default=None)
    ap.add_argument("--backend", choices=("process", "thread"),
                    default="process")
    ap.add_argument("--set", action="append", default=[], metavar="K=V")
    args = ap.parse_args(argv)

    overrides = parse_set_overrides(args.set)
    if args.num_actors is not None:
        overrides["num_actors"] = args.num_actors
    run_sweep(args.config, resolve_games(args.games), overrides,
              root_dir=args.root_dir, backend=args.backend)


if __name__ == "__main__":
    main()
