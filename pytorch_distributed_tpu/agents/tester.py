"""Tester: mode-2 offline evaluation of a saved checkpoint.

Re-design of reference core/single_processes/testers.py: load the params
checkpoint named by ``model_file`` (the reference loads a .pth state_dict on
CPU, reference :18-25), run ``tester_nepisodes`` greedy episodes, report
``avg_steps / avg_reward / nepisodes_solved`` (reference :78-83).  Returns
the stats dict so callers (main, tests) can assert on it instead of parsing
stdout.
"""

from __future__ import annotations

from typing import Dict

from pytorch_distributed_tpu.config import Options
from pytorch_distributed_tpu.factory import (
    EnvSpec, build_env, build_model, init_params,
)
from pytorch_distributed_tpu.agents.evaluator import greedy_episodes
from pytorch_distributed_tpu.utils import checkpoint as ckpt
from pytorch_distributed_tpu.utils.rngs import process_seed


def run_tester(opt: Options, spec: EnvSpec) -> Dict[str, float]:
    ap = opt.agent_params
    env = build_env(opt, process_ind=0)
    env.eval()
    if opt.env_params.render:
        from pytorch_distributed_tpu.utils.render import attach_frame_dumper

        attach_frame_dumper(env, opt.log_dir, "tester")
    model = build_model(opt, spec)
    template = init_params(opt, spec, model,
                           seed=process_seed(opt.seed, "tester"))
    path = opt.model_file
    assert path, "mode 2 needs model_file (reference utils/options.py:45-48)"
    if not path.endswith(".msgpack"):
        path = ckpt.params_path(path)
    params = ckpt.load_params(path, template)
    avg_steps, avg_reward, solved = greedy_episodes(
        opt, spec, model, params, env, ap.tester_nepisodes)
    out = {
        "avg_steps": avg_steps,
        "avg_reward": avg_reward,
        "nepisodes": float(ap.tester_nepisodes),
        "nepisodes_solved": float(solved),
    }
    print(f"[tester] {out}")  # reference testers.py:78-83 prints to stdout
    return out
