"""Versioned parameter publication.

The explicit replacement for the reference's implicit weight plane: there,
the learner's in-place Adam writes to a shared-CUDA-storage model are
instantly visible to actors' unsynchronized ``load_state_dict`` reads
(reference main.py:44-47, dqn_learner.py:87, dqn_actor.py:176-178; SURVEY.md
§2 mechanism 2).  TPU-first there is no shared device storage across
processes, so publication is explicit and versioned:

- the learner flattens its param pytree (``ravel_pytree``) and writes the
  flat fp32 vector into a shared-memory page under a lock, bumping a version
  counter — one coherent snapshot per publish, never a torn read (the
  reference tolerates torn reads by design; we get coherence for free);
- actors/evaluators poll ``fetch(min_version=...)`` on their sync cadence
  (reference ``actor_sync_freq``) and unravel into their local pytree; a
  fetch that finds no newer version costs one integer read.

Staleness bound: learner publish cadence + actor sync cadence, matching the
reference's <=100-actor-step bound (SURVEY.md §7 "hard parts").
"""

from __future__ import annotations

import ctypes
import multiprocessing as mp
from typing import Any, Callable, Optional, Tuple

import numpy as np

_CTX = mp.get_context("spawn")

PyTree = Any


class ParamStore:
    """One published flat-fp32 parameter snapshot + version counter."""

    def __init__(self, num_params: int):
        self.num_params = num_params
        self._buf = _CTX.Array(ctypes.c_float, num_params, lock=False)
        self._version = _CTX.Value("l", 0, lock=False)
        self._lock = _CTX.Lock()

    def __getstate__(self):
        d = self.__dict__.copy()
        d.pop("_np", None)
        return d

    def __setstate__(self, d):
        self.__dict__.update(d)

    @property
    def _view(self) -> np.ndarray:
        np_view = getattr(self, "_np", None)
        if np_view is None:
            np_view = np.frombuffer(self._buf, dtype=np.float32)
            self._np = np_view
        return np_view

    @property
    def version(self) -> int:
        return self._version.value

    def publish(self, flat: np.ndarray) -> int:
        """Write one coherent snapshot; returns the new version."""
        flat = np.asarray(flat, dtype=np.float32).ravel()
        assert flat.size == self.num_params, (flat.size, self.num_params)
        with self._lock:
            self._view[:] = flat
            self._version.value += 1
            return self._version.value

    def fetch(self, min_version: int = 0
              ) -> Optional[Tuple[np.ndarray, int]]:
        """Copy out (flat, version) if a snapshot newer than ``min_version``
        exists, else None (cheap no-op — the common case on the actor sync
        cadence)."""
        if self._version.value <= min_version:
            return None
        with self._lock:
            return self._view.copy(), self._version.value

    def wait(self, min_version: int = 0, timeout: float = 60.0,
             poll: float = 0.05, stop=None) -> Tuple[np.ndarray, int]:
        """Block until a snapshot newer than ``min_version`` appears —
        workers use this at startup so nobody acts on unseeded weights
        (the reference instead hard-syncs from the pre-spawn global model,
        reference dqn_actor.py:26-30)."""
        import time

        deadline = time.monotonic() + timeout
        while True:
            got = self.fetch(min_version)
            if got is not None:
                return got
            if stop is not None and stop.is_set():
                raise RuntimeError("stopped while waiting for params")
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"no params published within {timeout}s")
            time.sleep(poll)


def make_flattener(params: PyTree) -> Tuple[np.ndarray, Callable]:
    """Build (flat0, unravel) for a param pytree via ravel_pytree; every
    worker constructs the same tree structure from the same model config, so
    unravel on one side inverts ravel on the other."""
    from jax.flatten_util import ravel_pytree

    flat, unravel = ravel_pytree(params)
    return np.asarray(flat, dtype=np.float32), unravel
