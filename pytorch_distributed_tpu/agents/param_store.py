"""Versioned parameter publication.

The explicit replacement for the reference's implicit weight plane: there,
the learner's in-place Adam writes to a shared-CUDA-storage model are
instantly visible to actors' unsynchronized ``load_state_dict`` reads
(reference main.py:44-47, dqn_learner.py:87, dqn_actor.py:176-178; SURVEY.md
§2 mechanism 2).  TPU-first there is no shared device storage across
processes, so publication is explicit and versioned:

- the learner flattens its param pytree (``ravel_pytree``) and writes the
  flat fp32 vector into a shared-memory page under a lock, bumping a version
  counter — one coherent snapshot per publish, never a torn read (the
  reference tolerates torn reads by design; we get coherence for free);
- actors/evaluators poll ``fetch(min_version=...)`` on their sync cadence
  (reference ``actor_sync_freq``) and unravel into their local pytree; a
  fetch that finds no newer version costs one integer read.

Staleness bound: learner publish cadence + actor sync cadence, matching the
reference's <=100-actor-step bound (SURVEY.md §7 "hard parts").
"""

from __future__ import annotations

import ctypes
import multiprocessing as mp
from typing import Any, Callable, Optional, Tuple

import numpy as np

_CTX = mp.get_context("spawn")

PyTree = Any


class ParamStore:
    """One published flat-fp32 parameter snapshot + version counter."""

    def __init__(self, num_params: int):
        self.num_params = num_params
        self._buf = _CTX.Array(ctypes.c_float, num_params, lock=False)
        self._version = _CTX.Value("l", 0, lock=False)
        self._lock = _CTX.Lock()

    def __getstate__(self):
        d = self.__dict__.copy()
        d.pop("_np", None)
        return d

    def __setstate__(self, d):
        self.__dict__.update(d)

    @property
    def _view(self) -> np.ndarray:
        np_view = getattr(self, "_np", None)
        if np_view is None:
            np_view = np.frombuffer(self._buf, dtype=np.float32)
            self._np = np_view
        return np_view

    @property
    def version(self) -> int:
        return self._version.value

    def publish(self, flat: np.ndarray) -> int:
        """Write one coherent snapshot; returns the new version."""
        flat = np.asarray(flat, dtype=np.float32).ravel()
        assert flat.size == self.num_params, (flat.size, self.num_params)
        with self._lock:
            self._view[:] = flat
            self._version.value += 1
            return self._version.value

    def fetch(self, min_version: int = 0
              ) -> Optional[Tuple[np.ndarray, int]]:
        """Copy out (flat, version) if a snapshot newer than ``min_version``
        exists, else None (cheap no-op — the common case on the actor sync
        cadence)."""
        if self._version.value <= min_version:
            return None
        with self._lock:
            return self._view.copy(), self._version.value

    def wait(self, min_version: int = 0, timeout: float = 60.0,
             poll: float = 0.05, stop=None) -> Tuple[np.ndarray, int]:
        """Block until a snapshot newer than ``min_version`` appears —
        workers use this at startup so nobody acts on unseeded weights
        (the reference instead hard-syncs from the pre-spawn global model,
        reference dqn_actor.py:26-30)."""
        import time

        deadline = time.monotonic() + timeout
        while True:
            got = self.fetch(min_version)
            if got is not None:
                return got
            if stop is not None and stop.is_set():
                raise RuntimeError("stopped while waiting for params")
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"no params published within {timeout}s")
            time.sleep(poll)


class ParamPrefetcher:
    """Non-blocking weight refresh for actor hot loops (ISSUE 4).

    The serial actor paid the slow path — a ``fetch`` copy of the flat
    vector plus the pytree unravel — INSIDE its tick whenever the sync
    cadence found a newer version, which showed up as multi-ms
    ``advance`` spikes every few ticks at production cadences.  Here a
    background thread watches the store's version counter, and when a
    newer snapshot lands it does the copy + unravel off the hot path,
    parking the finished pytree in a ready slot.  The tick-side
    ``take()`` is a lock + reference swap: a version swap never stalls a
    tick, and the remaining swap cost is visible as the actor's
    ``param_swap`` timer phase.

    Staleness is bounded exactly as before — learner publish cadence +
    actor sync cadence — plus at most one ``poll_secs`` of thread lag.

    Works against any store with the ``fetch(min_version)`` surface.  A
    local ParamStore exposes ``version`` as a cheap shared-memory read,
    so the poll costs one integer compare; a DCN RemoteParamStore does
    not — there the fetch RPC itself IS the newer-version probe (the
    gateway answers "no newer" with one small frame), so the poll slows
    to ``remote_poll_secs`` to keep the wire chatter comparable to the
    old in-loop cadence.  DcnClient requests are RLock-serialized, so
    probing from this thread is safe alongside the actor's sends.

    ``refresh_secs`` bounds the background work: after a successful
    fetch+unravel the thread rests at least that long, so a
    fast-publishing learner (several publishes/sec) can't make every
    actor process burn its host core unraveling snapshots the tick side
    would discard anyway — the old in-loop code paid at most one fetch
    per sync cadence, and this keeps the same order of cost.
    """

    def __init__(self, store: ParamStore, unravel_fn: Callable,
                 start_version: int = 0, poll_secs: float = 0.1,
                 remote_poll_secs: float = 0.5,
                 refresh_secs: float = 0.5):
        import threading

        self._store = store
        self._unravel_fn = unravel_fn
        self._version = start_version
        if not hasattr(store, "version"):
            poll_secs = remote_poll_secs
        self._poll_secs = poll_secs
        self._refresh_secs = refresh_secs
        self._failures = 0
        self._ready: Optional[Tuple[Any, int]] = None
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="param-prefetch", daemon=True)
        self._thread.start()

    def _run(self) -> None:
        wait = self._poll_secs
        while not self._stop.is_set():
            try:
                wait = self._poll_secs
                newer = getattr(self._store, "version",
                                self._version + 1) > self._version
                if newer:
                    got = self._store.fetch(self._version)
                    if got is not None:
                        flat, version = got
                        tree = self._unravel_fn(flat)
                        with self._lock:
                            self._ready = (tree, version)
                            self._version = version
                        wait = max(self._poll_secs, self._refresh_secs)
            except Exception as e:  # noqa: BLE001 - a dying prefetch
                # thread must never take the actor down (the loop falls
                # back to the version it last delivered) — but an actor
                # rolling out stale weights for a whole job must not be
                # SILENT about why: record the failure where post-mortems
                # look, and say so once on stderr
                self._failures += 1
                if self._failures == 1:
                    import sys

                    from pytorch_distributed_tpu.utils import (
                        flight_recorder,
                    )

                    flight_recorder.get_recorder("param-prefetch").record(
                        "prefetch-failed", error=repr(e))
                    print(f"[param-prefetch] weight refresh failing "
                          f"({e!r}); actor continues on version "
                          f"{self._version} — will keep retrying "
                          f"quietly", file=sys.stderr, flush=True)
            self._stop.wait(wait)

    def take(self) -> Optional[Tuple[Any, int]]:
        """Swap out the newest prefetched (params, version), or None —
        the only call on the actor's hot path."""
        with self._lock:
            got, self._ready = self._ready, None
            return got

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2.0)


def make_flattener(params: PyTree) -> Tuple[np.ndarray, Callable]:
    """Build (flat0, unravel) for a param pytree via ravel_pytree; every
    worker constructs the same tree structure from the same model config, so
    unravel on one side inverts ravel on the other."""
    from jax.flatten_util import ravel_pytree

    flat, unravel = ravel_pytree(params)
    return np.asarray(flat, dtype=np.float32), unravel
