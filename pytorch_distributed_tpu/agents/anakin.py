"""The closed Anakin loop (ISSUE 12 tentpole): env fleet + learner in
ONE accelerator-owning process, zero host round-trips on the experience
path.

PR 7 put the env fleet on the device (envs/device_env.py) and fused
policy + env physics + n-step assembly into one donated scan
(models/policies.build_fused_rollout), but the device actor still ran
as a separate CPU-pinned process shipping finished chunks through the
spawn queue — ~56 KB per transition of pickle/pipe/H2D work while the
chip idled (BENCH_r03).  Podracer's Anakin topology (Hessel et al.
2021) and Ape-X's own act→store→sample→learn cycle (Horgan et al.
2018) both say the whole loop belongs in one program on one chip.
This module is that loop:

- the env fleet lives IN the learner process (``num_actors x
  num_envs_per_actor`` envs as one batched pure-JAX program on the
  fleet seed/epsilon slot contract, so backend choice never changes
  the exploration schedule);
- one driver alternates the donated fused-rollout dispatch
  (``emit="replay"``: transitions scatter straight into the
  device-resident replay ring, PER rows stamped at the running max
  priority via memory/device_per.per_write_masked) and the fused
  learner-step dispatch against the SAME ``ReplayState`` /
  ``PerReplayState`` — no actor processes, no spawn queue, no D2H on
  the experience path at all;
- the acting params ARE the train state's params (one shared
  reference): the published version is the acting version by
  construction, with zero staleness;
- a duty-cycle scheduler (``AnakinParams.rollout_ratio``) balances
  frames collected against updates applied — 0 = strict alternation,
  the bit-reproducible schedule the parity oracle pins;
- ``AnakinParams.double_buffer`` splits the ring into two
  half-capacity halves: learner dispatches sample the stable half
  while rollouts scatter into the other, halves swapping once the
  write half holds ``min_fill`` fresh rows — priority write-back races
  excluded by construction, not by ordering.

Parity contract (tests/test_anakin.py): under a fixed seed and the
strict-alternation schedule, a co-located run is bit-identical to the
split-process ``actor_backend="device"`` path — actions (via ring
contents), emitted transitions, PER priorities, and learner params
after N steps — because every XLA program involved is the SAME program
the split path dispatches (the fused rollout's replay-emit leg and the
learner's fused step), only the host plumbing between them vanishes.

Knobs live in ``config.AnakinParams``, env-overridable as
``TPU_APEX_ANAKIN_<FIELD>`` via ``resolve_anakin`` — the same
spawn-inheritance contract the health/perf/flow planes use.
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Any

import numpy as np

from pytorch_distributed_tpu.config import Options

_ENV_PREFIX = "TPU_APEX_ANAKIN_"


def resolve_anakin(ap=None):
    """AnakinParams + ``TPU_APEX_ANAKIN_<FIELD>`` env overrides — the
    override-by-env contract the health/perf/flow planes use.  Returns
    a NEW instance; the input is never mutated (Options rides spawn
    pickles)."""
    from pytorch_distributed_tpu.config import AnakinParams

    if ap is None:
        ap = AnakinParams()
    changes: dict = {}
    for f in dataclasses.fields(ap):
        raw = os.environ.get(_ENV_PREFIX + f.name.upper())
        if raw is None:
            continue
        cur = getattr(ap, f.name)
        if isinstance(cur, bool):
            changes[f.name] = raw.strip().lower() not in (
                "0", "false", "off", "no", "")
        elif isinstance(cur, int) and not isinstance(cur, bool):
            changes[f.name] = int(float(raw))
        elif isinstance(cur, float):
            changes[f.name] = float(raw)
        else:
            changes[f.name] = raw.strip()
    return dataclasses.replace(ap, **changes) if changes else ap


class AnakinDriver:
    """The co-located act→store→sample→learn driver.

    Owns the train state, the device env fleet, the fused rollout and
    fused learner programs, and the (single or double-buffered) HBM
    ring(s).  ``dispatch_rollout`` / ``dispatch_learn`` are exposed
    individually so the parity tests and the bench can drive bounded
    deterministic schedules; ``run`` is the production duty-cycle loop
    with the learner's usual cadences (publish / checkpoint / stats).
    """

    def __init__(self, opt: Options, spec, memory: Any, param_store,
                 clock, learner_stats, actor_stats=None,
                 process_ind: int = 0):
        import jax
        import jax.numpy as jnp

        from pytorch_distributed_tpu.agents.clocks import ActorStats
        from pytorch_distributed_tpu.factory import (
            anakin_eligible, build_device_env, build_megabatch_train_step,
            build_model, build_train_state_and_step, init_params,
            resolve_megabatch,
        )
        from pytorch_distributed_tpu.memory.device_per import (
            per_write_masked,
        )
        from pytorch_distributed_tpu.memory.device_replay import (
            DevicePerIngest, build_uniform_fused_step, sample_rows,
        )
        from pytorch_distributed_tpu.models.policies import (
            apex_epsilons, build_fused_rollout, init_rollout_carry,
        )
        from pytorch_distributed_tpu.parallel.learner import ShardedLearner
        from pytorch_distributed_tpu.parallel.mesh import make_mesh, replicated
        from pytorch_distributed_tpu.utils import checkpoint as ckpt
        from pytorch_distributed_tpu.utils import perf
        from pytorch_distributed_tpu.utils.metrics import MetricsWriter
        from pytorch_distributed_tpu.utils.profiling import StepTimer
        from pytorch_distributed_tpu.utils.rngs import (
            np_rng, process_key, process_seed,
        )

        ok, why = anakin_eligible(opt)
        if not ok:
            raise RuntimeError(f"anakin driver on an ineligible config: "
                               f"{why}")
        self._jax = jax
        self._version = 0
        self.opt = opt
        self.ap = opt.agent_params
        self.an = resolve_anakin(opt.anakin_params)
        self.memory = memory
        self.param_store = param_store
        self.clock = clock
        self.learner_stats = learner_stats
        self.actor_stats = (actor_stats if actor_stats is not None
                            else ActorStats())
        self.process_ind = process_ind
        pp = opt.parallel_params
        ap = self.ap

        # ---- model + train state (the learner half, as run_learner) ----
        mesh = None
        if len(jax.devices()) > 1:
            mesh = make_mesh(pp.dp_size, pp.mp_size, pp.sp_size,
                             pp.ep_size, pp.pp_size)
        self.mesh = mesh
        # every small device-resident operand (keys, eps, tick, prov,
        # beta, carry) is placed EXPLICITLY in the mesh's replicated
        # layout at creation — the compiled programs' input shardings —
        # so dispatches stage zero implicit reshards and the transfer
        # audit stays clean under a mesh exactly as on one device
        self._sharding = replicated(mesh) if mesh is not None else None
        self.model = build_model(opt, spec)
        params = init_params(opt, spec, self.model, seed=opt.seed)
        if opt.model_file:
            path = ckpt.params_path(opt.model_file) \
                if not opt.model_file.endswith(".msgpack") else opt.model_file
            params = ckpt.load_params(path, params)
        state, step_fn = build_train_state_and_step(opt, spec, self.model,
                                                    params, mesh=mesh)
        self._learner = ShardedLearner(step_fn, mesh, donate=pp.donate)
        self.state = self._learner.place(state)

        # ---- resume: newest complete epoch's train state + counters.
        # The anakin driver keeps resume SIMPLE — state, clocks and the
        # device sampling key, no rollback ladder (the health sentinel's
        # rollback machinery stays a split-topology feature for now).
        assert opt.resume in ("auto", "must", "never"), (
            f"unknown resume mode {opt.resume!r}")
        epoch = None
        if opt.resume != "never":
            epoch = ckpt.resolve_epoch(opt.model_name)
            if epoch is not None:
                self.state = self._learner.place(
                    ckpt.load_epoch_state(epoch,
                                          jax.device_get(self.state)))
                clock.seed_actor_steps(
                    int(epoch.extras.get("actor_step", 0)))
                clock.best_eval_reward.value = max(
                    float(epoch.extras.get("best_eval_reward",
                                           float("-inf"))),
                    ckpt.load_best_score(opt.model_name))
                print(f"[anakin] resumed epoch {epoch.epoch} "
                      f"(step {epoch.learner_step})")
            elif opt.resume == "must":
                raise RuntimeError(
                    f"resume='must' but no complete checkpoint epoch "
                    f"under {ckpt.ckpt_root(opt.model_name)}")
        self._epoch = epoch

        # ---- ring(s): single, or double-buffered halves ----
        self.is_per = isinstance(memory, DevicePerIngest)
        if self.an.double_buffer:
            self.rings = list(memory.attach_halves(mesh=mesh))
        else:
            self.rings = [memory.attach(mesh=mesh)]
        self.sample_ix = 0
        self.write_ix = 0
        self._fresh = 0  # rows into the write half since the last swap
        half_cap = self.rings[0].capacity
        mf = self.an.min_fill or min(ap.learn_start, half_cap - 1)
        self.min_fill = max(1, min(int(mf), half_cap))
        # host-side fill accounting per ring — no device sync on the
        # scheduler's hot path (the in-graph scatter's row count is a
        # pure function of the tick window, fetched with the stats)
        self._fill = [0 for _ in self.rings]
        if epoch is not None and opt.memory_params.checkpoint_replay:
            rows = ckpt.load_epoch_replay(epoch, memory)
            if rows:
                self._fill[0] = min(rows, half_cap)
                print(f"[anakin] replay restored from epoch "
                      f"{epoch.epoch}: {rows} rows")

        # ---- the co-located env fleet + fused rollout ----
        # the WHOLE fleet as one batched program: num_actors x
        # num_envs_per_actor envs on the fleet slot contract (env j of
        # virtual actor i takes seed slot i*N + j and epsilon slot
        # i*N + j of A*N — the same streams the split fleet draws)
        A = max(1, opt.num_actors)
        N = max(1, opt.env_params.num_envs_per_actor)
        self.fleet_envs = A * N
        self.env = build_device_env(opt, 0, self.fleet_envs)
        self.K_roll = max(1, int(opt.env_params.device_rollout_ticks))
        self.rollout = build_fused_rollout(
            self.model.apply, self.env, nstep=ap.nstep, gamma=ap.gamma,
            rollout_ticks=self.K_roll, emit="replay",
            ring_write_fn=per_write_masked if self.is_per else None)
        self.carry = self._place(init_rollout_carry(self.env, ap.nstep))
        self.eps_dev = self._place(jnp.asarray(
            apex_epsilons(0, 1, self.fleet_envs, ap.eps, ap.eps_alpha),
            jnp.float32))
        self.base_key = self._place(
            jnp.asarray(process_key(opt.seed, "actor", 0)))
        self.tick0 = self._place(jnp.int32(0))

        # ---- the fused learner program (the run_learner device path's
        # EXACT constructions, so a co-located step is the same XLA
        # program a split-process learner dispatches — the parity
        # oracle's ground) ----
        K = ap.steps_per_dispatch
        if K <= 0:
            K = 32 if jax.devices()[0].platform == "tpu" else 1
        # ISSUE-13 megabatching: the SAME factory resolution the
        # split-process learner uses, so the co-located twin's learner
        # dispatch is the same XLA program (the parity oracle's ground)
        M, K_mb = resolve_megabatch(opt, K)
        mega_step = None
        if M > 1:
            mega_step = build_megabatch_train_step(opt, self.model)
            if mega_step is None:
                print(f"[anakin] megabatch={M} unsupported for "
                      f"agent_type={opt.agent_type}; sequential fused "
                      f"step at steps_per_dispatch={K}", flush=True)
                M = 1
            else:
                # only an ENGAGED megabatch inflates the dispatch
                # quantum (and K_learn/duty-cycle accounting)
                K = K_mb
        mb_kw = (dict(megabatch=M, megabatch_step=mega_step)
                 if M > 1 else {})
        self.K_learn = K
        self._beta = None
        if self.is_per:
            self._fused_per = self.rings[0].build_fused_step(
                step_fn, ap.batch_size, donate=pp.donate,
                steps_per_call=K, **mb_kw)
            self._fused = None
        else:
            self._fused_per = None
            if K > 1:
                self._fused = build_uniform_fused_step(
                    step_fn, ap.batch_size, steps_per_call=K,
                    donate=pp.donate, **mb_kw)
            else:
                self._fused = jax.jit(
                    lambda ts, rs, key: step_fn(
                        ts, sample_rows(rs, key, ap.batch_size)),
                    donate_argnums=(0,) if pp.donate else ())

        # learner-side sampling key stream (run_learner's scheme: one
        # split amortised over 64 dispatches, beta refreshed with it)
        self._device_key = jax.random.PRNGKey(
            np_rng(opt.seed, "learner", process_ind).integers(2 ** 31))
        saved = (epoch.extras.get("rng", {}).get("learner_device")
                 if epoch is not None else None)
        if saved:
            self._device_key = ckpt.deserialize_prng_key(saved,
                                                         self._device_key)
        self._key_buf: list = []

        # ---- perf plane: ONE monitor carries both counters; live MFU
        # sums the learner program's per-update FLOPs and the rollout's
        # per-frame FLOPs (utils/perf.py drain combines them) ----
        self.perf = perf.get_monitor("learner", opt.perf_params)
        if self.perf.enabled:
            # fp32 models score MFU against the fp32 peak (ISSUE 13)
            _cd = getattr(self.model, "compute_dtype", None)
            if _cd is not None:
                self.perf.set_compute_dtype(jnp.dtype(_cd).name)
            self.perf.register_jit("fused_step",
                                   getattr(self._fused_per or self._fused,
                                           "_cache_size", None))
            self.perf.register_jit("anakin_rollout",
                                   self.rollout._cache_size)
            # seed-derived even though these keys only feed .lower()
            # for the FLOP capture (apexlint rng-key-reuse contract)
            _pkeys = jax.random.split(
                jax.random.PRNGKey(process_seed(opt.seed, "learner",
                                                process_ind)),
                K + 1)[1:]
            _pkeys = (_pkeys.reshape(K, *_pkeys.shape[1:]) if K > 1
                      else _pkeys[0])
            rs0 = self.rings[0].state
            if self.is_per:
                _pbeta = jax.device_put(
                    np.float32(self.rings[0].beta(0)))
                self.perf.capture_flops(
                    lambda: self._fused_per.lower(self.state, rs0,
                                                  _pkeys, _pbeta))
            else:
                self.perf.capture_flops(
                    lambda: self._fused.lower(self.state, rs0, _pkeys))
            self.perf.capture_frame_flops(
                lambda: self.rollout.lower(
                    self.state.params, self.carry, rs0, self.base_key,
                    self.tick0, self.eps_dev, self._make_prov(0)),
                frames_per_call=self.fleet_envs)
        self.audit = self.perf.audit

        # episode accounting (the actor harness's accumulators, fleet-
        # wide) + stat-flush cadence state
        self.episode_reward = np.zeros(self.fleet_envs, dtype=np.float64)
        self.episode_steps = np.zeros(self.fleet_envs, dtype=np.int64)
        self._acc = dict.fromkeys(ActorStats.FIELDS, 0.0)
        self.env_steps = 0
        self._next_flush = ap.actor_freq

        # duty-cycle input: CUMULATIVE frames vs cumulative updates
        # (lstep - lstep0).  Resume seeds it from the same epoch extras
        # the clock rides — a restart that restored lstep but started
        # frames at 0 would read as a huge frames deficit and flood
        # rollout-only (zero updates, zero stats cadences) until the
        # counter caught back up.
        self.frames = (int(epoch.extras.get("actor_step", 0))
                       if epoch is not None else 0)
        self.lstep = int(jax.device_get(self.state.step))
        self.lstep0 = self.lstep
        if epoch is not None:
            self.lstep0 = int(epoch.extras.get("lstep0", self.lstep0))
        clock.set_learner_step(self.lstep)
        self._last_was_rollout = False
        self._last_metrics = None
        # duty-cycle window accumulators (drained on the stats cadence)
        self._roll_s = 0.0
        self._learn_s = 0.0
        self._roll_frames = 0
        self.timer = StepTimer("learner")
        self.writer = MetricsWriter(opt.log_dir, enable_tensorboard=False,
                                    role="learner", run_id=opt.refs)
        # CPU backends block per dispatch (free — the dispatch IS the
        # compute there), which also makes the duty-cycle host timers
        # exact; on TPU timers attribute async-dispatch waits to the
        # NEXT fetch point, a documented approximation
        self._block = jax.devices()[0].platform == "cpu"

    # -- helpers -----------------------------------------------------------

    def _place(self, x):
        """Explicit device placement in the compiled programs' input
        layout (replicated over the mesh when one exists)."""
        if self._sharding is not None:
            return self._jax.device_put(x, self._sharding)
        return self._jax.device_put(x)

    def _make_prov(self, birth_step: int):
        """(actor_id, param_version, birth_step) for the in-graph
        provenance scatter — an EXPLICIT 12-byte device_put per rollout
        dispatch (control plane, not experience; never trips the
        transfer audit)."""
        return self._place(np.asarray([0, self._version, birth_step],
                                      np.int32))

    def _publish(self) -> None:
        from jax.flatten_util import ravel_pytree

        from pytorch_distributed_tpu.factory import published_params

        flat, _ = ravel_pytree(self._jax.device_get(
            published_params(self.opt, self.state)))
        self.param_store.publish(np.asarray(flat, dtype=np.float32))
        self._version = int(getattr(self.param_store, "version", 0) or 0)

    def _save_epoch(self) -> None:
        from pytorch_distributed_tpu.utils import checkpoint as ckpt

        extras = dict(
            learner_step=self.lstep,
            lstep0=self.lstep0,
            actor_step=int(self.clock.actor_step.value),
            best_eval_reward=float(self.clock.best_eval_reward.value),
            replay_size=int(getattr(self.memory, "size", 0)),
            rollbacks=int(self.clock.rollbacks.value),
            skipped_steps=int(self.clock.skipped_steps.value),
            rng=dict(
                learner_device=ckpt.serialize_prng_key(self._device_key)),
        )
        ckpt.save_epoch(
            self.opt.model_name, state=self.state,
            memory=(self.memory
                    if self.opt.memory_params.checkpoint_replay else None),
            extras=extras, retain=self.ap.checkpoint_retain)

    def replay_fill(self) -> float:
        """Fraction of total ring capacity holding valid rows (host
        accounting; both halves count in double-buffer mode)."""
        cap = sum(r.capacity for r in self.rings)
        return min(1.0, sum(self._fill) / max(cap, 1))

    def _maybe_swap(self) -> None:
        """Double-buffer swap schedule: the cold-start split (write
        half detaches from the sample half once it holds ``min_fill``
        rows), then a swap whenever the write half has accumulated
        ``min_fill`` FRESH rows.  Runs only between dispatches, so the
        learner never samples a half a rollout is writing."""
        if not self.an.double_buffer:
            return
        if self.write_ix == self.sample_ix:
            if self._fill[self.write_ix] >= self.min_fill:
                self.write_ix = 1 - self.write_ix
                self._fresh = 0
        elif self._fresh >= self.min_fill:
            self.sample_ix, self.write_ix = self.write_ix, self.sample_ix
            self._fresh = 0

    def want_rollout(self) -> bool:
        """The duty-cycle scheduler: warmup until the sample ring holds
        ``min_fill`` rows, then either the ``rollout_ratio`` frames-
        per-update setpoint or (ratio 0) strict alternation."""
        self._maybe_swap()
        if self._fill[self.sample_ix] < self.min_fill:
            return True
        ratio = self.an.rollout_ratio
        if ratio > 0:
            return self.frames < (self.lstep - self.lstep0) * ratio
        return not self._last_was_rollout

    # -- the two dispatches ------------------------------------------------

    def dispatch_rollout(self):
        """One fused rollout dispatch into the write ring: K_roll ticks
        of the whole fleet, transitions scattered in-graph.  Returns
        the dispatch's RolloutStats (host copies of the per-tick env
        stats — the control-plane D2H; experience never crosses)."""
        jax = self._jax
        ring = self.rings[self.write_ix]
        prov = self._make_prov(self.lstep)
        t0 = time.perf_counter()
        args = (self.state.params, self.carry, ring.state, self.base_key,
                self.tick0, self.eps_dev, prov)
        if self.audit is not None:
            self.carry, ring.state, stats = self.audit.run(self.rollout,
                                                           *args)
        else:
            self.carry, ring.state, stats = self.rollout(*args)
        self.tick0 = self.tick0 + self.K_roll
        stats = jax.device_get(stats)
        dt = time.perf_counter() - t0
        self.timer.add("rollout", dt)
        self._roll_s += dt
        fed = int(stats.fed)
        frames = self.K_roll * self.fleet_envs
        self.frames += frames
        self._roll_frames += frames
        self.env_steps += frames
        self.perf.note_frames(frames)
        self.clock.add_actor_steps(frames)
        self._fill[self.write_ix] = min(self._fill[self.write_ix] + fed,
                                        ring.capacity)
        self._fresh += fed
        # surface the scatter in the ingest's host accounting so the
        # fleet STATUS replay_size/fill and checkpoint extras see the
        # zero-copy rows too (queue drains count themselves)
        if hasattr(self.memory, "note_scatter"):
            self.memory.note_scatter(fed)
        self._last_was_rollout = True
        # episode + stat accounting shared with the device actor loop
        from pytorch_distributed_tpu.agents.actor import (
            fold_rollout_episode_stats,
        )

        self._acc["total_nframes"] += frames
        fold_rollout_episode_stats(stats.step_reward, stats.step_terminal,
                                   self.episode_reward, self.episode_steps,
                                   self._acc)
        if self.env_steps >= self._next_flush:
            self._next_flush += self.ap.actor_freq
            if any(self._acc.values()):
                self.actor_stats.add(**self._acc)
                self._acc = dict.fromkeys(self._acc, 0.0)
        return stats

    def dispatch_learn(self):
        """One fused learner dispatch (K_learn scanned updates) sampling
        the stable ring; PER priorities write back in-graph."""
        jax = self._jax
        ring = self.rings[self.sample_ix]
        if not self._key_buf:
            K = self.K_learn
            keys = jax.random.split(self._device_key, 64 * K + 1)
            self._device_key = keys[0]
            rest = self._place(keys[1:])  # one bulk placement / 64
            self._key_buf = (list(rest.reshape(64, K, *rest.shape[1:]))
                             if K > 1 else list(rest))
            if self.is_per:
                self._beta = self._place(
                    np.float32(self.rings[0].beta(self.lstep)))
        key = self._key_buf.pop()
        t0 = time.perf_counter()
        if self.is_per:
            if self.audit is not None:
                self.state, ring.state, m = self.audit.run(
                    self._fused_per, self.state, ring.state, key,
                    self._beta)
            else:
                self.state, ring.state, m = self._fused_per(
                    self.state, ring.state, key, self._beta)
        elif self.K_learn > 1:
            if self.audit is not None:
                self.state, m = self.audit.run(self._fused, self.state,
                                               ring.state, key)
            else:
                self.state, m = self._fused(self.state, ring.state, key)
        else:
            if self.audit is not None:
                self.state, m, _td = self.audit.run(
                    self._fused, self.state, ring.state, key)
            else:
                self.state, m, _td = self._fused(self.state, ring.state,
                                                 key)
        if self._block:
            jax.block_until_ready(self.state.params)
        dt = time.perf_counter() - t0
        self.timer.add("learn", dt)
        self._learn_s += dt
        self.lstep += self.K_learn
        self.clock.set_learner_step(self.lstep)
        self.perf.note_updates(self.K_learn)
        self._last_was_rollout = False
        self._last_metrics = m
        return m

    # -- the production loop -----------------------------------------------

    def run(self) -> None:
        jax = self._jax
        ap = self.ap
        clock = self.clock
        deadline = (time.monotonic() + ap.max_seconds) \
            if ap.max_seconds > 0 else float("inf")
        self._publish()
        if self.perf.enabled:
            self.writer.scalars(self.perf.drain(step=self.lstep),
                                step=self.lstep)
        t_cadence = time.monotonic()
        last_stats_lstep = self.lstep
        while self.lstep < ap.steps and not clock.stop.is_set() \
                and time.monotonic() < deadline:
            clock.bump_progress("learner")
            if self.an.drain_ingest and hasattr(self.memory, "drain"):
                # hybrid topologies: remote DCN actors' chunks land in
                # ring 0 between dispatches (zero rows on the pure
                # co-located path — the fleet never touches the queue)
                with self.timer.phase("drain"):
                    fed = self.memory.drain()
                if fed:
                    self._fill[0] = min(self._fill[0] + fed,
                                        self.rings[0].capacity)
            prev = self.lstep
            if self.want_rollout():
                self.dispatch_rollout()
            else:
                self.dispatch_learn()
            crossed = lambda freq: (freq and
                                    self.lstep // freq != prev // freq)
            if crossed(ap.param_publish_freq):
                with self.timer.phase("publish"):
                    self._publish()
            if crossed(ap.checkpoint_freq):
                self._save_epoch()
            if crossed(ap.learner_freq):
                now = time.monotonic()
                vals = {}
                if self._last_metrics is not None:
                    vals = {k: float(v) for k, v in jax.device_get(
                        self._last_metrics).items()}
                self.learner_stats.add(
                    counter=1,
                    critic_loss=vals.get("learner/critic_loss", 0.0),
                    actor_loss=vals.get("learner/actor_loss", 0.0),
                    q_mean=vals.get("learner/q_mean", 0.0),
                    grad_norm=vals.get("learner/grad_norm", 0.0),
                    steps_per_sec=(self.lstep - last_stats_lstep)
                    / max(now - t_cadence, 1e-9),
                )
                busy = self._roll_s + self._learn_s
                duty = self._roll_s / busy if busy > 0 else 0.0
                window = max(now - t_cadence, 1e-9)
                rows = {
                    "anakin/duty_cycle": duty,
                    "anakin/rollout_frames_per_s":
                        self._roll_frames / window,
                    "anakin/replay_fill": self.replay_fill(),
                }
                self.writer.scalars(rows, step=self.lstep)
                if self.perf.enabled:
                    for tag, v in rows.items():
                        self.perf.set_gauge(tag, v)
                    self.writer.scalars(self.perf.drain(step=self.lstep),
                                        step=self.lstep)
                self.writer.scalars(self.timer.drain(), step=self.lstep)
                self._roll_s = self._learn_s = 0.0
                self._roll_frames = 0
                t_cadence = now
                last_stats_lstep = self.lstep
        # final publication + epoch (also the SIGTERM preemption path:
        # runtime trips clock.stop, the loop drains out, state commits)
        self._publish()
        self._save_epoch()
        if any(self._acc.values()):
            self.actor_stats.add(**self._acc)
        if self.perf.enabled:
            self.writer.scalars(self.perf.drain(step=self.lstep),
                                step=self.lstep)
        self.writer.close()


def run_anakin_learner(opt: Options, spec, process_ind: int, memory: Any,
                       param_store, clock, stats,
                       actor_stats=None) -> None:
    """Learner-process entry for the co-located Anakin topology — the
    ``run_learner`` drop-in the runtime dispatches to when
    ``factory.anakin_active(opt)`` (no actor workers spawn; this loop
    IS the actor fleet and the learner)."""
    driver = AnakinDriver(opt, spec, memory, param_store, clock, stats,
                          actor_stats=actor_stats,
                          process_ind=process_ind)
    driver.run()
