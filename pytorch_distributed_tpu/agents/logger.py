"""Logger process: metrics aggregation.

Re-design of reference core/single_processes/dqn_logger.py /
ddpg_logger.py (near-identical files; unified here).  Same push model: the
workers accumulate into shared counter structs and this process drains on a
cadence — evaluator scalars whenever the flag handshake is raised (reference
dqn_logger.py:23-33), actor/learner accumulators every ``logger_freq``
seconds (reference :34-55) — writing every scalar against the global
learner step as x-axis, with the reference's exact tag names
(utils/metrics.py docstring).
"""

from __future__ import annotations

import time

from pytorch_distributed_tpu.config import Options
from pytorch_distributed_tpu.agents.clocks import (
    ActorStats, EvaluatorStats, GlobalClock, LearnerStats,
)
from pytorch_distributed_tpu.utils.metrics import MetricsWriter


def run_logger(opt: Options, clock: GlobalClock, actor_stats: ActorStats,
               learner_stats: LearnerStats,
               evaluator_stats: EvaluatorStats) -> None:
    ap = opt.agent_params
    writer = MetricsWriter(opt.log_dir, enable_tensorboard=opt.visualize,
                           role="logger", run_id=opt.refs)
    last_drain = time.monotonic()
    finished_at = None
    closing_at = None
    quiescent = 0
    final_a: dict = {}
    final_le: dict = {}
    try:
        while True:
            finished = clock.done(ap.steps)
            if finished and finished_at is None:
                finished_at = time.monotonic()
            # after the run ends, keep draining until the evaluator's final
            # eval lands (grace-capped) so its scalars are not dropped.
            # Grace sits just under runtime._join_all's 240 s deadline —
            # a batch-1 pixel eval on a starved 1-core host takes minutes,
            # and a 60 s grace silently dropped the config-14 run's final
            # point (round 4) — while leaving headroom for the quiescence
            # drains + final write below before the join terminates us.
            closing = finished and (
                evaluator_stats.done.value
                or time.monotonic() - finished_at > 230.0)
            if closing and closing_at is None:
                closing_at = time.monotonic()
            time.sleep(0.2)

            got = evaluator_stats.consume()
            if got is not None:
                # reference dqn_logger.py:23-33; rows carry the CAPTURE
                # wall time so curve crossings date the policy, not the
                # (possibly starved) eval episodes
                at_step, at_wall, ev = got
                writer.scalars({
                    "evaluator/avg_steps": ev["avg_steps"],
                    "evaluator/avg_reward": ev["avg_reward"],
                    "evaluator/nepisodes": ev["nepisodes"],
                    "evaluator/nepisodes_solved": ev["nepisodes_solved"],
                }, step=at_step, wall=at_wall or None)

            def write_group(a: dict, le: dict) -> None:
                step = clock.learner_step.value
                if a["nepisodes"] > 0:  # reference dqn_logger.py:34-47
                    writer.scalars({
                        "actor/avg_steps": a["total_steps"] / a["nepisodes"],
                        "actor/avg_reward": a["total_reward"] / a["nepisodes"],
                        "actor/nepisodes_solved": a["nepisodes_solved"],
                    }, step=step)
                if a["total_nframes"] > 0:
                    writer.scalar("actor/total_nframes", a["total_nframes"],
                                  step=step)
                if le["counter"] > 0:  # reference dqn_logger.py:48-55
                    writer.scalars({
                        "learner/critic_loss": le["critic_loss"] / le["counter"],
                        "learner/actor_loss": le["actor_loss"] / le["counter"],
                        "learner/q_mean": le["q_mean"] / le["counter"],
                        "learner/grad_norm": le["grad_norm"] / le["counter"],
                        "learner/steps_per_sec":
                            le["steps_per_sec"] / le["counter"],
                        # nonzero only for MoE models (models/moe.py);
                        # rides along like actor_loss does for non-DDPG
                        "learner/moe_aux": le["moe_aux"] / le["counter"],
                    }, step=step)
                writer.flush()

            if closing:
                # shutdown race guard: workers flush their accumulators in
                # their own shutdown paths, which can land AFTER the run
                # end is observed here — keep draining until quiescent
                # (nothing arrived for 2 consecutive drains and a settle
                # window passed), MERGING the late fragments so the final
                # datapoint is one aggregate, not several per-fragment
                # averages at the same step
                a, le = actor_stats.drain(), learner_stats.drain()
                arrived = (got is not None or a["nepisodes"] > 0
                           or a["total_nframes"] > 0 or le["counter"] > 0)
                for k, v in a.items():
                    final_a[k] = final_a.get(k, 0.0) + v
                for k, v in le.items():
                    final_le[k] = final_le.get(k, 0.0) + v
                quiescent = 0 if arrived else quiescent + 1
                if quiescent >= 2 \
                        and time.monotonic() - closing_at >= 2.0:
                    write_group(final_a, final_le)
                    break
            elif time.monotonic() - last_drain >= ap.logger_freq:
                last_drain = time.monotonic()
                write_group(actor_stats.drain(), learner_stats.drain())
    finally:
        writer.close()
