"""Shared inference batcher: SEED-style centralized actor forwards.

The inline/pipelined actor loops run rollout inference on each actor
process's OWN host CPU (utils/helpers.pin_to_cpu — the learner alone owns
the accelerator).  That is the right call when the accelerator is remote
or contended, but it leaves the chip idle between learner dispatches and
burns the actor host's cores on convnet forwards: BENCH_r03 shows the
flagship e2e topology pacing at ~475 env frames/s with ``time_act_ms``
(13.45) dwarfing ``time_env_ms`` (0.55) — the actor fleet is inference-
bound on a CPU while a TPU idles (ISSUE 4 motivation).

``actor_backend=batched`` flips the topology to the SEED architecture
(Espeholt et al. 2019; PAPERS.md): actor processes stop holding model
replicas entirely — no param fetches, no unravels, no local jit — and
submit observation batches to an ``InferenceServer`` THREAD living in the
process that owns the accelerator (the learner parent, runtime.py).  The
server coalesces whatever requests are pending, runs ONE wide forward on
the device, and scatters packed results back over per-client queues.  The
actor's software pipeline (agents/actor.py) is unchanged: submit is the
dispatch, collect is the sync, and the device forward + transfers overlap
the host's env stepping and feed work.

Determinism: per-row PRNG keys are ``fold_in(fold_in(fold_in(root, tick?
no — actor base key), tick), row)`` (models/policies.tick_keys), a pure
function of (actor, tick, row) — so action streams are independent of how
rows get batched together, and on a same-device server they are
bit-identical to the local loops.  What batched mode does NOT preserve is
the actors' weight-staleness schedule: the server refreshes from the
ParamStore on its own throttle (``sync_secs``), not per-actor cadences.

Wire format is deliberately dumb — numpy arrays over spawn-context
queues; clients are picklable and carry no jax state, so a batched actor
process never needs a model, flattener, or prefetcher.
"""

from __future__ import annotations

import multiprocessing as mp
import queue as _queue
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from pytorch_distributed_tpu.config import Options

_CTX = mp.get_context("spawn")

# response payload marker for a server-side failure: clients re-raise
# instead of hanging on a queue nobody will ever fill again
_ERROR = "__inference_error__"


class InferenceClient:
    """Actor-side handle: submit/collect one in-flight request.

    Picklable (rides the actor spec tuple through spawn); holds only the
    shared request queue, this client's response queue, and its row
    geometry.  ``begin_session`` must be called in the actor process
    before the first submit — it stamps a fresh nonce so responses to a
    dead incarnation of this slot (actor restarts are routine, runtime
    supervision) can never be mistaken for this one's.
    """

    def __init__(self, client_id: int, family: str, req_q, resp_q):
        self.client_id = client_id
        self.family = family
        self._req_q = req_q
        self._resp_q = resp_q
        self._nonce = 0
        self._key: Optional[np.ndarray] = None
        self._eps: Optional[np.ndarray] = None
        self._prev_obs: Optional[np.ndarray] = None

    def begin_session(self, base_key=None, eps=None) -> None:
        """Fresh incarnation: drain stale responses, stamp a nonce, bind
        this actor's PRNG base key + per-env epsilon ladder (sent with
        every request — a few dozen bytes — so the server stays
        stateless about clients)."""
        self._nonce = int(time.monotonic_ns() & 0x7FFFFFFF) or 1
        if base_key is not None:
            self._key = np.asarray(base_key)
        if eps is not None:
            self._eps = np.asarray(eps, np.float32)
        self._prev_obs = None  # first request re-seeds the server stack
        while True:
            try:
                self._resp_q.get_nowait()
            except _queue.Empty:
                break

    def submit(self, obs: np.ndarray, tick: int) -> int:
        """Ship this tick's obs.  Frame-stacked uint8 image batches whose
        rows all satisfy the roll property (``obs[:, :-1] == prev[:,
        1:]`` — no env reset this tick) go FRAME-PACKED: only the newest
        frame per env crosses to the server, which rolls its
        device-resident stack (models/policies.build_packed_roll_act);
        anything else — first tick, any reset, low-dim obs — ships full
        and re-seeds the server's stack.  The check is a cheap host
        memcmp against the previous tick, so packing is automatic and
        env-agnostic: it can never desync the device stack from what the
        env actually emitted."""
        obs = np.ascontiguousarray(obs)
        mode = "full"
        if (self.family == "dqn" and obs.dtype == np.uint8
                and obs.ndim >= 3 and obs.shape[1] > 1
                and self._prev_obs is not None
                and np.array_equal(obs[:, :-1], self._prev_obs[:, 1:])):
            mode = "packed"
            payload = np.ascontiguousarray(obs[:, -1])
        else:
            payload = obs
        self._prev_obs = obs
        self._req_q.put((self.client_id, self._nonce, int(tick), mode,
                         payload, self._eps, self._key))
        return int(tick)

    def collect(self, handle: int, timeout: float = 300.0) -> np.ndarray:
        """Block for the response to ``handle`` (the submitted tick).
        Responses from an older incarnation are dropped; a server error
        sentinel re-raises here so the actor dies loudly instead of
        spinning against a dead server."""
        deadline = time.monotonic() + timeout
        while True:
            remain = deadline - time.monotonic()
            if remain <= 0:
                raise TimeoutError(
                    f"inference client {self.client_id}: no response for "
                    f"tick {handle} within {timeout}s (server dead?)")
            try:
                nonce, tick, payload = self._resp_q.get(timeout=remain)
            except _queue.Empty:
                continue
            if isinstance(payload, tuple) and payload[:1] == (_ERROR,):
                raise RuntimeError(
                    f"inference server failed: {payload[1]}")
            if nonce != self._nonce:
                continue  # a dead incarnation's leftover
            if tick != handle:
                raise RuntimeError(
                    f"inference client {self.client_id}: got tick {tick}, "
                    f"expected {handle} (protocol violated)")
            return payload


class InferenceServer:
    """Batching forward server; one thread in the accelerator-owning
    process (runtime.Topology starts/stops it when
    ``actor_backend=batched``).

    Scheduling is greedy coalescing: block for the first pending request,
    then sweep whatever else is already queued (no artificial batching
    window — with pipelined clients there is always a tick of host work
    in flight to hide the forward under, and a wait would add straggler
    latency for nothing).  The single-client case — the production 1x16
    topology — skips concat/pad entirely and dispatches the same fused
    ``build_packed_act`` program the local pipelined loop runs, with the
    obs buffer device_put once and handed to the jit.
    Multi-client sweeps concatenate rows, pad to a power-of-two bucket
    (bounded compile count), and scatter the packed columns back.
    """

    def __init__(self, opt: Options, spec, param_store,
                 max_batch: int = 1024, sync_secs: float = 1.0):
        assert opt.agent_type in ("dqn", "ddpg"), (
            f"batched inference serves the flat families, not "
            f"{opt.agent_type} (recurrent actors keep per-env carry "
            f"state; resolve_actor_backend downgrades them)")
        self.opt = opt
        self.spec = spec
        self.param_store = param_store
        self.max_batch = max_batch
        self.sync_secs = sync_secs
        self._req_q = _CTX.Queue()
        self._clients: Dict[int, Any] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._params = None
        self._version = 0
        self._last_sync = 0.0
        # per-client device-resident frame stacks for the packed path:
        # client_id -> device array, or ("host", rows) parked seed
        self._stacks: Dict[int, Any] = {}
        # observability: swept into the learner-side metrics by whoever
        # owns the server (bench reads them off the object directly)
        self.stats = {"requests": 0, "batches": 0, "rows": 0,
                      "widest_batch": 0, "param_refreshes": 0}
        # perf plane (utils/perf.py): served-rows counter + retrace
        # watch on the server's jits; lands in the T_STATUS ``perf``
        # block via the process registry (the server lives in the
        # gateway's process, so no extra plumbing)
        from pytorch_distributed_tpu.utils import perf

        self.perf = perf.get_monitor("inference", opt.perf_params)

    # -- wiring (parent process, before spawn) ------------------------------

    def make_client(self, client_id: int) -> InferenceClient:
        resp_q = _CTX.Queue()
        self._clients[client_id] = resp_q
        return InferenceClient(client_id, self.opt.agent_type,
                               self._req_q, resp_q)

    def start(self) -> None:
        self._thread = threading.Thread(target=self._serve,
                                        name="inference-server",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._req_q.put(None)  # wake the blocking get
        if self._thread is not None:
            self._thread.join(timeout=10.0)

    def healthy(self) -> bool:
        """False once the serve thread has died abnormally.  Watched by
        the runtime monitor: without it, a dead server turns every
        supervised actor restart into a full collect() timeout — the
        crashed thread broadcasts ONE error sentinel per live client,
        but a freshly respawned actor drains its queue in begin_session
        and then blocks on a server that will never answer, burning the
        restart budget at 300 s per attempt instead of failing fast."""
        return (self._thread is None or self._thread.is_alive()
                or self._stop.is_set())

    # -- device programs ----------------------------------------------------

    def _build(self) -> None:
        """Model + jitted programs, built lazily INSIDE the serve thread:
        the constructor runs in the parent before workers spawn, and
        paying the device compile there would serialize it against the
        learner's own startup compiles."""
        import jax

        from pytorch_distributed_tpu.factory import (
            build_model, init_params,
        )
        from pytorch_distributed_tpu.models.policies import (
            build_packed_act, build_packed_act_rowkeys, tick_keys,
        )
        from pytorch_distributed_tpu.agents.param_store import (
            make_flattener,
        )

        model = build_model(self.opt, self.spec)
        params0 = init_params(self.opt, self.spec, model,
                              seed=self.opt.seed)
        _, self._unravel = make_flattener(params0)
        if self.opt.agent_type == "dqn":
            # no donate_obs: a feedforward act has no output that can
            # alias the obs buffer, so donation would only warn (the
            # buffers XLA genuinely reuses in place are the RECURRENT
            # carry and the frame-packed roll stack below)
            self._act_single = build_packed_act(model.apply)
            self._act_rows = build_packed_act_rowkeys(model.apply)
            from pytorch_distributed_tpu.models.policies import (
                build_packed_roll_act,
            )

            self._roll_act = build_packed_roll_act(model.apply)
        else:  # ddpg: deterministic forward, noise stays actor-side
            fwd = lambda p, o: model.apply(p, o,
                                           method=model.forward_actor)
            self._act_single = jax.jit(fwd)
            self._act_rows = self._act_single
        # per-row key expanders, cached per row count (row counts are
        # per-client env widths — a handful of static shapes)
        self._expanders: Dict[int, Any] = {}

        def expander(n: int):
            fn = self._expanders.get(n)
            if fn is None:
                fn = jax.jit(lambda bk, t: tick_keys(bk, t, n))
                self._expanders[n] = fn
            return fn

        self._expander = expander
        self.perf.register_jit("act_single",
                               getattr(self._act_single, "_cache_size",
                                       None))
        self.perf.register_jit("act_rows",
                               getattr(self._act_rows, "_cache_size",
                                       None))

    def _refresh_params(self, block: bool) -> None:
        """Pull the newest published weights onto the device.  Blocking
        only for the very first request (nobody can act on unseeded
        weights); afterwards refreshes ride a ``sync_secs`` throttle so
        a fast-publishing learner can't turn the weight plane into a
        device-transfer firehose."""
        now = time.monotonic()
        if self._params is not None:
            if (now - self._last_sync < self.sync_secs
                    or self.param_store.version <= self._version):
                return
            got = self.param_store.fetch(self._version)
        else:
            got = self.param_store.wait(0, timeout=300.0,
                                        stop=self._stop) if block else None
        if got is None:
            return
        flat, version = got
        self._params = self._unravel(flat)  # lands on the server device
        self._version = version
        self._last_sync = now
        self.stats["param_refreshes"] += 1

    # -- serve loop ---------------------------------------------------------

    def _serve(self) -> None:
        perf_writer = None
        last_perf = time.monotonic()
        try:
            self._build()
            if self.perf.enabled:
                # the server owns no stats cadence of its own, so the
                # serve loop drains its monitor every ~15 s — without
                # this the registered retrace watch never runs and the
                # served-frames rate never reaches the metrics stream
                from pytorch_distributed_tpu.utils.metrics import (
                    MetricsWriter,
                )

                perf_writer = MetricsWriter(
                    self.opt.log_dir, enable_tensorboard=False,
                    role="inference", run_id=self.opt.refs)
                self.perf.drain()  # anchor past the build compiles
            while not self._stop.is_set():
                if perf_writer is not None \
                        and time.monotonic() - last_perf >= 15.0:
                    last_perf = time.monotonic()
                    perf_writer.scalars(self.perf.drain(), step=0)
                try:
                    first = self._req_q.get(timeout=0.2)
                except _queue.Empty:
                    continue
                if first is None:
                    continue
                batch = [first]
                rows = len(first[4])
                while rows < self.max_batch:
                    try:
                        nxt = self._req_q.get_nowait()
                    except _queue.Empty:
                        break
                    if nxt is None:
                        continue
                    batch.append(nxt)
                    rows += len(nxt[4])
                self._refresh_params(block=True)
                self.stats["requests"] += len(batch)
                self.stats["batches"] += 1
                self.stats["rows"] += rows
                self.stats["widest_batch"] = max(
                    self.stats["widest_batch"], rows)
                self.perf.note_frames(rows)
                # Frame-packed requests carry per-client device state
                # (the roll stack), so they dispatch as one small fused
                # program per client — ALL issued asynchronously first,
                # then synced, so N packed clients cost N dispatches but
                # only one device round-trip of latency, not N blocking
                # syncs.  Full requests coalesce into one wide forward.
                # The trade is deliberate: packing buys a C-factor
                # upload cut per client at the price of the cross-client
                # wide batch; the topology this serves is a few actors
                # with WIDE env vectors (the wide batch is already
                # inside each request), not a large fleet of narrow
                # ones — those should run unpacked low-dim obs, which
                # coalesce below.
                inflight = [self._begin_packed(req) for req in batch
                            if req[3] == "packed"]
                full = [r for r in batch if r[3] == "full"]
                if full:
                    self._dispatch(full)
                for (cid, nonce, tick), out in inflight:
                    self._clients[cid].put((nonce, tick,
                                            np.asarray(out)))
        except BaseException as e:  # noqa: BLE001 - broadcast, then die
            if self._stop.is_set():
                return  # shutdown race (e.g. interrupted param wait)
            from pytorch_distributed_tpu.utils import flight_recorder

            flight_recorder.get_recorder("inference").record(
                "server-crash", error=repr(e))
            err = (0, 0, (_ERROR, repr(e)))
            for resp_q in self._clients.values():
                try:
                    resp_q.put(err)
                except Exception:  # noqa: BLE001
                    pass
            if not self._stop.is_set():
                raise
        finally:
            if perf_writer is not None:
                perf_writer.scalars(self.perf.drain(), step=0)
                perf_writer.close()

    def _begin_packed(self, req: Tuple):
        """Dispatch one frame-packed request WITHOUT syncing: roll the
        client's device-resident stack by its new frames and act, fused
        in one program — only the newest frame crossed the (possibly
        tunnelled) link.  Returns ``((cid, nonce, tick), out_handle)``
        for the caller to sync after every pending dispatch is issued.
        The stack seed always exists: a client's first
        post-``begin_session`` submit is a full upload by
        construction."""
        import jax

        cid, nonce, tick, _mode, new, eps, key = req
        stack = self._stacks[cid]
        if isinstance(stack, tuple):  # host-parked seed (multi-path full)
            stack = jax.device_put(stack[1])
        stack, out = self._roll_act(self._params, stack,
                                    jax.device_put(new), np.asarray(key),
                                    tick, np.asarray(eps, np.float32))
        self._stacks[cid] = stack
        if hasattr(out, "copy_to_host_async"):
            out.copy_to_host_async()
        return (cid, nonce, tick), out

    def _dispatch(self, batch: List[Tuple]) -> None:
        import jax

        if len(batch) == 1:
            cid, nonce, tick, _mode, obs, eps, key = batch[0]
            obs_dev = jax.device_put(obs)
            if self.family == "dqn":
                # the full upload doubles as the roll-stack seed for any
                # frame-packed follow-ups (obs_dev is NOT donated here)
                self._stacks[cid] = obs_dev
                out = self._act_single(self._params, obs_dev,
                                       np.asarray(key), tick,
                                       np.asarray(eps, np.float32))
            else:
                out = self._act_single(self._params, obs_dev)
            self._clients[cid].put((nonce, tick, np.asarray(out)))
            return
        # multi-client sweep: one wide forward over concatenated rows,
        # padded to a power-of-two bucket so compile count stays bounded
        sizes = [len(req[4]) for req in batch]
        total = sum(sizes)
        padded = 1
        while padded < total:
            padded *= 2
        obs = np.concatenate([req[4] for req in batch])
        if self.family == "dqn":
            for req in batch:  # park roll-stack seeds host-side (lazy
                self._stacks[req[0]] = ("host", req[4])  # upload on use)
        if padded > total:
            obs = np.concatenate(
                [obs, np.zeros((padded - total, *obs.shape[1:]),
                               obs.dtype)])
        obs_dev = jax.device_put(obs)
        if self.family == "dqn":
            keys = [np.asarray(self._expander(n)(np.asarray(req[6]),
                                                 req[2]))
                    for n, req in zip(sizes, batch)]
            keys.append(np.zeros((padded - total, 2),
                                 keys[0].dtype))
            eps = np.concatenate(
                [np.asarray(req[5], np.float32) for req in batch]
                + [np.zeros(padded - total, np.float32)])
            out = np.asarray(self._act_rows(self._params, obs_dev,
                                            np.concatenate(keys), eps))
            cuts = np.cumsum(sizes)[:-1]
            parts = np.split(out[:, :total], cuts, axis=1)
        else:
            out = np.asarray(self._act_rows(self._params, obs_dev))
            parts = np.split(out[:total], np.cumsum(sizes)[:-1])
        for (cid, nonce, tick, _m, _o, _e, _k), part in zip(batch, parts):
            self._clients[cid].put((nonce, tick, part))

    @property
    def family(self) -> str:
        return self.opt.agent_type
