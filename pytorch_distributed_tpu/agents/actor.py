"""Actor processes: asynchronous experience collection.

Re-design of reference core/single_processes/dqn_actor.py and
ddpg_actor.py.  Same topology — rollout workers with a full local model
replica, diversified by the Ape-X exploration schedule and per-process
seeds — with two structural upgrades:

- the reference's implicit shared-CUDA weight pulls become versioned
  ``ParamStore`` fetches on the ``actor_sync_freq`` cadence (reference
  dqn_actor.py:176-178), and its inline deque bookkeeping becomes the
  unit-tested ``NStepAssembler``;
- every actor is **vectorized**: it steps ``num_envs_per_actor`` envs with
  ONE jitted batched forward per tick (envs/vector.py) — the reference
  reserves this knob but asserts it to 1 (reference utils/options.py:32);
  batch-1 inference is the latency wall SURVEY.md §7 flags, and batching is
  how a TPU-host actor feeds the learner fast enough.  N=1 degenerates to
  the reference's exact per-step loop.

Cadences mirror the reference: stats pushed every ``actor_freq`` env steps
(reference dqn_actor.py:180-192), global actor-step counter advanced per
env step (reference :166-167), loop until the global learner clock reaches
``steps`` (reference :62).

Exploration diversity follows Ape-X across the whole fleet: env ``j`` of
actor ``i`` takes exploration slot ``i*N + j`` of ``num_actors*N``
(reference dqn_actor.py:33-36 has one slot per actor).
"""

from __future__ import annotations

from typing import Any, List

import numpy as np

from pytorch_distributed_tpu.config import Options
from pytorch_distributed_tpu.factory import (
    EnvSpec, build_env_vector, build_model, init_params,
)
from pytorch_distributed_tpu.agents.clocks import ActorStats, GlobalClock
from pytorch_distributed_tpu.agents.param_store import (
    ParamStore, make_flattener,
)
from pytorch_distributed_tpu.ops.nstep import NStepAssembler
from pytorch_distributed_tpu.utils.random_process import (
    OrnsteinUhlenbeckProcess,
)
from pytorch_distributed_tpu.utils.helpers import (
    pin_to_cpu, unravel_on_cpu,
)
from pytorch_distributed_tpu.utils.rngs import process_key, process_seed


class _ActorHarness:
    """Shared plumbing for both actor families: vector env + model/param
    setup, per-env n-step feeds, stat accumulation, sync cadence."""

    def __init__(self, opt: Options, spec: EnvSpec, process_ind: int,
                 memory: Any, param_store: ParamStore, clock: GlobalClock,
                 stats: ActorStats):
        self.opt = opt
        self.ap = opt.agent_params
        self.spec = spec
        self.process_ind = process_ind
        self.memory = memory
        self.param_store = param_store
        self.clock = clock
        self.stats = stats

        self.num_envs = max(1, opt.env_params.num_envs_per_actor)
        self.env = build_env_vector(opt, process_ind, self.num_envs)
        self.env.train()
        self.model = build_model(opt, spec)
        params0 = init_params(opt, spec, self.model, seed=process_seed(
            opt.seed, "actor", process_ind))
        _, self.unravel = make_flattener(params0)
        # block until the learner publishes the initial weights — the
        # explicit version of the reference's pre-spawn hard sync
        # (reference dqn_actor.py:26-30).  Generous timeout: the first
        # publication sits behind the learner process's remote XLA
        # compiles, which can take minutes on a tunnelled chip; a dead
        # learner is caught by the stop event, not this timeout.
        flat, self.version = param_store.wait(0, timeout=300.0,
                                              stop=clock.stop)
        if hasattr(memory, "set_stop"):
            # stop-aware feeding: a flush blocked on a full queue after
            # the learner stopped draining must abort, not deadlock the
            # teardown join
            memory.set_stop(clock.stop)
        # rollout inference is pinned to the host CPU: the learner owns
        # the accelerator; batch-1/small-batch forwards must not round-trip
        # a (possibly tunnelled) chip (utils/helpers.py pin_to_cpu)
        self.params = unravel_on_cpu(self.unravel, flat)

        N = self.num_envs
        self.assemblers: List[NStepAssembler] = [
            NStepAssembler(self.ap.nstep, self.ap.gamma) for _ in range(N)]
        self.episode_steps = np.zeros(N, dtype=np.int64)
        self.episode_reward = np.zeros(N, dtype=np.float64)

        # Actor-computed initial PER priorities (the plumbing the reference
        # anticipated but never finished, reference dqn_actor.py:113-115):
        # per env, q_sel of each acted step FIFO-aligned with the
        # assembler's FIFO emissions, plus a one-tick holding pen for
        # steady-state emissions whose bootstrap state's q_max only becomes
        # known at the NEXT tick's batched forward.
        from collections import deque

        self.per_priorities = (opt.memory_params.enable_per
                               and opt.agent_type == "dqn")
        self._q_hist = [deque() for _ in range(N)]
        self._q_pending: List[list] = [[] for _ in range(N)]

        # local stat accumulators, flushed every actor_freq env steps
        self._acc = dict.fromkeys(ActorStats.FIELDS, 0.0)
        self.env_steps = 0
        self._next_flush = self.ap.actor_freq
        self._next_sync = self.ap.actor_sync_freq

        from pytorch_distributed_tpu.utils import tracing
        from pytorch_distributed_tpu.utils.metrics import MetricsWriter
        from pytorch_distributed_tpu.utils.profiling import StepTimer

        self.timer = StepTimer("actor")
        self._timing_writer = MetricsWriter(
            opt.log_dir, enable_tensorboard=False,
            role=f"actor-{process_ind}", run_id=opt.refs)
        # distributed-trace origin: every chunk this actor flushes is
        # stamped with a trace id here and records an "enqueue" span (a
        # blocking put IS backpressure); downstream hops — gateway, feed,
        # sample, learn — attach to the same id (utils/tracing.py)
        self.tracer = tracing.get_tracer("actor")
        if hasattr(memory, "set_tracer"):
            memory.set_tracer(self.tracer)

    # -- one vector tick ----------------------------------------------------

    def advance(self, actions, next_obs, rewards, terminals, infos,
                q_sel=None, q_max=None) -> None:
        """Feed assemblers/memory for one batched env step and run every
        cadence (counter, stats, weight sync).  ``q_sel``/``q_max`` are this
        tick's per-env Q diagnostics from the batched forward (DQN actors);
        with PER enabled they become initial priorities."""
        if self.per_priorities:
            self._resolve_pending(q_max)
        for j in range(self.num_envs):
            true_next = infos[j].get("final_obs", next_obs[j])
            truncated = bool(infos[j].get("truncated", False))
            if self.per_priorities:
                self._q_hist[j].append(float(q_sel[j]))
            transitions = self.assemblers[j].feed(
                self._obs[j], actions[j], float(rewards[j]), true_next,
                bool(terminals[j]), truncated=truncated)
            if self.per_priorities:
                self._feed_with_priorities(j, transitions,
                                           bool(terminals[j]), truncated)
            else:
                for t in transitions:
                    self.memory.feed(t, None)
            self.episode_steps[j] += 1
            self.episode_reward[j] += float(rewards[j])
            if terminals[j]:
                self._record_episode(j, infos[j])
                self.on_env_reset(j)
        self._obs = next_obs
        self._run_cadences()

    def _record_episode(self, j: int, info: dict) -> None:
        """Fold env slot j's finished episode into the stat accumulators."""
        solved = bool(info.get("solved", self.episode_reward[j] > 0))
        self._acc["nepisodes"] += 1
        self._acc["nepisodes_solved"] += float(solved)
        self._acc["total_steps"] += float(self.episode_steps[j])
        self._acc["total_reward"] += float(self.episode_reward[j])
        self.episode_steps[j] = 0
        self.episode_reward[j] = 0.0

    def _run_cadences(self) -> None:
        """Per-tick counter bump + the stat-flush and weight-sync cadences
        (reference dqn_actor.py:166-192)."""
        N = self.num_envs
        self.env_steps += N
        self.clock.add_actor_steps(N)  # reference dqn_actor.py:166-167
        self._acc["total_nframes"] += N
        if self.env_steps >= self._next_flush:
            self._next_flush += self.ap.actor_freq
            self.flush_stats()
            step = self.clock.learner_step.value
            self._timing_writer.scalars(self.timer.drain(), step=step)
            self.tracer.flush_to(self._timing_writer, step=step)
            if hasattr(self.memory, "flush"):
                self.memory.flush()  # queue feeders drain on the cadence
        if self.env_steps >= self._next_sync:
            self._next_sync += self.ap.actor_sync_freq
            got = self.param_store.fetch(self.version)
            if got is not None:
                flat, self.version = got
                self.params = unravel_on_cpu(self.unravel, flat)

    # -- actor-side TD-error priorities (PER) -------------------------------

    def _resolve_pending(self, q_max) -> None:
        """Steady-state emissions held from the previous tick bootstrap
        from the state the actor is looking at NOW — its q_max just arrived
        with this tick's forward.  priority = |R + gamma_m * maxQ(s_end) -
        q_sel(s_t)|, the n-step TD estimate under the actor's weights."""
        for j in range(self.num_envs):
            if not self._q_pending[j]:
                continue
            for t, q_t in self._q_pending[j]:
                pr = abs(float(t.reward)
                         + float(t.gamma_n) * float(q_max[j]) - q_t)
                self.memory.feed(t, pr)
            self._q_pending[j] = []

    def _feed_with_priorities(self, j: int, transitions,
                              terminal: bool, truncated: bool) -> None:
        if terminal or truncated:
            # episode boundary: every window closed this tick.  True
            # terminals have a zero bootstrap so the TD estimate needs no
            # future q; truncated tails would need q(final_obs), which was
            # never computed — they take the standard new-sample max
            # priority (None).
            for t in transitions:
                q_t = self._q_hist[j].popleft()
                if truncated:
                    self.memory.feed(t, None)
                else:
                    self.memory.feed(t, abs(float(t.reward) - q_t))
            self._q_hist[j].clear()  # next episode starts a fresh history
        else:
            for t in transitions:  # bootstrap q arrives next tick
                self._q_pending[j].append((t, self._q_hist[j].popleft()))

    def start(self) -> None:
        self._obs = self.env.reset()

    def on_env_reset(self, j: int) -> None:
        """Hook for per-env exploration state (DDPG OU paths)."""

    def flush_stats(self) -> None:
        if any(self._acc.values()):
            self.stats.add(**self._acc)
            self._acc = dict.fromkeys(ActorStats.FIELDS, 0.0)

    def shutdown(self) -> None:
        # Best-effort final drain: over DCN a terminally disconnected
        # transport raises from these feeds/flushes (parallel/dcn.py
        # DcnDisconnected), and a teardown crash here would mask WHY the
        # loop ended — the runner's exit code must come from the
        # stop-vs-disconnected split (fleet._remote_actor_main), not
        # from a flush traceback.  Local queue transports never raise
        # these, so nothing is hidden on the single-host path.
        try:
            for j in range(self.num_envs):  # unresolved holds: max priority
                for t, _q in self._q_pending[j]:
                    self.memory.feed(t, None)
                self._q_pending[j] = []
            self.flush_stats()
            if hasattr(self.memory, "flush"):
                self.memory.flush()
        except (ConnectionError, OSError):
            pass
        from pytorch_distributed_tpu.memory.feeder import QueueFeeder

        if isinstance(self.memory, QueueFeeder):
            self.memory.close()
        self.tracer.flush_to(self._timing_writer,
                             step=self.clock.learner_step.value)
        self._timing_writer.close()


def run_dqn_actor(opt: Options, spec: EnvSpec, process_ind: int, memory: Any,
                  param_store: ParamStore, clock: GlobalClock,
                  stats: ActorStats) -> None:
    """eps-greedy rollout worker (reference dqn_actor.py:9-192), batched
    over the actor's env vector."""
    import jax

    from pytorch_distributed_tpu.models.policies import (
        apex_epsilons, build_epsilon_greedy_act,
    )

    h = _ActorHarness(opt, spec, process_ind, memory, param_store, clock,
                      stats)
    act = build_epsilon_greedy_act(h.model.apply)
    eps = apex_epsilons(process_ind, opt.num_actors, h.num_envs,
                        h.ap.eps, h.ap.eps_alpha)
    key = pin_to_cpu(process_key(opt.seed, "actor", process_ind))

    h.start()
    while not clock.done(h.ap.steps):
        with h.timer.phase("act"):
            key, sub = jax.random.split(key)
            a, q_sel, q_max = act(h.params, h._obs, sub, eps)
            actions = np.asarray(a)
        with h.timer.phase("env"):
            next_obs, rewards, terminals, infos = h.env.step(actions)
        with h.timer.phase("advance"):
            h.advance(actions, next_obs, rewards, terminals, infos,
                      q_sel=np.asarray(q_sel), q_max=np.asarray(q_max))
    h.shutdown()


def run_ddpg_actor(opt: Options, spec: EnvSpec, process_ind: int,
                   memory: Any, param_store: ParamStore, clock: GlobalClock,
                   stats: ActorStats) -> None:
    """OU-noise rollout worker (reference ddpg_actor.py:9-172): same
    skeleton with one OrnsteinUhlenbeckProcess state per env (theta/sigma
    from AgentParams, anneal over memory_size*100 steps — reference
    ddpg_actor.py:34-35)."""
    from pytorch_distributed_tpu.models.policies import build_ddpg_act

    class _DdpgHarness(_ActorHarness):
        ou: OrnsteinUhlenbeckProcess  # set right after construction

        def on_env_reset(self, j: int) -> None:
            # fresh noise path per episode, per env
            self.ou.x_prev.reshape(self.num_envs, -1)[j] = self.ou.x0

    h = _DdpgHarness(opt, spec, process_ind, memory, param_store, clock,
                     stats)
    act = build_ddpg_act(lambda p, o: h.model.apply(
        p, o, method=h.model.forward_actor))
    h.ou = ou = OrnsteinUhlenbeckProcess(
        size=h.num_envs * spec.action_dim,
        theta=h.ap.ou_theta,
        mu=h.ap.ou_mu,
        sigma=h.ap.ou_sigma,
        n_steps_annealing=opt.memory_params.memory_size * 100,
        seed=process_seed(opt.seed, "actor", process_ind) + 17,
    )

    h.start()
    while not clock.done(h.ap.steps):
        a = np.asarray(act(h.params, h._obs))
        noise = ou.sample().reshape(h.num_envs, spec.action_dim)
        actions = np.clip(a + noise, -1.0, 1.0).astype(np.float32)
        next_obs, rewards, terminals, infos = h.env.step(actions)
        h.advance(actions, next_obs, rewards, terminals, infos)
    h.shutdown()
