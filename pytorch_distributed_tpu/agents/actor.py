"""Actor processes: asynchronous experience collection.

Re-design of reference core/single_processes/dqn_actor.py and
ddpg_actor.py.  Same topology — rollout workers with a full local model
replica, diversified by the Ape-X exploration schedule and per-process
seeds — with three structural upgrades:

- the reference's implicit shared-CUDA weight pulls become versioned
  ``ParamStore`` fetches on the ``actor_sync_freq`` cadence (reference
  dqn_actor.py:176-178) — prefetched off the hot path by a
  ``ParamPrefetcher`` thread so a version swap never stalls a tick — and
  its inline deque bookkeeping becomes the unit-tested ``NStepAssembler``;
- every actor is **vectorized**: it steps ``num_envs_per_actor`` envs with
  ONE jitted batched forward per tick (envs/vector.py) — the reference
  reserves this knob but asserts it to 1 (reference utils/options.py:32);
- the hot loop is **software-pipelined** (ISSUE 4 tentpole): the jitted
  ``act`` for tick k+1 is dispatched asynchronously (JAX async dispatch)
  right after tick k's env step, so the device forward overlaps the
  host's feed/advance work, and the action sync happens at the last
  moment as ONE packed device→host copy.  The per-tick host work the
  serial loop carried — key splits, three separate device reads — is
  fused into the jitted step (models/policies.build_packed_act: the PRNG
  key stays on-device, a tick counter is folded in instead of a
  host-side split chain).

Three interchangeable backends (``env_params.actor_backend``), all
bit-identical action/transition streams under a fixed seed because
per-tick randomness is a pure function of (actor, tick, env row):

- ``inline``   — the serial schedule: dispatch, sync, step, feed.  The
  fallback and the determinism reference.
- ``pipelined`` — the two-stage overlapped schedule above (default).
- ``batched``  — SEED-style: no local model at all; obs go to the shared
  ``InferenceServer`` in the accelerator-owning process
  (agents/inference.py) and the wide forward runs there.  Requires the
  co-located server; downgrades to ``pipelined`` with a warning when
  none is wired in (e.g. remote DCN actor hosts).

A fourth backend, ``device`` (ISSUE 7), replaces the per-tick loop with
the fused on-device rollout below; and a fifth, ``anakin`` (ISSUE 12),
removes the actor process entirely — the env fleet lives in the learner
process and agents/anakin.py drives the same fused rollout against the
learner's own replay ring, so no actor worker ever spawns.

Cadences mirror the reference: stats pushed every ``actor_freq`` env steps
(reference dqn_actor.py:180-192), global actor-step counter advanced per
env step (reference :166-167), loop until the global learner clock reaches
``steps`` (reference :62).  The weight-sync cadence is checked at ONE
defined point per tick (after the env step, before the next dispatch) so
the inline and pipelined schedules see identical staleness.

Exploration diversity follows Ape-X across the whole fleet: env ``j`` of
actor ``i`` takes exploration slot ``i*N + j`` of ``num_actors*N``
(reference dqn_actor.py:33-36 has one slot per actor).
"""

from __future__ import annotations

import time
from typing import Any, List, Optional

import numpy as np

from pytorch_distributed_tpu.config import Options
from pytorch_distributed_tpu.factory import (
    EnvSpec, build_device_env, build_env_vector, build_model,
    init_params, resolve_actor_backend,
)
from pytorch_distributed_tpu.agents.clocks import ActorStats, GlobalClock
from pytorch_distributed_tpu.agents.param_store import (
    ParamPrefetcher, ParamStore, make_flattener,
)
from pytorch_distributed_tpu.ops.nstep import NStepAssembler
from pytorch_distributed_tpu.utils.experience import make_prov
from pytorch_distributed_tpu.utils.random_process import (
    OrnsteinUhlenbeckProcess,
)
from pytorch_distributed_tpu.utils.helpers import (
    pin_to_cpu, unravel_on_cpu,
)
from pytorch_distributed_tpu.utils.rngs import process_key, process_seed


class _ActorHarness:
    """Shared plumbing for both actor families: vector env + model/param
    setup, per-env n-step feeds, stat accumulation, sync cadence."""

    def __init__(self, opt: Options, spec: EnvSpec, process_ind: int,
                 memory: Any, param_store: ParamStore, clock: GlobalClock,
                 stats: ActorStats, backend: str = "pipelined"):
        self.opt = opt
        self.ap = opt.agent_params
        self.spec = spec
        self.process_ind = process_ind
        self.memory = memory
        self.param_store = param_store
        self.clock = clock
        self.stats = stats
        self.backend = backend

        self.num_envs = max(1, opt.env_params.num_envs_per_actor)
        if backend == "device":
            # Sebulba actor (ISSUE 7): the env fleet is a pure-JAX
            # program advanced inside the fused rollout dispatch — no
            # host env objects exist in this process at all
            self.env = None
            self.device_env = build_device_env(opt, process_ind,
                                               self.num_envs)
        else:
            self.env = build_env_vector(opt, process_ind, self.num_envs)
            self.env.train()
        self._prefetch: Optional[ParamPrefetcher] = None
        if backend == "batched":
            # SEED-style actor: inference lives with the accelerator, so
            # this process holds NO model replica — no init, no
            # flattener, no per-cadence fetch/unravel (the serial loop's
            # single biggest off-tick cost).  The initial wait stays: it
            # is the learner-alive barrier every worker starts behind.
            self.model = None
            self.unravel = None
            self.params = None
            _flat, self.version = param_store.wait(0, timeout=300.0,
                                                   stop=clock.stop)
        else:
            self.model = build_model(opt, spec)
            params0 = init_params(opt, spec, self.model, seed=process_seed(
                opt.seed, "actor", process_ind))
            _, self.unravel = make_flattener(params0)
            # block until the learner publishes the initial weights — the
            # explicit version of the reference's pre-spawn hard sync
            # (reference dqn_actor.py:26-30).  Generous timeout: the first
            # publication sits behind the learner process's remote XLA
            # compiles, which can take minutes on a tunnelled chip; a dead
            # learner is caught by the stop event, not this timeout.
            flat, self.version = param_store.wait(0, timeout=300.0,
                                                  stop=clock.stop)
            # rollout inference is pinned to the host CPU: the learner owns
            # the accelerator; batch-1/small-batch forwards must not
            # round-trip a (possibly tunnelled) chip (helpers.pin_to_cpu)
            self.params = unravel_on_cpu(self.unravel, flat)
            # weight refresh happens off the hot path from here on: the
            # prefetcher thread does the fetch+unravel, the tick-side
            # swap is a reference exchange (ParamPrefetcher docstring)
            self._prefetch = ParamPrefetcher(
                param_store,
                lambda f: unravel_on_cpu(self.unravel, f),
                start_version=self.version)
        if hasattr(memory, "set_stop"):
            # stop-aware feeding: a flush blocked on a full queue after
            # the learner stopped draining must abort, not deadlock the
            # teardown join
            memory.set_stop(clock.stop)
        if hasattr(memory, "configure_flow"):
            # ISSUE-11 overload policy: shed-vs-block on the local
            # spawn-queue feeder, selected from the run's FlowParams
            # (env overrides land through flow.resolve_flow as usual)
            memory.configure_flow(opt.flow_params)

        # data-plane provenance (ISSUE 8): every transition this actor
        # emits carries (actor_id, env_slot, param_version, birth_step)
        # minted at action time.  ``_feed_version`` snapshots the version
        # that actually SELECTED this tick's actions — tick_sync captures
        # it BEFORE running the swap cadence, so the swap tick's rows
        # still carry the acting version; ``_birth_step`` is the global
        # learner step the actor observed (sample age is then a
        # learner-step subtraction on the learner side, no clock math).
        self._feed_version = getattr(self, "version", 0)
        self._birth_step = int(clock.learner_step.value)

        N = self.num_envs
        self.assemblers: List[NStepAssembler] = [
            NStepAssembler(self.ap.nstep, self.ap.gamma) for _ in range(N)]
        self.episode_steps = np.zeros(N, dtype=np.int64)
        self.episode_reward = np.zeros(N, dtype=np.float64)

        # Actor-computed initial PER priorities (the plumbing the reference
        # anticipated but never finished, reference dqn_actor.py:113-115):
        # per env, q_sel of each acted step FIFO-aligned with the
        # assembler's FIFO emissions, plus a one-tick holding pen for
        # steady-state emissions whose bootstrap state's q_max only becomes
        # known at the NEXT tick's batched forward.
        from collections import deque

        self.per_priorities = (opt.memory_params.enable_per
                               and opt.agent_type == "dqn")
        self._q_hist = [deque() for _ in range(N)]
        self._q_pending: List[list] = [[] for _ in range(N)]

        # local stat accumulators, flushed every actor_freq env steps
        self._acc = dict.fromkeys(ActorStats.FIELDS, 0.0)
        self.env_steps = 0
        self._next_flush = self.ap.actor_freq
        self._next_sync = self.ap.actor_sync_freq

        from pytorch_distributed_tpu.utils import perf, tracing
        from pytorch_distributed_tpu.utils.faults import FaultInjector
        from pytorch_distributed_tpu.utils.metrics import MetricsWriter
        from pytorch_distributed_tpu.utils.profiling import StepTimer

        # hang-watchdog liveness mark (utils/supervision.ProgressBoard,
        # attached to the clock by the topology) + the actor fault plane
        # (``ACTOR_FAULTS``, one frame per vector tick — ``hang@N``
        # makes this worker stop progressing without exiting, the drill
        # the watchdog must catch).  Test clocks may lack the surface.
        self._bump_progress = getattr(clock, "bump_progress",
                                      lambda label, n=1: None)
        self._progress_label = f"actor-{process_ind}"
        self._faults = FaultInjector.from_env("actor")

        self.timer = StepTimer("actor")
        self._timing_writer = MetricsWriter(
            opt.log_dir, enable_tensorboard=False,
            role=f"actor-{process_ind}", run_id=opt.refs)
        # perf plane (utils/perf.py, TPU_APEX_PERF=1): env-frames/s +
        # memory watermarks on the actor_freq cadence; tags stay
        # "actor/..." (fleet-comparable), rows carry this process's role
        self.perf = perf.get_monitor(f"actor-{process_ind}",
                                     opt.perf_params, prefix="actor")
        self.perf.drain()  # anchor the first rate window at startup
        # distributed-trace origin: every chunk this actor flushes is
        # stamped with a trace id here and records an "enqueue" span (a
        # blocking put IS backpressure); downstream hops — gateway, feed,
        # sample, learn — attach to the same id (utils/tracing.py)
        self.tracer = tracing.get_tracer("actor")
        if hasattr(memory, "set_tracer"):
            memory.set_tracer(self.tracer)

    # -- one vector tick ----------------------------------------------------

    def tick_sync(self) -> None:
        """Once per vector tick, at the ONE schedule-invariant point
        (after the env step, before the next act dispatch): bump the
        global/local step counters and run the weight-sync cadence.  The
        swap itself is non-blocking — the prefetcher already did the
        fetch+unravel on its own thread — and is timed as ``param_swap``
        so any residual stall is visible in traces (ISSUE 4
        satellite)."""
        N = self.num_envs
        self.env_steps += N
        self._feed_version = getattr(self, "version", 0)
        self._birth_step = int(self.clock.learner_step.value)
        self.perf.note_frames(N)  # one int add; no-op when disabled
        self.clock.add_actor_steps(N)  # reference dqn_actor.py:166-167
        self._bump_progress(self._progress_label)  # watchdog liveness
        self._faults.data_frame(())  # ACTOR_FAULTS: hang@N / crash@N
        self._acc["total_nframes"] += N
        if self.env_steps >= self._next_sync:
            self._next_sync += self.ap.actor_sync_freq
            if self._prefetch is not None:
                t0 = time.perf_counter()
                got = self._prefetch.take()
                if got is not None:
                    self.params, self.version = got
                    self.timer.add("param_swap",
                                   time.perf_counter() - t0)

    def advance(self, actions, next_obs, rewards, terminals, infos,
                q_sel=None, q_max=None) -> None:
        """Feed assemblers/memory for one batched env step and run the
        stat-flush cadence.  ``q_sel``/``q_max`` are this tick's per-env Q
        diagnostics from the batched forward (DQN actors); with PER
        enabled they become initial priorities.  In the pipelined
        schedule this host work runs while the NEXT tick's forward is
        already in flight on the device."""
        if self.per_priorities:
            self._resolve_pending(q_max)
        for j in range(self.num_envs):
            true_next = infos[j].get("final_obs", next_obs[j])
            truncated = bool(infos[j].get("truncated", False))
            if self.per_priorities:
                self._q_hist[j].append(float(q_sel[j]))
            transitions = self.assemblers[j].feed(
                self._obs[j], actions[j], float(rewards[j]), true_next,
                bool(terminals[j]), truncated=truncated,
                prov=make_prov(self.process_ind, j, self._feed_version,
                               self._birth_step))
            if self.per_priorities:
                self._feed_with_priorities(j, transitions,
                                           bool(terminals[j]), truncated)
            else:
                for t in transitions:
                    self.memory.feed(t, None)
            self.episode_steps[j] += 1
            self.episode_reward[j] += float(rewards[j])
            if terminals[j]:
                self._record_episode(j, infos[j])
                self.on_env_reset(j)
        self._obs = next_obs
        self._flush_cadence()

    def _record_episode(self, j: int, info: dict) -> None:
        """Fold env slot j's finished episode into the stat accumulators."""
        solved = bool(info.get("solved", self.episode_reward[j] > 0))
        self._acc["nepisodes"] += 1
        self._acc["nepisodes_solved"] += float(solved)
        self._acc["total_steps"] += float(self.episode_steps[j])
        self._acc["total_reward"] += float(self.episode_reward[j])
        self.episode_steps[j] = 0
        self.episode_reward[j] = 0.0

    def _flush_cadence(self) -> None:
        """Stat-flush cadence (reference dqn_actor.py:180-192); the
        weight-sync cadence lives in ``tick_sync``."""
        if self.env_steps >= self._next_flush:
            self._next_flush += self.ap.actor_freq
            self.flush_stats()
            step = self.clock.learner_step.value
            self._timing_writer.scalars(self.timer.drain(), step=step)
            if self.perf.enabled:
                self._timing_writer.scalars(self.perf.drain(step=step),
                                            step=step)
            self.tracer.flush_to(self._timing_writer, step=step)
            if hasattr(self.memory, "flush"):
                self.memory.flush()  # queue feeders drain on the cadence

    # -- actor-side TD-error priorities (PER) -------------------------------

    def _resolve_pending(self, q_max) -> None:
        """Steady-state emissions held from the previous tick bootstrap
        from the state the actor is looking at NOW — its q_max just arrived
        with this tick's forward.  priority = |R + gamma_m * maxQ(s_end) -
        q_sel(s_t)|, the n-step TD estimate under the actor's weights."""
        for j in range(self.num_envs):
            if not self._q_pending[j]:
                continue
            for t, q_t in self._q_pending[j]:
                pr = abs(float(t.reward)
                         + float(t.gamma_n) * float(q_max[j]) - q_t)
                self.memory.feed(t, pr)
            self._q_pending[j] = []

    def _feed_with_priorities(self, j: int, transitions,
                              terminal: bool, truncated: bool) -> None:
        if terminal or truncated:
            # episode boundary: every window closed this tick.  True
            # terminals have a zero bootstrap so the TD estimate needs no
            # future q; truncated tails would need q(final_obs), which was
            # never computed — they take the standard new-sample max
            # priority (None).
            for t in transitions:
                q_t = self._q_hist[j].popleft()
                if truncated:
                    self.memory.feed(t, None)
                else:
                    self.memory.feed(t, abs(float(t.reward) - q_t))
            self._q_hist[j].clear()  # next episode starts a fresh history
        else:
            for t in transitions:  # bootstrap q arrives next tick
                self._q_pending[j].append((t, self._q_hist[j].popleft()))

    def start(self) -> None:
        self._obs = self.env.reset()

    def on_env_reset(self, j: int) -> None:
        """Hook for per-env exploration state (DDPG OU paths)."""

    def flush_stats(self) -> None:
        if any(self._acc.values()):
            self.stats.add(**self._acc)
            self._acc = dict.fromkeys(ActorStats.FIELDS, 0.0)

    def shutdown(self) -> None:
        if self._prefetch is not None:
            self._prefetch.close()
        # Best-effort final drain: over DCN a terminally disconnected
        # transport raises from these feeds/flushes (parallel/dcn.py
        # DcnDisconnected), and a teardown crash here would mask WHY the
        # loop ended — the runner's exit code must come from the
        # stop-vs-disconnected split (fleet._remote_actor_main), not
        # from a flush traceback.  Local queue transports never raise
        # these, so nothing is hidden on the single-host path.
        try:
            for j in range(self.num_envs):  # unresolved holds: max priority
                for t, _q in self._q_pending[j]:
                    self.memory.feed(t, None)
                self._q_pending[j] = []
            self.flush_stats()
            if hasattr(self.memory, "flush"):
                self.memory.flush()
        except (ConnectionError, OSError):
            pass
        from pytorch_distributed_tpu.memory.feeder import QueueFeeder

        if isinstance(self.memory, QueueFeeder):
            self.memory.close()
        if self.perf.enabled:
            # final partial window: bounded runs still export a rate
            self._timing_writer.scalars(
                self.perf.drain(step=self.clock.learner_step.value),
                step=self.clock.learner_step.value)
        self.tracer.flush_to(self._timing_writer,
                             step=self.clock.learner_step.value)
        self._timing_writer.close()


# ---------------------------------------------------------------------------
# Act engines: submit/collect pairs the loop driver schedules.
#
# ``submit(obs, tick, reset_mask)`` dispatches the tick's forward and
# returns an opaque handle WITHOUT blocking on the result (JAX async
# dispatch locally; a queue send to the shared server in batched mode).
# ``collect(handle)`` syncs the result into numpy at the last moment and
# returns ``(actions, advance_kwargs)``.  One engine instance is scheduled
# by both the inline and the pipelined loops, so the two backends can
# never drift numerically.
# ---------------------------------------------------------------------------


def _unpack_dqn(packed: np.ndarray):
    """(3, B) packed (action, q_sel, q_max) -> advance arguments."""
    return (packed[0].astype(np.int64),
            dict(q_sel=packed[1], q_max=packed[2]))


class _LocalDqnEngine:
    """Fused eps-greedy forward on this process's host CPU."""

    def __init__(self, h: _ActorHarness, base_key, eps):
        import jax.numpy as jnp

        from pytorch_distributed_tpu.models.policies import build_packed_act

        self._h = h
        self._act = build_packed_act(h.model.apply)
        self._key = pin_to_cpu(base_key)
        self._eps = pin_to_cpu(jnp.asarray(eps, jnp.float32))

    def submit(self, obs, tick, reset_mask):
        out = self._act(self._h.params, obs, self._key, tick, self._eps)
        out.copy_to_host_async()  # D2H overlaps the host work too
        return out

    def collect(self, pending):
        return _unpack_dqn(np.asarray(pending))

    def jit_cache_size(self) -> Optional[int]:
        return self._act._cache_size()

    def close(self) -> None:
        pass


def _ou_explore(h: _ActorHarness, a: np.ndarray) -> np.ndarray:
    """Add the harness's OU exploration noise to a deterministic policy
    output and clip to the action box — ONE implementation shared by the
    local and batched DDPG engines, because both schedules' noise
    streams must stay bit-identical (the tests' oracle) and a divergence
    here would desync them silently."""
    noise = h.ou.sample().reshape(h.num_envs, h.spec.action_dim)
    return np.clip(a + noise, -1.0, 1.0).astype(np.float32)


class _LocalDdpgEngine:
    """Deterministic policy forward; OU noise stays host-side at sync
    time so the noise stream is schedule-invariant."""

    def __init__(self, h: _ActorHarness):
        from pytorch_distributed_tpu.models.policies import build_ddpg_act

        self._h = h
        self._act = build_ddpg_act(lambda p, o: h.model.apply(
            p, o, method=h.model.forward_actor))

    def submit(self, obs, tick, reset_mask):
        out = self._act(self._h.params, obs)
        out.copy_to_host_async()
        return out

    def collect(self, pending):
        return _ou_explore(self._h, np.asarray(pending)), {}

    def jit_cache_size(self) -> Optional[int]:
        return self._act._cache_size()

    def close(self) -> None:
        pass


class _BatchedDqnEngine:
    """Submit obs to the shared InferenceServer (agents/inference.py)."""

    def __init__(self, client, base_key, eps):
        self._client = client
        client.begin_session(base_key=np.asarray(base_key),
                             eps=np.asarray(eps, np.float32))

    def submit(self, obs, tick, reset_mask):
        return self._client.submit(obs, tick)

    def collect(self, pending):
        return _unpack_dqn(self._client.collect(pending))

    def jit_cache_size(self) -> Optional[int]:
        return None  # the jit lives server-side

    def close(self) -> None:
        pass


class _BatchedDdpgEngine:
    def __init__(self, h: _ActorHarness, client):
        self._h = h
        self._client = client
        client.begin_session()

    def submit(self, obs, tick, reset_mask):
        return self._client.submit(obs, tick)

    def collect(self, pending):
        return _ou_explore(self._h, self._client.collect(pending)), {}

    def jit_cache_size(self) -> Optional[int]:
        return None

    def close(self) -> None:
        pass


# ---------------------------------------------------------------------------
# The loop driver: one schedule for every family and backend.
# ---------------------------------------------------------------------------


def _drive_actor_loop(h: _ActorHarness, engine, clock: GlobalClock,
                      pipelined: bool) -> _ActorHarness:
    """Run the actor loop to the global clock's termination.

    Serial (``pipelined=False``)::

        act(k) . sync . env(k) . tick_sync . feed(k)

    Pipelined (``pipelined=True``) — the ISSUE 4 two-stage software
    pipeline; act(k+1) is IN FLIGHT on the device while the host feeds
    tick k::

        sync(k) . env(k) . tick_sync . dispatch act(k+1) . feed(k)

    Both schedules drive the same engine in the same per-tick order
    (submit once, collect once, tick_sync between env step and next
    dispatch), so their action/transition streams are bit-identical
    under a fixed seed.  Timer phases: the serial loop books ``act``;
    the pipelined loop books ``dispatch`` (issue cost), ``sync``
    (blocked-on-device time — the part overlap is hiding) and an ``act``
    aggregate of the two so dashboards compare across schedules.
    """
    timer = h.timer
    h.engine = engine  # introspection: bench/tests read jit_cache_size
    # retrace detector: the fused act program must never recompile
    # after warmup (batched engines return None — the jit lives
    # server-side and the server registers its own)
    h.perf.register_jit("act", engine.jit_cache_size)
    h.start()
    tick = 0
    reset_mask = np.zeros(h.num_envs, dtype=bool)
    pending = None
    if pipelined:
        t0 = time.perf_counter()
        pending = engine.submit(h._obs, 0, reset_mask)
        timer.add("dispatch", time.perf_counter() - t0)
    t_sync = 0.0
    while not clock.done(h.ap.steps):
        if pipelined:
            t0 = time.perf_counter()
            actions, extras = engine.collect(pending)
            t_sync = time.perf_counter() - t0
            timer.add("sync", t_sync)
        else:
            t0 = time.perf_counter()
            pending = engine.submit(h._obs, tick, reset_mask)
            actions, extras = engine.collect(pending)
            timer.add("act", time.perf_counter() - t0)
        with timer.phase("env"):
            next_obs, rewards, terminals, infos = h.env.step(actions)
        h.tick_sync()
        tick += 1
        if pipelined:
            t0 = time.perf_counter()
            pending = engine.submit(next_obs, tick, terminals)
            t_disp = time.perf_counter() - t0
            timer.add("dispatch", t_disp)
            timer.add("act", t_sync + t_disp)
        else:
            reset_mask = terminals
        with timer.phase("advance"):
            h.advance(actions, next_obs, rewards, terminals, infos,
                      **extras)
    h.shutdown()
    engine.close()
    return h


def fold_rollout_episode_stats(step_reward, step_terminal, episode_reward,
                               episode_steps, acc: dict) -> None:
    """Fold a fused dispatch's ``(K, N)`` per-tick env stats into the
    harness-style per-env episode accumulators and the actor stat dict
    (``ActorStats.FIELDS`` keys) — ONE implementation shared by the
    split-process device actor loop and the co-located Anakin driver
    (agents/anakin.py), so the two backends' episode curves can never
    drift.  ``episode_reward``/``episode_steps`` are mutated in place;
    an episode counts as solved when its return is positive (the
    ``_record_episode`` default for envs that report no ``solved``)."""
    K = np.asarray(step_reward).shape[0]
    for k in range(K):
        episode_reward += np.asarray(step_reward[k], np.float64)
        episode_steps += 1
        for j in np.nonzero(np.asarray(step_terminal[k]))[0]:
            j = int(j)
            acc["nepisodes"] += 1
            acc["nepisodes_solved"] += float(episode_reward[j] > 0)
            acc["total_steps"] += float(episode_steps[j])
            acc["total_reward"] += float(episode_reward[j])
            episode_steps[j] = 0
            episode_reward[j] = 0.0


def _drive_device_actor_loop(h: _ActorHarness, clock: GlobalClock,
                             base_key, eps) -> _ActorHarness:
    """The Sebulba actor loop (ISSUE 7): no per-tick host work at all.

    One fused, donated XLA program advances all N envs x K ticks —
    policy forward, row-keyed eps-greedy, env physics/render, n-step
    assembly — and the host's whole job per dispatch is ONE packed
    device->host copy of the emitted transition chunk plus the feed
    into the replay plane.  Action streams are bit-identical to the
    inline loop over the same device env (the tick_keys contract), and
    the emitted transition stream is bit-identical to what the host
    ``NStepAssembler`` would produce from those ticks
    (tests/test_device_env.py pins both).

    Cadences quantize to the dispatch: the weight-sync check, stat
    flush, watchdog liveness marks and fault frames all run once per
    K-tick dispatch instead of per tick.  Timer phases: ``rollout``
    (dispatch issue), ``emit`` (blocked on the program + the chunk
    D2H), ``advance`` (replay feed + episode accounting),
    ``param_swap`` (the prefetched weight swap)."""
    import jax
    import jax.numpy as jnp

    from pytorch_distributed_tpu.models.policies import (
        build_fused_rollout, init_rollout_carry, rollout_priorities,
    )
    from pytorch_distributed_tpu.utils.experience import Transition

    ap = h.ap
    N = h.num_envs
    K = max(1, int(getattr(h.opt.env_params, "device_rollout_ticks", 8)))
    env = h.device_env
    rollout = build_fused_rollout(h.model.apply, env, nstep=ap.nstep,
                                  gamma=ap.gamma, rollout_ticks=K,
                                  emit="chunk")
    h.rollout_jit = rollout  # introspection: tests/bench read the cache
    # perf plane: the fused rollout is a registered hot program (a
    # post-warmup recompile = a shape/dtype leak paying compile latency
    # on the hot path) and its per-frame FLOPs feed the actor-side MFU
    # on the live plane (utils/perf.py flops_per_frame)
    h.perf.register_jit("device_rollout", rollout._cache_size)
    carry = init_rollout_carry(env, ap.nstep)
    eps_dev = jnp.asarray(eps, jnp.float32)
    key_dev = jnp.asarray(base_key)
    if h.perf.enabled:
        # XLA's cost analysis counts the K-tick scan body ONCE
        # (verified: totals are K-invariant, utils/perf.
        # flops_of_compiled docstring), so the per-call figure is one
        # tick of all N envs — divide by N, not K*N
        h.perf.capture_frame_flops(
            lambda: rollout.lower(h.params, carry, key_dev,
                                  jnp.int32(0), eps_dev),
            frames_per_call=N)
    timer = h.timer
    # tick0 stays DEVICE-resident and advances on device (+K is a weak
    # python constant): the audited dispatch must stage zero host
    # arrays, so the transfer audit (TPU_APEX_PERF_TRANSFER_AUDIT=1)
    # proves the hot path transfer-free instead of flagging its own
    # tick counter
    tick0 = jnp.int32(0)
    audit = h.perf.audit
    while not clock.done(ap.steps):
        t0 = time.perf_counter()
        if audit is not None:
            carry, chunk = audit.run(rollout, h.params, carry, key_dev,
                                     tick0, eps_dev)
        else:
            carry, chunk = rollout(h.params, carry, key_dev, tick0,
                                   eps_dev)
        tick0 = tick0 + K
        timer.add("rollout", time.perf_counter() - t0)
        t0 = time.perf_counter()
        ch = jax.device_get(chunk)  # the dispatch's ONE device->host copy
        timer.add("emit", time.perf_counter() - t0)
        # ---- per-dispatch cadence (the vector ticks' tick_sync) ----
        # provenance stamps quantize to the dispatch: the chunk's rows
        # carry the version that acted THIS dispatch (captured before
        # the swap cadence below) and the learner step observed at
        # fetch — windows opened in the previous dispatch inherit the
        # current stamp, a documented <=K-tick quantization
        feed_version = h.version
        birth_step = int(h.clock.learner_step.value)
        h.env_steps += K * N
        h.perf.note_frames(K * N)
        h.clock.add_actor_steps(K * N)
        # one liveness mark covering the dispatch's K vector ticks:
        # mark counts stay in tick units, so the fleet STATUS per-actor
        # frames/s (marks x num_envs / dt) is backend-invariant
        h._bump_progress(h._progress_label, n=K)
        h._faults.data_frame(())
        h._acc["total_nframes"] += K * N
        if h.env_steps >= h._next_sync:
            h._next_sync += ap.actor_sync_freq
            if h._prefetch is not None:
                t0 = time.perf_counter()
                got = h._prefetch.take()
                if got is not None:
                    h.params, h.version = got
                    timer.add("param_swap", time.perf_counter() - t0)
        with timer.phase("advance"):
            valid = np.asarray(ch.valid)
            prio = None
            if h.per_priorities:
                flat = {f: np.asarray(getattr(ch, f)).reshape(
                    (K * N,) + np.asarray(getattr(ch, f)).shape[2:])
                    for f in ("reward", "gamma_n", "terminal1",
                              "q_boot", "q_sel", "prio_ok")}
                prio = rollout_priorities(flat, True).reshape(K, N)
            for k in range(K):
                for j in range(N):
                    if not valid[k, j]:
                        continue
                    t = Transition(
                        state0=ch.state0[k, j], action=ch.action[k, j],
                        reward=ch.reward[k, j],
                        gamma_n=ch.gamma_n[k, j],
                        state1=ch.state1[k, j],
                        terminal1=ch.terminal1[k, j],
                        prov=make_prov(h.process_ind, j, feed_version,
                                       birth_step))
                    h.memory.feed(t, prio[k][j] if prio is not None
                                  else None)
            # episode accounting off the per-tick env stats (shared
            # with the Anakin driver: fold_rollout_episode_stats)
            fold_rollout_episode_stats(ch.step_reward, ch.step_terminal,
                                       h.episode_reward, h.episode_steps,
                                       h._acc)
            h._flush_cadence()
    h.shutdown()
    return h


def run_dqn_actor(opt: Options, spec: EnvSpec, process_ind: int, memory: Any,
                  param_store: ParamStore, clock: GlobalClock,
                  stats: ActorStats, inference: Any = None):
    """eps-greedy rollout worker (reference dqn_actor.py:9-192), batched
    over the actor's env vector and scheduled per ``actor_backend``."""
    from pytorch_distributed_tpu.models.policies import apex_epsilons

    backend = resolve_actor_backend(opt, inference)
    if backend == "anakin":
        # an actor PROCESS can never be the co-located loop (that loop
        # is the learner); remote hosts in a hybrid anakin fleet run
        # the split-process device schedule against the same env fleet
        backend = "device"
    h = _ActorHarness(opt, spec, process_ind, memory, param_store, clock,
                      stats, backend=backend)
    eps = apex_epsilons(process_ind, opt.num_actors, h.num_envs,
                        h.ap.eps, h.ap.eps_alpha)
    base_key = process_key(opt.seed, "actor", process_ind)
    if backend == "device":
        return _drive_device_actor_loop(h, clock, base_key, eps)
    if backend == "batched":
        engine = _BatchedDqnEngine(inference, base_key, eps)
    else:
        engine = _LocalDqnEngine(h, base_key, eps)
    return _drive_actor_loop(h, engine, clock,
                             pipelined=(backend != "inline"))


def run_ddpg_actor(opt: Options, spec: EnvSpec, process_ind: int,
                   memory: Any, param_store: ParamStore, clock: GlobalClock,
                   stats: ActorStats, inference: Any = None):
    """OU-noise rollout worker (reference ddpg_actor.py:9-172): same
    skeleton with one OrnsteinUhlenbeckProcess state per env (theta/sigma
    from AgentParams, anneal over memory_size*100 steps — reference
    ddpg_actor.py:34-35).  Rides the shared loop driver, so — unlike the
    original loop, which skipped them (ISSUE 4 satellite) — its
    act/env/advance tick breakdown reaches the metrics stream exactly
    like the DQN family's."""
    backend = resolve_actor_backend(opt, inference)

    class _DdpgHarness(_ActorHarness):
        ou: OrnsteinUhlenbeckProcess  # set right after construction

        def on_env_reset(self, j: int) -> None:
            # fresh noise path per episode, per env
            self.ou.x_prev.reshape(self.num_envs, -1)[j] = self.ou.x0

    h = _DdpgHarness(opt, spec, process_ind, memory, param_store, clock,
                     stats, backend=backend)
    h.ou = OrnsteinUhlenbeckProcess(
        size=h.num_envs * spec.action_dim,
        theta=h.ap.ou_theta,
        mu=h.ap.ou_mu,
        sigma=h.ap.ou_sigma,
        n_steps_annealing=opt.memory_params.memory_size * 100,
        seed=process_seed(opt.seed, "actor", process_ind) + 17,
    )
    if backend == "batched":
        engine = _BatchedDdpgEngine(h, inference)
    else:
        engine = _LocalDdpgEngine(h)
    return _drive_actor_loop(h, engine, clock,
                             pipelined=(backend != "inline"))


# ---------------------------------------------------------------------------
# In-process bounded runs (tests + bench.py actor-pipeline section)
# ---------------------------------------------------------------------------


class _RecordingSink:
    """Memory stand-in that records every fed item in arrival order."""

    def __init__(self):
        self.items: List[tuple] = []

    def feed(self, item, priority=None) -> None:
        self.items.append((item, priority))


def bounded_actor_run(opt: Options, ticks: int, spec: EnvSpec = None,
                      process_ind: int = 0, inference: Any = None,
                      param_seed: int = 0) -> dict:
    """Run ONE actor loop in this process for exactly ``ticks`` vector
    ticks against a recording sink and a single fixed parameter snapshot.

    The harness behind the determinism tests (pipelined/batched streams
    must be bit-identical to inline, tests/test_actor_pipeline.py) and
    the bench's actor-pipeline section: no learner, no spawn — the param
    store is pre-published once from ``init_params(seed=param_seed)``, so
    two runs over the same opt see identical weights.  Returns
    ``{"stream": [(item, priority), ...], "timer_ms": {...},
    "harness": h}`` — the timer dict is the StepTimer drain (per-phase
    mean/max/calls in ms) accumulated over the run, provided
    ``actor_freq`` was set larger than ``ticks * num_envs`` (a mid-run
    flush would drain it early).
    """
    import threading
    import types

    from pytorch_distributed_tpu.factory import get_worker, probe_env

    spec = spec if spec is not None else probe_env(opt)
    model = build_model(opt, spec)
    flat0, _ = make_flattener(init_params(opt, spec, model,
                                          seed=param_seed))
    store = ParamStore(flat0.size)
    store.publish(flat0)

    class _BoundedClock:
        """Quacks like GlobalClock; ends the loop after ``ticks``
        iterations instead of at a learner-step horizon."""

        def __init__(self, ticks_left: int):
            self._left = ticks_left
            self.stop = threading.Event()
            self.learner_step = types.SimpleNamespace(value=0)

        def done(self, steps: int) -> bool:
            if self._left <= 0:
                return True
            self._left -= 1
            return False

        def add_actor_steps(self, n: int = 1) -> int:
            return n

    sink = _RecordingSink()
    clock = _BoundedClock(ticks)
    h = get_worker("actor", opt.agent_type)(
        opt, spec, process_ind, sink, store, clock, ActorStats(),
        inference)
    return {"stream": sink.items, "timer_ms": h.timer.drain(),
            "harness": h}
