"""Actor processes: asynchronous experience collection.

Re-design of reference core/single_processes/dqn_actor.py and
ddpg_actor.py.  Same topology — N independent rollout workers, each with a
full local model replica and its own env, diversified by the Ape-X
exploration schedule and per-process seed — with the reference's implicit
shared-CUDA weight pulls replaced by versioned ``ParamStore`` fetches and
its inline deque bookkeeping replaced by the unit-tested ``NStepAssembler``.

Cadences mirror the reference: weight re-sync every ``actor_sync_freq``
local steps (reference dqn_actor.py:176-178), stats pushed every
``actor_freq`` steps (reference :180-192), one global actor-step counter
increment per env step under its lock (reference :166-167), loop until the
global learner clock reaches ``steps`` (reference :62).

Inference is a jitted host-side forward (the actor process pins JAX to CPU
via the runtime trampoline), so per-step latency has no device round-trip —
the answer to the reference's latency-bound batch-1 CUDA forward
(SURVEY.md §7 "hard parts").
"""

from __future__ import annotations

from typing import Any

import numpy as np

from pytorch_distributed_tpu.config import Options
from pytorch_distributed_tpu.factory import (
    EnvSpec, build_env, build_model, ddpg_applies, init_params,
)
from pytorch_distributed_tpu.agents.clocks import ActorStats, GlobalClock
from pytorch_distributed_tpu.agents.param_store import (
    ParamStore, make_flattener,
)
from pytorch_distributed_tpu.ops.nstep import NStepAssembler
from pytorch_distributed_tpu.utils.random_process import (
    OrnsteinUhlenbeckProcess,
)
from pytorch_distributed_tpu.utils.rngs import process_key, process_seed


class _ActorHarness:
    """Shared plumbing for both actor families: env/model/params setup,
    n-step feed, stat accumulation, sync cadence."""

    def __init__(self, opt: Options, spec: EnvSpec, process_ind: int,
                 memory: Any, param_store: ParamStore, clock: GlobalClock,
                 stats: ActorStats):
        self.opt = opt
        self.ap = opt.agent_params
        self.spec = spec
        self.process_ind = process_ind
        self.memory = memory
        self.param_store = param_store
        self.clock = clock
        self.stats = stats

        self.env = build_env(opt, process_ind)
        self.env.train()
        self.model = build_model(opt, spec)
        params0 = init_params(opt, spec, self.model, seed=process_seed(
            opt.seed, "actor", process_ind))
        _, self.unravel = make_flattener(params0)
        # block until the learner publishes the initial weights — the
        # explicit version of the reference's pre-spawn hard sync
        # (reference dqn_actor.py:26-30)
        flat, self.version = param_store.wait(0, stop=clock.stop)
        self.params = self.unravel(flat)
        self.assembler = NStepAssembler(self.ap.nstep, self.ap.gamma)

        # local stat accumulators, flushed every actor_freq steps
        self._acc = dict.fromkeys(ActorStats.FIELDS, 0.0)
        self.local_step = 0

    # -- cadence hooks ------------------------------------------------------

    def maybe_sync(self) -> None:
        if self.local_step % self.ap.actor_sync_freq == 0:
            got = self.param_store.fetch(self.version)
            if got is not None:
                flat, self.version = got
                self.params = self.unravel(flat)

    def push_step(self, transitions) -> None:
        for t in transitions:
            self.memory.feed(t, None)
        self.local_step += 1
        self.clock.add_actor_steps(1)
        self._acc["total_nframes"] += 1
        if self.local_step % self.ap.actor_freq == 0:
            self.flush_stats()

    def end_episode(self, episode_steps: int, episode_reward: float,
                    solved: bool) -> None:
        self._acc["nepisodes"] += 1
        self._acc["nepisodes_solved"] += float(solved)
        self._acc["total_steps"] += episode_steps
        self._acc["total_reward"] += episode_reward
        if hasattr(self.memory, "flush"):
            self.memory.flush()  # queue feeders drain at episode ends

    def flush_stats(self) -> None:
        if any(self._acc.values()):
            self.stats.add(**self._acc)
            self._acc = dict.fromkeys(ActorStats.FIELDS, 0.0)

    def shutdown(self) -> None:
        self.flush_stats()
        if hasattr(self.memory, "flush"):
            self.memory.flush()


def run_dqn_actor(opt: Options, spec: EnvSpec, process_ind: int, memory: Any,
                  param_store: ParamStore, clock: GlobalClock,
                  stats: ActorStats) -> None:
    """eps-greedy rollout worker (reference dqn_actor.py:9-192)."""
    import jax

    from pytorch_distributed_tpu.models.policies import (
        apex_epsilon, build_epsilon_greedy_act,
    )

    h = _ActorHarness(opt, spec, process_ind, memory, param_store, clock,
                      stats)
    act = build_epsilon_greedy_act(h.model.apply)
    eps = apex_epsilon(process_ind, opt.num_actors,
                       h.ap.eps, h.ap.eps_alpha)
    key = process_key(opt.seed, "actor", process_ind)

    obs = h.env.reset()
    episode_steps, episode_reward = 0, 0.0
    while not clock.done(h.ap.steps):
        key, sub = jax.random.split(key)
        a, _q_sel, _q_max = act(h.params, obs[None], sub, eps)
        a = int(a[0])
        next_obs, r, terminal, info = h.env.step(a)
        transitions = h.assembler.feed(
            obs, a, r, next_obs, terminal,
            truncated=bool(info.get("truncated", False)))
        h.push_step(transitions)
        episode_steps += 1
        episode_reward += float(r)
        obs = next_obs
        if terminal:
            h.end_episode(episode_steps, episode_reward,
                          solved=bool(info.get("solved",
                                               episode_reward > 0)))
            obs = h.env.reset()
            episode_steps, episode_reward = 0, 0.0
        h.maybe_sync()
    h.shutdown()


def run_ddpg_actor(opt: Options, spec: EnvSpec, process_ind: int,
                   memory: Any, param_store: ParamStore, clock: GlobalClock,
                   stats: ActorStats) -> None:
    """OU-noise rollout worker (reference ddpg_actor.py:9-172): same skeleton
    as the DQN actor with one process-local OrnsteinUhlenbeckProcess
    (theta/sigma from AgentParams, anneal over memory_size*100 steps —
    reference ddpg_actor.py:34-35)."""
    h = _ActorHarness(opt, spec, process_ind, memory, param_store, clock,
                      stats)
    from pytorch_distributed_tpu.models.policies import build_ddpg_act

    act = build_ddpg_act(lambda p, o: h.model.apply(
        p, o, method=h.model.forward_actor))
    ou = OrnsteinUhlenbeckProcess(
        size=spec.action_dim,
        theta=h.ap.ou_theta,
        mu=h.ap.ou_mu,
        sigma=h.ap.ou_sigma,
        n_steps_annealing=opt.memory_params.memory_size * 100,
        seed=process_seed(opt.seed, "actor", process_ind) + 17,
    )

    obs = h.env.reset()
    ou.reset_states()
    episode_steps, episode_reward = 0, 0.0
    while not clock.done(h.ap.steps):
        a = np.asarray(act(h.params, obs[None]))[0]
        a = np.clip(a + ou.sample(), -1.0, 1.0).astype(np.float32)
        next_obs, r, terminal, info = h.env.step(a)
        transitions = h.assembler.feed(
            obs, a, r, next_obs, terminal,
            truncated=bool(info.get("truncated", False)))
        h.push_step(transitions)
        episode_steps += 1
        episode_reward += float(r)
        obs = next_obs
        if terminal:
            h.end_episode(episode_steps, episode_reward,
                          solved=bool(info.get("solved",
                                               episode_reward > 0)))
            obs = h.env.reset()
            ou.reset_states()  # fresh noise path per episode
            episode_steps, episode_reward = 0, 0.0
        h.maybe_sync()
    h.shutdown()
